"""Mesh-sharding subsystem: the 8-device mesh in the production dispatch path.

ROADMAP item 1.  ``tests/test_multichip.py`` proved (since the seed) that
the fused device programs produce bit-identical results when their batch
axis is sharded over a ``jax.sharding.Mesh`` — but nothing in production
ever built that mesh.  This module is the missing layer between the
``ops/batch_axes.py`` contract and the bucketed entry points:

- **mesh construction** — :func:`configure` reads ``LIGHTHOUSE_TPU_MESH``
  (``0`` = disabled, ``N`` = first N devices, ``auto`` = every device) and
  builds a 1-D data-parallel mesh (axis ``"dp"``).  Fewer than 2 usable
  devices disables the mesh transparently: every op falls back to the
  exact single-device path that shipped before this module.
- **mechanical spec derivation** — :class:`ShardedEntry` reads an entry
  point's ``BATCH_AXES`` declaration and derives its ``PartitionSpec``\\ s:
  ``batched_args`` shard their declared batch axis over ``("dp",)``,
  ``replicated_args`` broadcast, and outputs shard or replicate per the
  entry's ``out_batched`` flag (``reduces_over_batch`` programs lower
  their batch-global sums through XLA-inserted ``psum``\\ s and stay in
  ``device_supervisor.NO_SPLIT_OPS``).  No op hand-maintains a spec.
- **the mesh placer** — :meth:`ShardedEntry.place` is the ONE sanctioned
  ``jax.device_put`` site when the mesh is on (the sharding-ready static
  pass flags placements that bypass it): it pads the batch axis up to a
  multiple of the mesh size (jax rejects non-divisible input shardings;
  the pad rows are the same neutral elements bucket padding already uses)
  and uploads every argument under its derived ``NamedSharding``.
- **per-device breakers** — a dispatch failure while the mesh is active is
  charged to a *device* (parsed from the error when the runtime names one,
  else the deterministic suspect — the highest-index survivor).  A device
  whose breaker trips is removed and the mesh **re-shards over the
  survivors**: specs re-derive, the per-topology jit/AOT warmup state is
  invalidated (``device_telemetry.COMPILE_CACHE`` drops the old topology's
  entries), ``device_mesh_size`` / ``device_mesh_reshards_total`` move,
  and the supervisor retries the batch on the shrunk mesh.  Only when the
  mesh is exhausted (fewer than 2 survivors) does the op-level breaker
  resume sole ownership — host fallback remains the terminal state.

Thread discipline: all mutable state sits behind one ``TimeoutLock``;
``generation()`` is the cheap read callers key their caches on.  The
module imports neither jax nor ``ops/`` at import time (the pipeline and
scheduler import it for :func:`scale_target` without pulling a device
runtime); jax loads lazily on :func:`configure`.
"""

from __future__ import annotations

import inspect
import os
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import blackbox, locksmith, metrics
from .logs import get_logger
from .timeout_lock import TimeoutLock

log = get_logger("device_mesh")

#: The one mesh axis: pure data parallelism over the batch axis.
AXIS = "dp"

MESH_ENV = "LIGHTHOUSE_TPU_MESH"

#: Consecutive failures charged to one device before its breaker trips and
#: the mesh re-shards without it.  Deliberately lower than the op breaker's
#: threshold: shrinking the mesh is cheap and reversible-by-restart, while
#: an op trip parks EVERY batch on the slow host path.
DEVICE_FAILURE_THRESHOLD_ENV = "LIGHTHOUSE_TPU_MESH_DEVICE_FAILURES"
DEFAULT_DEVICE_FAILURE_THRESHOLD = 2

#: Runtimes that name the failing chip do it in one of these spellings
#: (``TPU_3``, ``device 5``, ``device_ordinal: 2``, ...).
_DEVICE_ID_RE = re.compile(
    r"(?:TPU|device(?:_ordinal)?)[ _:#]*(\d+)", re.IGNORECASE
)


def _registry() -> dict:
    # Lazy: ops/__init__ documents the package; batch_axes itself is a
    # plain dict literal with no imports, so this cannot cycle back here.
    from .ops.batch_axes import BATCH_AXES

    return BATCH_AXES


class _DeviceBreaker:
    """Per-device failure counter: CLOSED until ``threshold`` consecutive
    charged failures, then OPEN (sticky — a removed device rejoins only via
    an operator reset/restart; auto re-admission would need a re-warm and
    re-proof the failure was transient, which nothing here can see)."""

    __slots__ = ("device_id", "threshold", "failures", "open", "last_reason")

    def __init__(self, device_id: int, threshold: int):
        self.device_id = device_id
        self.threshold = threshold
        self.failures = 0
        self.open = False
        self.last_reason: Optional[str] = None

    def record(self, reason: str) -> bool:
        """Charge one failure; True iff this charge tripped the breaker."""
        self.failures += 1
        self.last_reason = reason
        if not self.open and self.failures >= self.threshold:
            self.open = True
            return True
        return False

    def snapshot(self) -> dict:
        return {
            "device": self.device_id,
            "state": "open" if self.open else "closed",
            "failures": self.failures,
            "threshold": self.threshold,
            "last_reason": self.last_reason,
        }


class MeshState:
    """The process-wide mesh: device roster, breakers, topology generation."""

    def __init__(self) -> None:
        self._lock = TimeoutLock("device_mesh", label="MeshState._lock")
        self._configured = False
        self._devices: List[Any] = []          # live mesh members, id order
        self._mesh = None                      # jax.sharding.Mesh | None
        self._full_size = 0                    # size as originally configured
        self._generation = 0
        self._reshards_total = 0
        self._breakers: Dict[int, _DeviceBreaker] = {}
        self._threshold = DEFAULT_DEVICE_FAILURE_THRESHOLD

    # ---------------------------------------------------------- configure

    def configure(self, spec: Optional[str] = None) -> int:
        """(Re)build the mesh per ``spec`` (default: the env var).  Returns
        the active mesh size (0 = disabled).  Idempotent for a given spec;
        an explicit call always rebuilds from the full device roster."""
        raw = (spec if spec is not None
               else os.environ.get(MESH_ENV, "0")).strip().lower()
        threshold = max(1, int(os.environ.get(
            DEVICE_FAILURE_THRESHOLD_ENV, str(DEFAULT_DEVICE_FAILURE_THRESHOLD))))
        devices: List[Any] = []
        if raw not in ("", "0", "off", "false"):
            import jax

            available = list(jax.devices())
            want = len(available) if raw == "auto" else int(raw)
            devices = available[: max(0, want)]
        if len(devices) < 2:
            devices = []  # single-device: the mesh buys nothing, stay off
        with self._lock:
            self._configured = True
            self._threshold = threshold
            self._devices = devices
            self._full_size = len(devices)
            self._breakers = {
                int(d.id): _DeviceBreaker(int(d.id), threshold) for d in devices
            }
            self._mesh = self._build_mesh(devices)
            self._generation += 1
            size = len(devices)
        metrics.DEVICE_MESH_SIZE.set(size)
        for d in devices:
            metrics.DEVICE_MESH_DEVICE_STATE.set(0, device=str(int(d.id)))
        if size:
            log.info("device mesh enabled", size=size, axis=AXIS,
                     devices=[int(d.id) for d in devices])
        return size

    @staticmethod
    def _build_mesh(devices: Sequence[Any]):
        if len(devices) < 2:
            return None
        import numpy as np
        from jax.sharding import Mesh

        return Mesh(np.array(devices), (AXIS,))

    def _ensure_configured(self) -> None:
        with self._lock:
            configured = self._configured
        if not configured:
            self.configure()

    # ------------------------------------------------------------- reads

    def enabled(self) -> bool:
        self._ensure_configured()
        with self._lock:
            return self._mesh is not None

    def size(self) -> int:
        with self._lock:
            return len(self._devices)

    def full_size(self) -> int:
        with self._lock:
            return self._full_size

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def mesh(self):
        with self._lock:
            return self._mesh

    def pad_rows(self, n: int) -> int:
        """``n`` rounded up to a multiple of the mesh size (jax rejects
        non-divisible input shardings); ``n`` unchanged when disabled."""
        with self._lock:
            m = len(self._devices)
        if m < 2:
            return n
        return -(-n // m) * m

    # ------------------------------------------------- failure accounting

    def suspect_device(self, err: Optional[BaseException]) -> Optional[int]:
        """The device a failure is charged to: the id the error names when
        the runtime names one, else the deterministic suspect — the
        highest-index survivor (degradation order is then reproducible,
        which the 2-run scenario gate requires)."""
        with self._lock:
            if not self._devices:
                return None
            member_ids = {int(d.id) for d in self._devices}
            fallback = int(self._devices[-1].id)
        if err is not None:
            m = _DEVICE_ID_RE.search(str(err))
            if m and int(m.group(1)) in member_ids:
                return int(m.group(1))
        return fallback

    def note_success(self) -> None:
        """A meshed dispatch completed: clear the failure counters of every
        still-CLOSED device breaker.  This is what makes the threshold
        genuinely *consecutive* — without it, unattributable transients
        hours apart would ratchet healthy devices out of the mesh one by
        one (the deterministic suspect is always the highest-index
        survivor).  OPEN breakers stay open: re-admission is
        operator-driven."""
        with self._lock:
            for br in self._breakers.values():
                if not br.open:
                    br.failures = 0

    def note_failure(self, reason: str,
                     device_id: Optional[int] = None,
                     err: Optional[BaseException] = None) -> bool:
        """Charge one dispatch failure to a device; True iff the charge
        tripped that device's breaker and the mesh re-sharded (the caller
        should then retry the batch on the survivors)."""
        if device_id is None:
            device_id = self.suspect_device(err)
        if device_id is None:
            return False
        transitions: List[int] = []
        with self._lock:
            br = self._breakers.get(device_id)
            if br is None or self._mesh is None:
                return False
            tripped = br.record(reason)
            if tripped:
                transitions.append(device_id)
                self._shrink_locked(device_id, reason)
            size = len(self._devices)
            gen = self._generation
        metrics.DEVICE_MESH_DEVICE_FAILURES.inc(device=str(device_id))
        for dev in transitions:
            metrics.DEVICE_MESH_DEVICE_STATE.set(1, device=str(dev))
            metrics.DEVICE_MESH_RESHARDS.inc(reason=reason)
            metrics.DEVICE_MESH_SIZE.set(size)
            log.warning("mesh device breaker tripped; re-sharded",
                        device=dev, reason=reason, survivors=size,
                        generation=gen)
            blackbox.emit("mesh", "reshard", device=dev, reason=reason,
                          survivors=size, generation=gen)
            self._invalidate_topology()
        return bool(transitions)

    def force_trip(self, device_id: int, reason: str = "forced") -> bool:
        """Trip one device's breaker outright (admin/scenario seam: the
        deterministic 'kill a device mid-sync' event)."""
        with self._lock:
            br = self._breakers.get(int(device_id))
            if br is None or self._mesh is None or br.open:
                return False
            br.failures = max(br.failures, br.threshold)
            br.open = True
            br.last_reason = reason
            self._shrink_locked(int(device_id), reason)
            size = len(self._devices)
        metrics.DEVICE_MESH_DEVICE_STATE.set(1, device=str(int(device_id)))
        metrics.DEVICE_MESH_RESHARDS.inc(reason=reason)
        metrics.DEVICE_MESH_SIZE.set(size)
        log.warning("mesh device force-tripped; re-sharded",
                    device=int(device_id), reason=reason, survivors=size)
        blackbox.emit("mesh", "reshard", device=int(device_id), reason=reason,
                      survivors=size, forced=True)
        self._invalidate_topology()
        return True

    def _shrink_locked(self, device_id: int, reason: str) -> None:
        """Remove ``device_id`` and rebuild the mesh over the survivors
        (lock held).  Below 2 survivors the mesh disables entirely — the
        single-device path (and, past it, the op breaker's host fallback)
        is the terminal degradation state."""
        self._devices = [d for d in self._devices if int(d.id) != device_id]
        self._reshards_total += 1
        self._generation += 1
        self._mesh = self._build_mesh(self._devices)
        if self._mesh is None and self._devices:
            log.warning("mesh exhausted; single-device dispatch",
                        survivor=int(self._devices[0].id), reason=reason)

    def _invalidate_topology(self) -> None:
        """The old topology's executables are dead weight: drop its
        compile-mirror entries (so telemetry re-attributes the survivors'
        first dispatches as the compiles they are) — the AOT-warmup
        invalidation half of a reshard.  jax-level caches are keyed by the
        jitted wrapper identity, which :class:`ShardedEntry` rotates via
        the generation."""
        from . import device_telemetry

        device_telemetry.COMPILE_CACHE.invalidate_meshed()

    # ------------------------------------------------------------ surface

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self._mesh is not None,
                "axis": AXIS,
                "size": len(self._devices),
                "full_size": self._full_size,
                "generation": self._generation,
                "reshards_total": self._reshards_total,
                "device_failure_threshold": self._threshold,
                "devices": [int(d.id) for d in self._devices],
                "breakers": [b.snapshot()
                             for _, b in sorted(self._breakers.items())],
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._configured = False
            self._devices = []
            self._mesh = None
            self._full_size = 0
            self._generation += 1
            self._reshards_total = 0
            self._breakers = {}
        metrics.DEVICE_MESH_SIZE.set(0)


STATE = MeshState()


# ------------------------------------------------------------ module facade


def configure(spec: Optional[str] = None) -> int:
    return STATE.configure(spec)


def enabled() -> bool:
    return STATE.enabled()


def size() -> int:
    return STATE.size()


def generation() -> int:
    return STATE.generation()


def pad_rows(n: int) -> int:
    return STATE.pad_rows(n)


def note_success() -> None:
    STATE.note_success()


def note_failure(reason: str, device_id: Optional[int] = None,
                 err: Optional[BaseException] = None) -> bool:
    return STATE.note_failure(reason, device_id=device_id, err=err)


def grow_rows(arr, rows: int, fill):
    """Grow a host array's leading (batch) axis to ``rows`` with ``fill``
    (broadcast into the new rows) — the one shared mesh-divisibility pad
    the ops' placement stages use next to :func:`pad_rows`."""
    import numpy as np

    if arr.shape[0] == rows:
        return arr
    out = np.empty((rows,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    out[arr.shape[0]:] = fill
    return out


def force_trip(device_id: int, reason: str = "forced") -> bool:
    return STATE.force_trip(device_id, reason)


def summary() -> dict:
    """The ``mesh`` section of ``GET /lighthouse/device``."""
    return STATE.snapshot()


def reset_for_tests() -> None:
    STATE.reset_for_tests()


def scale_target(target_sets: int) -> int:
    """A batch-fill target scaled to the CURRENT mesh (the device pipeline
    consults this per coalescing decision): a mesh shrunk from F to S
    devices fills S/F of the configured target — waiting to fill lanes the
    survivors no longer have would only add linger latency.  Identity when
    the mesh is off or at full strength.  Never imports jax."""
    with STATE._lock:
        full, current = STATE._full_size, len(STATE._devices)
    if full < 2 or current >= full or current < 2:
        return target_sets
    return max(1, target_sets * current // full)


# ----------------------------------------------------------- sharded entry


class ShardedEntry:
    """One entry point's sharded lowering, derived from ``BATCH_AXES``.

    ``fn`` is the *unwrapped* python callable (``entry.__wrapped__``) — the
    jitted wrapper here carries the mesh ``in_shardings``/``out_shardings``
    and is cached per topology generation, so a reshard transparently
    recompiles for the surviving devices on the next dispatch.
    """

    def __init__(self, entry_key: str, fn, *,
                 static_argnames: Tuple[str, ...] = ()):
        decl = _registry().get(entry_key)
        if decl is None:
            raise KeyError(
                f"{entry_key} has no ops/batch_axes.py declaration — the "
                "mesh layer cannot derive its PartitionSpecs")
        self.entry_key = entry_key
        self.op = decl["op"]
        self.fn = fn
        self.static_argnames = tuple(static_argnames)
        self.batch_axis = int(decl["batch_axis"])
        raw_out = decl.get("out_batched", False)
        # A list declares one flag per output leaf (mixed batched /
        # replicated results, e.g. the fused epoch-boundary kernel's
        # per-validator arrays alongside its replicated proposer table).
        self.out_batched = (
            tuple(bool(b) for b in raw_out)
            if isinstance(raw_out, (list, tuple)) else bool(raw_out)
        )
        batched = list(decl["batched_args"])
        replicated = list(decl["replicated_args"])
        params = [
            p.name for p in inspect.signature(fn).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.name not in self.static_argnames
        ]
        undeclared = [p for p in params if p not in batched + replicated]
        if undeclared:
            raise ValueError(
                f"{entry_key}: parameters {undeclared} are neither batched "
                "nor replicated in ops/batch_axes.py — declare them")
        #: positional arg index -> True when batched
        self.arg_batched: Tuple[bool, ...] = tuple(
            name in batched for name in params
        )
        self._cache_lock = locksmith.lock("ShardedEntry._cache_lock")
        self._jitted: Dict[int, Any] = {}  # generation -> jitted wrapper

    # ------------------------------------------------------------- specs

    def _specs(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = [None] * (self.batch_axis + 1)
        spec[self.batch_axis] = AXIS
        dp = NamedSharding(mesh, P(*spec))
        repl = NamedSharding(mesh, P())
        return dp, repl

    def in_shardings(self, mesh) -> tuple:
        """Per-positional-arg sharding tree (each entry broadcasts over
        that argument's leaves — jit/device_put accept prefix pytrees)."""
        dp, repl = self._specs(mesh)
        return tuple(dp if b else repl for b in self.arg_batched)

    def out_sharding(self, mesh):
        dp, repl = self._specs(mesh)
        if isinstance(self.out_batched, tuple):
            return tuple(dp if b else repl for b in self.out_batched)
        return dp if self.out_batched else repl

    # --------------------------------------------------------- placement

    def place(self, *args):
        """THE mesh placer: upload every argument under its derived
        ``NamedSharding`` on the current mesh.  Callers pad the batch axis
        with :func:`pad_rows` first (this asserts divisibility rather than
        letting jax produce an opaque sharding error mid-dispatch)."""
        import jax

        mesh = STATE.mesh()
        if mesh is None:
            raise RuntimeError("device mesh is not enabled")
        shardings = self.in_shardings(mesh)
        assert len(shardings) == len(args), (
            f"{self.entry_key}: {len(args)} args vs "
            f"{len(shardings)} declared parameters")
        return tuple(
            jax.device_put(a, s) for a, s in zip(args, shardings)
        )

    # ---------------------------------------------------------- dispatch

    def callable(self, **static_kwargs):
        """The jitted sharded wrapper for the current topology (compiled
        lazily per (generation, static kwargs); stale generations are
        dropped so an old mesh's executables cannot be dispatched to dead
        devices).  Static keyword arguments (the epoch kernel's
        ``in_leak``) are bound via ``functools.partial`` — pjit rejects
        kwargs alongside ``in_shardings``, and a bound static forks the
        compiled program exactly like ``static_argnames`` would."""
        import functools

        import jax

        mesh = STATE.mesh()
        if mesh is None:
            raise RuntimeError("device mesh is not enabled")
        unknown = set(static_kwargs) - set(self.static_argnames)
        if unknown:
            raise TypeError(f"{self.entry_key}: non-static kwargs {unknown}")
        gen = STATE.generation()
        key = (gen, tuple(sorted(static_kwargs.items())))
        with self._cache_lock:
            if not any(k[0] == gen for k in self._jitted):
                self._jitted = {}  # topology changed: drop stale wrappers
            fn = self._jitted.get(key)
            if fn is None:
                base = (functools.partial(self.fn, **static_kwargs)
                        if static_kwargs else self.fn)
                # One wrapper per (topology generation, static args); the
                # dict IS the bounded cache, stale generations dropped.
                # recompile-hazard: ok(per-generation wrapper cache)
                fn = self._jitted[key] = jax.jit(
                    base,
                    in_shardings=self.in_shardings(mesh),
                    out_shardings=self.out_sharding(mesh),
                )
            return fn

    def __call__(self, *args, **static_kwargs):
        return self.callable(**static_kwargs)(*args)

    def shard_live_counts(self, n_live: int, padded_rows: int) -> List[int]:
        """Host-side per-shard live-row counts (live rows are packed at the
        front of every batch): the per-shard occupancy view — padding lands
        on the LAST shards, and this shows exactly where."""
        m = STATE.size()
        if m < 2 or padded_rows % m:
            return [n_live]
        rows = padded_rows // m
        return [max(0, min(rows, n_live - s * rows)) for s in range(m)]
