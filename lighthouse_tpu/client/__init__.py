"""Client assembly and runtime: the staged builder, the per-slot timer, the
notifier, and shutdown orchestration.

Equivalent of the reference's ``beacon_node/client`` crate
(``builder.rs:109-1008`` ``ClientBuilder`` — staged construction of
store → chain → network → http; ``notifier.rs`` — the per-slot status log)
plus ``common/task_executor`` (``lib.rs:169-258`` — spawn/shutdown of the
service tasks).

The builder defaults the BLS backend to ``jax`` — production nodes verify on
the device program; tests that want the host/fake backends pass them
explicitly (VERDICT r1 item 5: the device backend is the node default).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from ..chain import BeaconChain
from ..chain.slot_clock import SystemTimeSlotClock
from ..scheduler import BeaconProcessor
from ..types.containers import build_types
from ..types.spec import ChainSpec, mainnet_spec

from ..logs import get_logger

log = get_logger("client")


class ClientBuilder:
    """Staged assembly; each ``with_*`` returns self (builder.rs style)."""

    def __init__(self):
        self._spec: Optional[ChainSpec] = None
        self._genesis_state = None
        self._datadir: Optional[str] = None
        self._el_url: Optional[str] = None
        self._el_jwt: Optional[bytes] = None
        self._http_port: Optional[int] = None
        self._metrics = True
        self._slasher = False
        self._bls_backend = os.environ.get("LIGHTHOUSE_TPU_BLS_BACKEND", "jax")
        self._max_workers = 4
        self._kzg = None

    def with_spec(self, spec: ChainSpec) -> "ClientBuilder":
        self._spec = spec
        return self

    def with_genesis_state(self, state) -> "ClientBuilder":
        self._genesis_state = state
        return self

    def with_interop_genesis(self, validator_count: int,
                             genesis_time: Optional[int] = None) -> "ClientBuilder":
        from ..consensus.genesis import interop_genesis_state
        import time as _time

        spec = self._spec or mainnet_spec()
        self._spec = spec
        types = build_types(spec.preset)
        self._genesis_state = interop_genesis_state(
            validator_count, types, spec,
            genesis_time=int(_time.time()) if genesis_time is None else genesis_time,
        )
        return self

    def with_datadir(self, path: str) -> "ClientBuilder":
        self._datadir = path
        return self

    def with_execution_layer(self, url: str, jwt_secret: bytes) -> "ClientBuilder":
        self._el_url = url
        self._el_jwt = jwt_secret
        return self

    def with_http_api(self, port: int = 5052) -> "ClientBuilder":
        self._http_port = port
        return self

    def with_checkpoint_sync(self, url: str) -> "ClientBuilder":
        """Boot from a trusted node's FINALIZED checkpoint instead of genesis
        (reference ``builder.rs:341-528`` weak-subjectivity sync): fetch the
        finalized block + its post-state as SSZ over the standard API and
        anchor the chain there; backfill fills history behind it."""
        self._checkpoint_url = url
        return self

    def with_network(self, *, listen_port: int = 0, listen_address: str = "0.0.0.0",
                     peers=None, boot_nodes=None) -> "ClientBuilder":
        """Join the p2p fabric over TCP: listen, dial static peers and boot
        nodes, discover the rest (reference: the network stage of
        builder.rs wiring lighthouse_network + router + sync)."""
        self._net_listen = (listen_address, listen_port)
        self._net_peers = list(peers or [])
        self._net_boot_nodes = list(boot_nodes or [])
        return self

    def with_monitoring(self, endpoint: str,
                        update_period: float = 60.0) -> "ClientBuilder":
        """Push node stats to a remote client-stats endpoint (reference
        ``common/monitoring_api`` / the --monitoring-endpoint flag)."""
        self._monitoring_endpoint = endpoint
        self._monitoring_period = update_period
        return self

    def with_slasher(self, enabled: bool = True) -> "ClientBuilder":
        self._slasher = enabled
        return self

    def with_bls_backend(self, name: str) -> "ClientBuilder":
        self._bls_backend = name
        return self

    def with_kzg(self, kzg) -> "ClientBuilder":
        self._kzg = kzg
        return self

    # ----------------------------------------------------------------- build

    def _checkpoint_fetch(self, types):
        """Fetch (anchor_state, anchor_block) from the trusted URL."""
        from ..http_api.client import BeaconNodeHttpClient

        remote = BeaconNodeHttpClient(self._checkpoint_url, timeout=30.0)
        root = remote.block_root("finalized")
        raw_block, fork = remote.get_ssz(f"/eth/v2/beacon/blocks/0x{root.hex()}")
        if fork is None:
            # no consensus-version header: derive the fork from the slot at
            # its fixed SSZ offset (message offset word + 96-byte signature)
            slot = int.from_bytes(raw_block[100:108], "little")
            fork = self._spec.fork_name_at_slot(slot)
        if fork not in types.signed_block:
            raise ValueError(f"checkpoint provider sent unknown fork {fork!r}")
        anchor_block = types.signed_block[fork].from_ssz_bytes(raw_block)
        if anchor_block.message.hash_tree_root() != root:
            # The URL may be plain HTTP and the provider is only *semi*
            # trusted: without this check a tampered response could anchor
            # the node on a different block while still passing the
            # state-root check below.
            raise ValueError(
                "checkpoint provider served a block that does not match the "
                "finalized root it advertised — refusing the anchor"
            )
        state_root = bytes(anchor_block.message.state_root)
        raw_state, sfork = remote.get_ssz(
            f"/eth/v2/debug/beacon/states/0x{state_root.hex()}"
        )
        anchor_state = types.state[sfork or fork].from_ssz_bytes(raw_state)
        if anchor_state.hash_tree_root() != state_root:
            raise ValueError(
                "checkpoint provider served a state that does not match the "
                "finalized block's state root — refusing the anchor"
            )
        log.info(
            "checkpoint sync: anchored at finalized slot %d (%s)",
            int(anchor_block.message.slot), root.hex()[:12],
        )
        return anchor_state, anchor_block

    def build(self) -> "Client":
        anchor_block = None
        types = None
        if getattr(self, "_checkpoint_url", None):
            if self._spec is None:
                raise ValueError("checkpoint sync still needs a spec")
            types = build_types(self._spec.preset)
            self._genesis_state, anchor_block = self._checkpoint_fetch(types)
        if self._spec is None or self._genesis_state is None:
            raise ValueError("builder needs a spec and a genesis state")
        from ..crypto.bls.backends import set_backend

        set_backend(self._bls_backend)  # node assembly selects the device path
        if self._bls_backend == "jax":
            # Persistent compile cache + optional AOT bucket warmup
            # (ops/compile_cache.py): cold XLA compiles are paid once per
            # binary, not per node restart — and with
            # LIGHTHOUSE_TPU_AOT_WARMUP=1 the standard buckets compile on a
            # background thread before the first batch arrives.
            try:
                from ..ops import compile_cache

                compile_cache.configure_persistent_cache()
                compile_cache.maybe_warmup_from_env()
            except Exception:
                log.warning("persistent compile-cache setup failed",
                            exc_info=True)
            # Mesh sharding (device_mesh.py): LIGHTHOUSE_TPU_MESH=N|auto
            # shards every bucketed device op's batch axis over the device
            # mesh.  Configured eagerly at node assembly so the topology
            # (and its per-device breakers) is logged and gauged before
            # traffic arrives; <2 devices falls back to single-device
            # dispatch transparently.
            try:
                from .. import device_mesh

                device_mesh.configure()
            except Exception:
                log.warning("device mesh setup failed", exc_info=True)
            # Async device pipeline (device_pipeline.py): production nodes
            # stream every signature-set group through the persistent device
            # worker so block import / gossip / sync-committee work coalesce
            # into maximal device batches.  LIGHTHOUSE_TPU_DEVICE_PIPELINE=0
            # opts out (device_pipeline.enable honors it).
            from .. import device_pipeline

            device_pipeline.enable()
            # Self-tuning control plane (autotune.py): under
            # LIGHTHOUSE_TPU_AUTOTUNE=live this measures the fq backend
            # (FQ_BACKEND=auto only; cached per device kind) and starts the
            # periodic controller that overlays bucket vocabularies from
            # the flight recorder.  The default mode (pinned) starts
            # nothing — decisions then replay only from an installed pin.
            try:
                from .. import autotune

                autotune.maybe_start_from_env()
            except Exception:
                log.warning("autotune startup failed", exc_info=True)
        if os.environ.get("LIGHTHOUSE_TPU_DEVICE_SHA") == "1":
            from ..ops.sha256_device import install_device_hash

            install_device_hash()  # bulk Merkle layers on the device VPU
        if types is None:
            types = build_types(self._spec.preset)

        db = None
        if self._datadir is not None:
            os.makedirs(self._datadir, exist_ok=True)
            from ..store import HotColdDB
            from ..store.lockbox_store import LockboxStore

            db = HotColdDB(
                hot=LockboxStore(os.path.join(self._datadir, "chain.db")),
                types=types,
                spec=self._spec,
            )

        execution_engine = None
        if self._el_url is not None:
            from ..execution_layer import ExecutionLayer

            execution_engine = ExecutionLayer(url=self._el_url, jwt_secret=self._el_jwt)

        chain = BeaconChain(
            genesis_state=self._genesis_state,
            types=types,
            spec=self._spec,
            db=db,
            slot_clock=SystemTimeSlotClock(
                int(self._genesis_state.genesis_time), self._spec.seconds_per_slot
            ),
            execution_engine=execution_engine,
            kzg=self._kzg,
            anchor_block=anchor_block,
        )
        processor = BeaconProcessor(max_workers=self._max_workers)
        slasher = None
        if self._slasher:
            from ..slasher import Slasher, SlasherConfig

            slasher = Slasher(
                types,
                SlasherConfig(slots_per_epoch=self._spec.slots_per_epoch),
                # durable history on the node's lockbox store (reference:
                # SlasherDB over LMDB) — a restart still holds every recorded
                # attestation within the history window; memory-only without
                # a datadir
                store=db.hot if db is not None else None,
            )
        http_server = None
        if self._http_port is not None:
            from ..http_api import HttpApiServer

            http_server = HttpApiServer(chain, processor=processor, port=self._http_port)
        network_node = None
        if getattr(self, "_net_listen", None) is not None:
            from ..network.node import LocalNode
            from ..network.tcp_transport import TcpEndpoint
            import secrets as _secrets

            host, port = self._net_listen
            endpoint_obj = TcpEndpoint(
                f"bn-{_secrets.token_hex(4)}", host=host, port=port
            )
            network_node = LocalNode(
                peer_id=endpoint_obj.peer_id, chain=chain, endpoint=endpoint_obj,
            )
        monitoring = None
        if getattr(self, "_monitoring_endpoint", None):
            from ..monitoring import MonitoringService

            monitoring = MonitoringService(
                endpoint=self._monitoring_endpoint, chain=chain,
                update_period=getattr(self, "_monitoring_period", 60.0),
            )
        if http_server is not None and network_node is not None:
            # VC subnet subscriptions reach the subnet service through the
            # API (reference: http_api -> validator_subscriptions channel),
            # and API-published objects gossip out through the node
            # (reference publish_blocks.rs: gossip first, then self-import)
            http_server.subnet_service = network_node.subnets
            http_server.publish_block_fn = network_node.publish_block
            http_server.publish_attestation_fn = network_node.publish_attestation
            http_server.publish_operation_fn = network_node.publish_operation
        client = Client(
            chain=chain, processor=processor, http_server=http_server,
            slasher=slasher, monitoring=monitoring, network_node=network_node,
        )
        client._static_peers = list(getattr(self, "_net_peers", []))
        client._boot_nodes = list(getattr(self, "_net_boot_nodes", []))
        return client


class Client:
    """The assembled node: owns the service threads and their shutdown
    (task_executor semantics — every service stops on ``stop()``)."""

    def __init__(self, *, chain, processor, http_server=None, slasher=None,
                 monitoring=None, network_node=None):
        self.chain = chain
        self.processor = processor
        self.http_server = http_server
        self.slasher = slasher
        self.monitoring = monitoring
        self.network_node = network_node
        self._static_peers: List[str] = []
        self._boot_nodes: List[str] = []
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "Client":
        if self.http_server is not None:
            self.http_server.start()
        if self.monitoring is not None:
            self.monitoring.start()
        if self.network_node is not None:
            for addr in self._static_peers + self._boot_nodes:
                try:
                    h, _, p = addr.rpartition(":")
                    self.network_node.endpoint.dial(h, int(p), timeout=5.0)
                except Exception as e:
                    log.warning("dial %s failed: %s", addr, e)
            try:
                n = self.network_node.discover_peers()
                if n:
                    log.info("discovered %d peers", n)
            except Exception as e:
                log.warning("peer discovery failed: %s", e)
            if self.chain.anchor_slot > 0:
                # checkpoint boot: fill history behind the anchor off the
                # hot path (reference: backfill runs as a background sync)
                t = threading.Thread(
                    target=self._run_backfill, name="backfill", daemon=True
                )
                t.start()
                self._threads.append(t)
        timer = threading.Thread(target=self._slot_timer, name="slot-timer", daemon=True)
        timer.start()
        self._threads.append(timer)
        return self

    def _run_backfill(self) -> None:
        from ..network.backfill import BackfillSync

        backfill = BackfillSync(chain=self.chain, service=self.network_node.service)
        while not self._shutdown.is_set() and not backfill.complete:
            peers = list(self.network_node.endpoint.connected_peers())
            progressed = 0
            for peer in peers:
                try:
                    # a batch that fails on `peer` retries once against the
                    # next connected peer instead of ending the round
                    progressed += backfill.backfill_from(
                        peer, fallback_peers=[p for p in peers if p != peer])
                except Exception as e:
                    log.warning("backfill from %s failed: %s", peer, e)
                if backfill.complete:
                    break
            if backfill.complete:
                log.info("backfill complete: %d blocks", backfill.blocks_filled)
                return
            if not progressed:
                # nothing served this round: wait for more/better peers
                self._shutdown.wait(timeout=12.0)

    def _slot_timer(self) -> None:
        """Per-slot tick + notifier line (reference ``timer`` crate +
        ``notifier.rs``)."""
        clock = self.chain.slot_clock
        sps = self.chain.spec.seconds_per_slot
        while not self._shutdown.is_set():
            wait = clock.duration_to_next_slot()
            if wait is None:
                wait = sps
            # tail-of-slot: pre-advance the head state for the NEXT slot
            # (reference state_advance_timer fires at 3/4 of the slot)
            head_wait = max(0.0, wait - sps / 4)
            if self._shutdown.wait(timeout=head_wait + 0.01):
                return
            slot_before = self.chain.current_slot()
            try:
                self.chain.prepare_next_slot()
            except Exception as e:
                log.warning("state pre-advance failed: %s", e)
            if self.chain.current_slot() == slot_before:
                # normal case: the advance finished inside the slot — wait
                # out the remainder.  If it OVERRAN the boundary, fall
                # through and tick immediately (the new slot must not lose
                # its head recompute/pruning to a full-slot sleep).
                remaining = clock.duration_to_next_slot()
                if remaining is None:
                    remaining = sps - head_wait  # pre-genesis: keep 1 tick/slot
                if self._shutdown.wait(timeout=remaining + 0.05):
                    return
            try:
                self.chain.per_slot_task()
                node = self.network_node
                if node is not None and getattr(node, "subnets", None) is not None:
                    slot = self.chain.current_slot()
                    node.subnets.prune(slot)
                    node.subnets.update_epoch(
                        slot // self.chain.spec.slots_per_epoch)
                    node.refresh_subnet_advertisement()
                self._notify()
            except Exception as e:  # a tick must never kill the timer
                log.warning("per-slot task failed: %s", e)

    def _notify(self) -> None:
        chain = self.chain
        slot = chain.current_slot()
        head_slot = chain.head_slot()
        f_epoch, _ = chain.finalized_checkpoint()
        distance = max(0, slot - head_slot)
        status = "synced" if distance <= 1 else f"behind ({distance} slots)"
        log.info(
            "slot %d | head %s at slot %d | finalized epoch %d | %s",
            slot, chain.head_root.hex()[:10], head_slot, f_epoch, status,
        )
        # Fork-readiness watcher (reference notifier.rs *_readiness blocks):
        # logs ready / NOT-ready inside the pre-fork window.
        from ..chain.fork_readiness import fork_readiness

        try:
            fork_readiness(chain)
        except Exception:
            pass  # a readiness probe must never kill the notifier

    def stop(self) -> None:
        self._shutdown.set()
        if self.network_node is not None:
            try:
                self.network_node.shutdown()
            except Exception:
                pass
        if self.monitoring is not None:
            self.monitoring.stop()
        if self.http_server is not None:
            self.http_server.stop()
        self.processor.shutdown()
        # Drain the device pipeline AFTER the processor stops feeding it:
        # pending futures resolve (no caller hangs), then its threads exit.
        from .. import device_pipeline

        device_pipeline.shutdown()
        for t in self._threads:
            t.join(timeout=2.0)
        if self.chain.db is not None:
            try:
                self.chain.db.close()
            except AttributeError:
                pass
