"""Timeout-guarded locks: deadlocks must scream, not hang.

The reference wraps its canonical-head and snapshot locks in
``TimeoutRwLock`` (beacon_chain/src/timeout_rw_lock.rs): a lock held past a
deadline raises instead of blocking forever, because a deadlock between the
HTTP threads, the processor workers and the import path would otherwise
present as a silent stall.  Python's GIL removes data races but not
lock-ordering deadlocks — the same discipline applies.
"""

from __future__ import annotations

from . import locksmith
from .logs import get_logger

log = get_logger("locks")

#: Generous default: normal holds are micro/milliseconds; anything reaching
#: this is a bug, not contention (reference uses 1s for the head lock).
DEFAULT_TIMEOUT = 5.0


class LockTimeout(Exception):
    """A lock acquire exceeded its deadline — report the likely deadlock."""


class TimeoutLock:
    """``with lock:`` like ``threading.Lock``, but a bounded acquire that
    raises ``LockTimeout`` (and logs, with the lock's name) on expiry."""

    def __init__(self, name: str = "lock", timeout: float = DEFAULT_TIMEOUT,
                 label: str = None):
        # Label routing (ISSUE 18): the inner lock comes from the locksmith
        # factory, so under LIGHTHOUSE_TPU_LOCK_SANITIZE=1 TimeoutLock
        # acquisitions participate in the runtime order/ownership checks
        # under their static-graph label ("Class.attr").  Off by default:
        # the factory returns a plain threading.Lock.
        self._lock = locksmith.lock(label or name)
        self.name = name
        self.timeout = timeout

    def acquire(self, timeout: float = None) -> bool:
        limit = self.timeout if timeout is None else timeout
        if self._lock.acquire(timeout=limit):
            return True
        log.error("lock acquire timed out (possible deadlock)",
                  lock=self.name, timeout_s=limit)
        raise LockTimeout(f"{self.name}: not acquired within {limit}s")

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "TimeoutLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()
