"""Structured logging: the reference's ``common/logging`` role.

The reference emits slog-style structured records (message + key=value
fields) to the terminal and keeps an in-memory tail that the HTTP API
streams over SSE (``common/logging/src/lib.rs:207-224`` — Siren's live log
view).  Here:

- ``setup_logging`` installs a key=value formatter (or JSON lines with
  ``json_format=True``) on the ``lighthouse_tpu`` logger tree.
- ``LogRing`` is a bounded ring of recent records every handler feeds;
  ``/lighthouse/logs`` (http_api) streams it as SSE.
- ``get_logger(name).info("imported block", slot=5, root="0x..")`` —
  keyword fields ride the record and render as ``key=value`` pairs.
"""

from __future__ import annotations

import collections
import io
import json
import logging
import threading
import time
from typing import Deque, Dict, List, Optional

_ROOT_NAME = "lighthouse_tpu"


class LogRing(logging.Handler):
    """Keep the last N formatted records for the SSE tail (the reference's
    SSELoggingComponents channel)."""

    def __init__(self, capacity: int = 512):
        super().__init__()
        self.capacity = capacity
        self._buf: Deque[dict] = collections.deque(maxlen=capacity)
        self._cv = threading.Condition()
        self._seq = 0

    def emit(self, record: logging.LogRecord) -> None:
        entry = {
            "seq": 0,  # assigned under the lock
            "time": round(record.created, 3),
            "level": record.levelname,
            "module": record.name,
            "message": record.getMessage(),
            "fields": getattr(record, "structured_fields", {}),
        }
        # Log lines join the black-box cross-reference scheme: a line
        # emitted inside a traced request carries its trace id, so a
        # postmortem bundle's log tail links to the implicated trace
        # trees the same way journal and flight records do.  (Imported
        # here, not at module top: ``tracing`` is stdlib-only, but every
        # module in the package imports ``logs`` first.)
        from . import tracing

        sp = tracing.current_span()
        if sp is not None:
            entry["trace_id"] = sp.trace.trace_id
        with self._cv:
            self._seq += 1
            entry["seq"] = self._seq
            self._buf.append(entry)
            self._cv.notify_all()
        # Node-scoped mirror: a line emitted while a telemetry scope is
        # active also lands in that node's log tail, so a fleet triage
        # reads one node's lines without grepping the merged ring.
        from . import telemetry_scope

        scope = telemetry_scope.current()
        if scope is not None:
            entry = dict(entry)
            entry["node"] = scope.node_id
            scope.note_log(entry)

    def tail(self, n: int = 100) -> List[dict]:
        with self._cv:
            return list(self._buf)[-n:]

    def wait_for(self, after_seq: int, timeout: float = 10.0) -> List[dict]:
        """Records with seq > after_seq, blocking up to ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                fresh = [e for e in self._buf if e["seq"] > after_seq]
                if fresh:
                    return fresh
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cv.wait(remaining)


RING = LogRing()


class StructuredFormatter(logging.Formatter):
    """``HH:MM:SS.mmm LEVL module  message  key=value ...`` (slog-shaped)."""

    def __init__(self, json_format: bool = False):
        super().__init__()
        self.json_format = json_format

    def format(self, record: logging.LogRecord) -> str:
        fields: Dict = getattr(record, "structured_fields", {})
        if self.json_format:
            return json.dumps({
                "ts": round(record.created, 3),
                "level": record.levelname,
                "module": record.name,
                "msg": record.getMessage(),
                **fields,
            })
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        ms = int((record.created % 1) * 1000)
        out = io.StringIO()
        out.write(f"{ts}.{ms:03d} {record.levelname:<5} {record.name}  ")
        out.write(record.getMessage())
        for k, v in fields.items():
            out.write(f"  {k}={v}")
        if record.exc_info:
            out.write("\n" + self.formatException(record.exc_info))
        return out.getvalue()


class StructuredAdapter(logging.LoggerAdapter):
    """Keyword arguments become structured fields:
    ``log.info("imported", slot=5)`` -> ``imported  slot=5``."""

    _RESERVED = {"exc_info", "stack_info", "stacklevel", "extra"}

    def _forward(self, level, msg, args, kwargs):
        fields = {k: v for k, v in kwargs.items() if k not in self._RESERVED}
        passthrough = {k: v for k, v in kwargs.items() if k in self._RESERVED}
        extra = passthrough.setdefault("extra", {})
        extra["structured_fields"] = fields
        self.logger.log(level, msg, *args, **passthrough)

    def debug(self, msg, *args, **kwargs):
        self._forward(logging.DEBUG, msg, args, kwargs)

    def info(self, msg, *args, **kwargs):
        self._forward(logging.INFO, msg, args, kwargs)

    def warning(self, msg, *args, **kwargs):
        self._forward(logging.WARNING, msg, args, kwargs)

    def error(self, msg, *args, **kwargs):
        self._forward(logging.ERROR, msg, args, kwargs)

    def critical(self, msg, *args, **kwargs):
        self._forward(logging.CRITICAL, msg, args, kwargs)


def get_logger(name: str) -> StructuredAdapter:
    """Logger under the package tree; fields via keyword arguments."""
    full = name if name.startswith(_ROOT_NAME) else f"{_ROOT_NAME}.{name}"
    return StructuredAdapter(logging.getLogger(full), {})


_configured = False


def setup_logging(level: int = logging.INFO, *, json_format: bool = False,
                  stream=None) -> None:
    """Install the structured formatter + the SSE ring on the package tree.
    Idempotent; safe to call from the CLI and from tests."""
    global _configured
    root = logging.getLogger(_ROOT_NAME)
    root.setLevel(level)
    if _configured:
        return
    handler = logging.StreamHandler(stream)
    handler.setFormatter(StructuredFormatter(json_format=json_format))
    root.addHandler(handler)
    root.addHandler(RING)
    root.propagate = False
    _configured = True
