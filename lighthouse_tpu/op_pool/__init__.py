"""Operation pool: attestations (max-cover packed), slashings, exits,
BLS-to-execution changes.

Equivalent of the reference's ``beacon_node/operation_pool`` (3.5k LoC):
compact attestation storage keyed by ``AttestationData`` root with multiple
(possibly overlapping) aggregates per key, greedy **max-cover** selection for
block production (`operation_pool/src/max_cover.rs`), and validity-filtered
pools for the other operation types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..consensus import helpers as h
from ..types.spec import TIMELY_TARGET_FLAG_INDEX, ChainSpec


def attester_slashing_indices(slashing) -> List[int]:
    """Validators an attester slashing convicts: the intersection of the two
    attestations' index sets (spec ``process_attester_slashing``) — the ONE
    implementation every consumer (packing, pruning, fork-choice masking,
    adversary evidence) shares."""
    a1 = {int(i) for i in slashing.attestation_1.attesting_indices}
    a2 = {int(i) for i in slashing.attestation_2.attesting_indices}
    return sorted(a1 & a2)


def max_cover(candidates: Sequence[Tuple[object, Set[int]]], limit: int) -> List[object]:
    """Greedy maximum-coverage: repeatedly take the candidate covering the
    most yet-uncovered items (reference ``max_cover.rs`` — same greedy
    (1 - 1/e)-approximation, with covered items deducted from remaining
    candidates each round)."""
    remaining = [(item, set(cover)) for item, cover in candidates]
    covered: Set[int] = set()
    out: List[object] = []
    while remaining and len(out) < limit:
        best_i = max(range(len(remaining)), key=lambda i: len(remaining[i][1] - covered))
        item, cover = remaining.pop(best_i)
        fresh = cover - covered
        if not fresh:
            break
        covered |= fresh
        out.append(item)
    return out


@dataclass
class _AttestationGroup:
    """All aggregates seen for one AttestationData (reference
    ``attestation_storage.rs`` compact representation)."""

    data: object
    aggregates: List[object] = field(default_factory=list)  # Attestation objects

    def insert(self, attestation) -> None:
        new_bits = list(attestation.aggregation_bits)
        for existing in self.aggregates:
            if list(existing.aggregation_bits) == new_bits:
                return  # exact duplicate
        # keep only non-subsumed aggregates
        self.aggregates = [
            a
            for a in self.aggregates
            if not _is_subset(list(a.aggregation_bits), new_bits)
        ]
        if not any(
            _is_subset(new_bits, list(a.aggregation_bits)) for a in self.aggregates
        ):
            self.aggregates.append(attestation.copy())


def _is_subset(a: List[bool], b: List[bool]) -> bool:
    return all((not x) or y for x, y in zip(a, b))


class OperationPool:
    def __init__(self) -> None:
        self._attestations: Dict[Tuple[int, bytes], _AttestationGroup] = {}
        self._proposer_slashings: Dict[int, object] = {}  # by proposer index
        # keyed by hash_tree_root: the local slasher and the gossip topic can
        # both deliver the same container — the pool must not grow on replays
        self._attester_slashings: Dict[bytes, object] = {}
        self._voluntary_exits: Dict[int, object] = {}  # by validator index
        self._bls_changes: Dict[int, object] = {}  # by validator index

    # ------------------------------------------------------- attestations

    def insert_attestation(self, attestation) -> None:
        key = (int(attestation.data.slot), h.attestation_dedup_key(attestation))
        group = self._attestations.get(key)
        if group is None:
            group = self._attestations[key] = _AttestationGroup(data=attestation.data)
        group.insert(attestation)

    def num_attestations(self) -> int:
        return sum(len(g.aggregates) for g in self._attestations.values())

    def get_attestations(self, state, types, spec: ChainSpec, limit: int) -> List[object]:
        """Max-cover packing of attestations valid for a block on ``state``
        (reference ``op_pool.get_attestations`` → ``AttMaxCover``): coverage
        sets are the attesting validator indices not yet known to the state's
        participation."""
        from ..consensus.per_block import process_attestation

        candidates: List[Tuple[object, Set[int]]] = []
        state_slot = int(state.slot)
        fork = type(state).fork_name
        # Freshness filter (reference ``AttMaxCover::fresh_validators``):
        # a validator who already carries the timely-target flag for the
        # attestation's epoch contributes nothing, so it must not count as
        # coverage.  Without this, deneb's unbounded inclusion window
        # (EIP-7045) lets stale aggregates outscore fresh current-epoch
        # ones and crowd them out of the block — justification then lands
        # one epoch late and finalization trails by an epoch.  phase0
        # states keep raw coverage (participation is pending-attestation
        # based there; the inclusion window is one epoch anyway).
        participation_by_epoch = {}
        if fork != "phase0":
            participation_by_epoch = {
                int(h.get_previous_epoch(state, spec)):
                    state.previous_epoch_participation,
                int(h.get_current_epoch(state, spec)):
                    state.current_epoch_participation,
            }
        # Canonical candidate order (sorted keys, then bit patterns), NOT
        # gossip-arrival order: max_cover breaks ties by position, so two
        # nodes with the same pool contents — or one node across two runs —
        # must pack identical bodies whatever order the wire delivered the
        # attestations in (the scenario soak's determinism gate).
        is_electra_state = fork == "electra"
        for (slot, _), group in sorted(self._attestations.items()):
            if not spec.attestation_includable(slot, state_slot):
                continue
            for att in sorted(
                group.aggregates,
                key=lambda a: (tuple(a.aggregation_bits),
                               tuple(getattr(a, "committee_bits", ()) or ())),
            ):
                committee_bits = getattr(att, "committee_bits", None)
                # container families don't cross the electra boundary:
                # pre-fork attestations can't ride in electra bodies (and
                # vice versa) — EIP-7549 changed the container.
                if (committee_bits is not None) != is_electra_state:
                    continue
                try:
                    if committee_bits is not None:
                        # electra: indices derived through committee_bits
                        cover = set(h.get_attesting_indices(
                            state, att.data, att.aggregation_bits, spec,
                            committee_bits=committee_bits,
                        ))
                    else:
                        committee = h.get_beacon_committee(
                            state, int(att.data.slot), int(att.data.index), spec
                        )
                        cover = {
                            int(committee[i])
                            for i, bit in enumerate(att.aggregation_bits)
                            if bit and i < len(committee)
                        }
                except Exception:
                    continue
                if fork != "phase0":
                    part = participation_by_epoch.get(int(att.data.target.epoch))
                    if part is None:
                        continue  # target epoch not includable on this state
                    cover = {
                        i for i in cover
                        if i < len(part)
                        and not h.has_flag(int(part[i]), TIMELY_TARGET_FLAG_INDEX)
                    }
                if cover:
                    candidates.append((att, cover))
        picked = max_cover(candidates, limit)
        # Validity filter by trial application (the reference's per-op checks)
        scratch = state.copy()
        out = []
        for att in picked:
            try:
                process_attestation(scratch, att, types, spec, verify=False)
            except Exception:
                continue
            out.append(att)
        return out

    # ---------------------------------------------------------- slashings

    def insert_proposer_slashing(self, slashing) -> None:
        self._proposer_slashings[int(slashing.signed_header_1.message.proposer_index)] = slashing

    def insert_attester_slashing(self, slashing) -> None:
        self._attester_slashings.setdefault(slashing.hash_tree_root(), slashing)

    def attester_slashings(self) -> List[object]:
        """Pool contents in canonical (container-root) order.  Iterations
        snapshot via ``.copy()`` (GIL-atomic): the pool is lock-free and a
        worker may insert concurrently — the old list tolerated appends
        mid-iteration, the dict must too."""
        return [s for _root, s in sorted(self._attester_slashings.copy().items())]

    def num_proposer_slashings(self) -> int:
        return len(self._proposer_slashings)

    def has_proposer_slashing(self, proposer_index: int) -> bool:
        return int(proposer_index) in self._proposer_slashings

    def num_attester_slashings(self) -> int:
        return len(self._attester_slashings)

    def get_slashings(self, state, spec: ChainSpec, types) -> Tuple[List, List]:
        """(proposer_slashings, attester_slashings) valid against ``state``,
        bounded by the preset maxima.

        Packing order is canonical (proposer slashings by proposer index,
        attester slashings by container root), never arrival order: two
        nodes holding the same pool — or one node across two runs — must
        pack identical bodies (the scenario soak's determinism gate), and a
        slashing flood past the per-block cap must overflow into later
        blocks deterministically.  Slashings whose validators are all
        already slashed in ``state`` are dead block space and are skipped
        (``is_slashable_validator`` excludes slashed validators)."""
        epoch = h.get_current_epoch(state, spec)
        proposer = []
        for idx in sorted(self._proposer_slashings):
            s = self._proposer_slashings[idx]
            if idx < len(state.validators) and h.is_slashable_validator(
                state.validators[idx], epoch
            ):
                proposer.append(s)
            if len(proposer) >= spec.preset.max_proposer_slashings:
                break
        attester = []
        covered: Set[int] = set()
        is_electra_state = type(state).fork_name == "electra"
        max_attester = (
            spec.preset.max_attester_slashings_electra
            if is_electra_state
            else spec.preset.max_attester_slashings
        )
        for _root, s in sorted(self._attester_slashings.copy().items()):
            # container families don't cross the electra boundary (EIP-7549
            # changed IndexedAttestation's limits)
            if ("Electra" in type(s).__name__) != is_electra_state:
                continue
            # a mis-oriented pair would fail per_block processing and poison
            # every produced block — never hand one out
            if not h.is_slashable_attestation_data(
                s.attestation_1.data, s.attestation_2.data
            ):
                continue
            slashable = {
                i
                for i in attester_slashing_indices(s)
                if i < len(state.validators)
                and h.is_slashable_validator(state.validators[i], epoch)
            }
            if slashable - covered:
                covered |= slashable
                attester.append(s)
            if len(attester) >= max_attester:
                break
        return proposer, attester

    # -------------------------------------------------------------- exits

    def insert_voluntary_exit(self, signed_exit) -> None:
        self._voluntary_exits[int(signed_exit.message.validator_index)] = signed_exit

    def get_voluntary_exits(self, state, types, spec: ChainSpec) -> List[object]:
        """Exits includable in a block on ``state``: full spec validity via
        trial application (a stale pool entry must never break production —
        reference filters with ``verify_operation`` revalidation)."""
        from ..consensus.per_block import process_voluntary_exit

        scratch = None
        out = []
        for idx, ex in self._voluntary_exits.items():
            if idx >= len(state.validators):
                continue
            if scratch is None:
                scratch = state.copy()
            try:
                process_voluntary_exit(scratch, ex, types, spec, verify=False)
            except Exception:
                continue
            out.append(ex)
            if len(out) >= spec.preset.max_voluntary_exits:
                break
        return out

    # --------------------------------------------------- bls-to-execution

    def insert_bls_to_execution_change(self, signed_change) -> None:
        self._bls_changes[int(signed_change.message.validator_index)] = signed_change

    def get_bls_to_execution_changes(self, state, spec: ChainSpec) -> List[object]:
        out = []
        for idx, ch in self._bls_changes.items():
            if idx < len(state.validators) and bytes(
                state.validators[idx].withdrawal_credentials
            )[:1] == b"\x00":
                out.append(ch)
            if len(out) >= spec.preset.max_bls_to_execution_changes:
                break
        return out

    # ------------------------------------------------------------- pruning

    def prune(self, state, spec: ChainSpec, current_slot: Optional[int] = None) -> None:
        """Drop operations no longer includable (reference ``prune_all``).
        ``current_slot`` is the wall-clock slot (the head block may be old)."""
        from ..types.spec import FAR_FUTURE_EPOCH

        cur = int(state.slot) if current_slot is None else current_slot
        self._attestations = {
            k: g for k, g in self._attestations.items() if k[0] + spec.slots_per_epoch >= cur
        }
        n = len(state.validators)
        self._voluntary_exits = {
            i: e
            for i, e in self._voluntary_exits.items()
            if i < n and state.validators[i].exit_epoch == FAR_FUTURE_EPOCH
        }
        epoch = h.get_current_epoch(state, spec)
        self._proposer_slashings = {
            i: s
            for i, s in self._proposer_slashings.items()
            if i < n and h.is_slashable_validator(state.validators[i], epoch)
        }
        # Attester slashings with no still-slashable intersection validator
        # are dead weight forever — every offender is already slashed (or
        # withdrawn); drop them so a slashing flood cannot pin pool memory.
        def _still_slashable(s) -> bool:
            return any(
                i < n and h.is_slashable_validator(state.validators[i], epoch)
                for i in attester_slashing_indices(s)
            )

        self._attester_slashings = {
            root: s for root, s in self._attester_slashings.copy().items()
            if _still_slashable(s)
        }
