"""Virtual time for the scenario engine (ISSUE 20).

The soak engine's control loops used to breathe wall-clock: pump
deadlines, settle quiescence windows, rekick cadences, breaker
cooldowns, and peer-score decay all read ``time.monotonic()``, so a
loaded box hit deadlines at different *virtual* points than an idle
one (ROADMAP item 4's determinism fragility; the 7 ``wallclock``
baseline entries PR 18 left as the work list).  This module is the
sanctioned seam that replaces them.

Model
-----
* A **tick** is the scheduler quantum of the simulated fleet — the
  same unit ``transport.Hub`` counts for delayed-delivery heaps.  The
  hub's ``advance_tick`` drives the clock forward via ``on_tick``, so
  "ticks = hub ticks" holds by construction.
* Virtual **seconds** are derived: ``now() = ticks * tick_s`` with
  ``tick_s = 0.002`` (the settle loop's historical poll quantum).  All
  existing deadline constants (60 s sync, 30 s converge/settle, 1 s
  rekick, breaker cooldowns) keep their meaning as *idealized unloaded
  wall seconds*: a control loop that yields for ``y`` real seconds on
  an idle box advances the virtual clock by the same ``y``.
* **Slots** are derived from ticks (``ticks_per_slot``), giving fault
  plans and scenario gates a slot index that cannot drift from the
  clock.

Who may read the wall clock
---------------------------
Only this module.  ``WallClock`` wraps ``time.monotonic`` for
production (non-scenario) callers, and ``telemetry_stamp`` wraps it
for *telemetry* fields (artifact durations, log stamps) where real
elapsed time is the point.  ``wallclock_pass`` sanctions exactly those
two contexts; every other control-path read is a finding.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Union

#: Virtual seconds represented by one tick.  Chosen to match the old
#: settle poll sleep so "one settle round" costs the same virtual time
#: it used to cost in wall time.
TICK_S = 0.002

#: Real scheduling slice granted to a busy worker thread per settle
#: round.  Virtual time is charged for it via ``charge`` so settle's
#: virtual budget tracks the real waiting it grants.
WAIT_SLICE_S = 0.05


class VirtualClock:
    """A monotonic tick counter masquerading as a clock.

    Thread-safe: the hub tick thread, the scenario runner, and worker
    threads all advance/read it.  Uses a plain ``threading.Lock`` (not
    ``locksmith``) deliberately — the clock is a leaf that never calls
    out while holding its lock, and keeping it out of the lock graph
    keeps the committed graph stable.
    """

    def __init__(self, tick_s: float = TICK_S, *,
                 ticks_per_slot: Optional[int] = None,
                 seconds_per_slot: float = 1.0) -> None:
        if tick_s <= 0:
            raise ValueError("tick_s must be positive")
        self.tick_s = float(tick_s)
        if ticks_per_slot is None:
            ticks_per_slot = max(1, round(seconds_per_slot / self.tick_s))
        if ticks_per_slot <= 0:
            raise ValueError("ticks_per_slot must be positive")
        self.ticks_per_slot = int(ticks_per_slot)
        self._ticks = 0
        self._lock = threading.Lock()

    # ----------------------------------------------------------- reads

    @property
    def ticks(self) -> int:
        with self._lock:
            return self._ticks

    def now(self) -> float:
        """Virtual seconds since clock creation (monotonic)."""
        with self._lock:
            return self._ticks * self.tick_s

    def slot(self) -> int:
        """Slot index derived from ticks."""
        with self._lock:
            return self._ticks // self.ticks_per_slot

    # -------------------------------------------------------- advances

    def advance(self, n: int = 1) -> int:
        """Advance ``n`` ticks; returns the new tick count.

        The hub's ``on_tick`` hook calls this with ``n=1`` per
        delivered tick, which is what makes "ticks = hub ticks" true.
        """
        if n < 0:
            raise ValueError("clock cannot go backwards")
        with self._lock:
            self._ticks += int(n)
            return self._ticks

    def snap_to_next_slot(self) -> int:
        """Advance to the next slot boundary; returns the new tick count.

        The scenario runner calls this at the end of every stepped slot.
        Within-slot tick accrual (settle rounds, wait-slice charges) is
        schedule-dependent; snapping re-anchors the clock so any duration
        that SPANS slots — breaker cooldowns, score decay across a fault
        window — is a deterministic function of the slot timeline alone.
        """
        with self._lock:
            self._ticks += self.ticks_per_slot - (
                self._ticks % self.ticks_per_slot)
            return self._ticks

    def charge(self, seconds: float) -> None:
        """Account for ``seconds`` of real waiting done elsewhere.

        ``Simulator.settle`` grants a busy processor a real
        ``wait_idle(WAIT_SLICE_S)`` slice; charging the equivalent
        ticks keeps the virtual deadline budget aligned with the real
        waiting actually performed, so settle timeouts neither starve
        nor balloon relative to the old wall-clock budget.
        """
        if seconds > 0:
            self.advance(max(1, round(seconds / self.tick_s)))

    # ---------------------------------------------------------- yields

    def lull(self, yield_s: float) -> None:
        """Yield the CPU for ``yield_s`` real seconds *and* advance the
        equivalent virtual ticks.

        This is the control loop's replacement for a bare
        ``time.sleep``: the real yield lets worker threads run, while
        the tick advance moves virtual deadlines at the idealized
        unloaded rate — host load can delay the yield's return without
        shifting the virtual point at which a deadline fires.
        """
        if yield_s > 0:
            time.sleep(yield_s)
            self.advance(max(1, round(yield_s / self.tick_s)))

    def sleep(self, seconds: float) -> None:
        """Burn ``seconds`` of *virtual* time with one real yield.

        Used by the fault-injection hang seam during scenarios: a
        2-second injected hang advances the virtual clock 1000 ticks
        but costs ~0 real time, which is what makes hundreds-of-epochs
        soaks affordable.
        """
        if seconds > 0:
            self.advance(max(1, round(seconds / self.tick_s)))
            time.sleep(0)  # one real yield so waiters can observe it


class WallClock:
    """Production default: virtual time *is* wall time.

    ``now`` is the single sanctioned control-path ``time.monotonic``
    read; ``lull`` degrades to a plain sleep and the virtual-only
    operations are no-ops (wall time advances itself).
    """

    tick_s = TICK_S
    ticks_per_slot = max(1, round(1.0 / TICK_S))

    @property
    def ticks(self) -> int:
        return int(self.now() / self.tick_s)

    def now(self) -> float:
        return time.monotonic()

    def slot(self) -> int:
        return self.ticks // self.ticks_per_slot

    def advance(self, n: int = 1) -> int:
        return self.ticks

    def snap_to_next_slot(self) -> int:
        return self.ticks

    def charge(self, seconds: float) -> None:
        pass

    def lull(self, yield_s: float) -> None:
        if yield_s > 0:
            time.sleep(yield_s)

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


Clock = Union[VirtualClock, WallClock]


def telemetry_stamp() -> float:
    """Wall-clock stamp for telemetry fields (durations, artifacts).

    Telemetry wants *real* elapsed time — an operator reading
    ``duration_s`` in a SOAK artifact is asking how long the run took
    on their box, not how much virtual time it simulated.  This is the
    sanctioned seam for those reads; control paths must use a Clock.
    """
    return time.monotonic()


class _CallableShim:
    """Adapts a legacy ``clock=time.monotonic``-style callable to the
    Clock protocol (``Simulator(clock=fn)`` predates this module)."""

    tick_s = TICK_S
    ticks_per_slot = max(1, round(1.0 / TICK_S))

    def __init__(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def ticks(self) -> int:
        return int(self._fn() / self.tick_s)

    def now(self) -> float:
        return self._fn()

    def slot(self) -> int:
        return self.ticks // self.ticks_per_slot

    def advance(self, n: int = 1) -> int:
        return self.ticks

    def snap_to_next_slot(self) -> int:
        return self.ticks

    def charge(self, seconds: float) -> None:
        pass

    def lull(self, yield_s: float) -> None:
        if yield_s > 0:
            time.sleep(yield_s)

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


def ensure_clock(clock) -> Clock:
    """Coerce ``None`` / legacy callables / Clock instances to a Clock."""
    if clock is None:
        return WallClock()
    if hasattr(clock, "now") and hasattr(clock, "lull"):
        return clock
    if callable(clock):
        return _CallableShim(clock)
    raise TypeError(f"not a clock: {clock!r}")
