"""Native (C++) runtime components, compiled on demand with g++.

The reference links LevelDB/LMDB/SQLite as native storage engines; here the
equivalent embedded engine is ``lockbox.cc``, built once into a shared
library and loaded via ctypes (no pybind11 in the image).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_BUILD_LOCK = threading.Lock()
_LIB = None
_HASH_LIB = None


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "build")
    os.makedirs(d, exist_ok=True)
    return d


def _compile_and_load(src_name: str, so_name: str, extra_flags=()) -> ctypes.CDLL:
    """Compile ``src_name`` (if absent or stale) into ``so_name`` and load it."""
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)), src_name)
    so = os.path.join(_build_dir(), so_name)
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        tmp = so + ".tmp"
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src,
             *extra_flags],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, so)
    return ctypes.CDLL(so)


def load_hash_pairs() -> ctypes.CDLL:
    """Compile (if needed) and load the batched SHA-256 pair hasher."""
    global _HASH_LIB
    if _HASH_LIB is not None:
        return _HASH_LIB
    with _BUILD_LOCK:
        if _HASH_LIB is not None:
            return _HASH_LIB
        lib = _compile_and_load("hash_pairs.cc", "libhashpairs.so",
                                ["-ldl", "-lpthread"])
        lib.hash_pairs.restype = ctypes.c_int
        lib.hash_pairs.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
        ]
        _HASH_LIB = lib
        return lib


def load_lockbox() -> ctypes.CDLL:
    """Compile (if needed) and load the lockbox shared library."""
    global _LIB
    if _LIB is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None:
            return _LIB
        lib = _compile_and_load("lockbox.cc", "liblockbox.so")
        lib.lockbox_open.restype = ctypes.c_void_p
        lib.lockbox_open.argtypes = [ctypes.c_char_p]
        lib.lockbox_close.argtypes = [ctypes.c_void_p]
        lib.lockbox_put.restype = ctypes.c_int
        lib.lockbox_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.lockbox_get.restype = ctypes.c_int64
        lib.lockbox_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.lockbox_delete.restype = ctypes.c_int
        lib.lockbox_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.lockbox_count.restype = ctypes.c_uint64
        lib.lockbox_count.argtypes = [ctypes.c_void_p]
        lib.lockbox_keys.restype = ctypes.c_uint64
        lib.lockbox_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.lockbox_flush.restype = ctypes.c_int
        lib.lockbox_flush.argtypes = [ctypes.c_void_p]
        lib.lockbox_compact.restype = ctypes.c_int
        lib.lockbox_compact.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib
