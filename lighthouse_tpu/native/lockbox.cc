// lockbox: embedded append-only-log key-value store.
//
// The native storage engine backing the hot/cold database — the slot the
// reference fills with LevelDB (C++) via its KeyValueStore trait
// (beacon_node/store/src/leveldb_store.rs).  Deliberately simpler than an
// LSM tree: beacon-chain storage is append-mostly (blocks/states written
// once, pruned in ranges), so a single log file + in-memory index +
// stop-the-world compaction covers the access pattern.
//
// Format: sequence of records
//   [u8 op] [u32 klen] [u32 vlen] [key bytes] [value bytes]
// op: 1 = put, 2 = delete (vlen == 0).  Little-endian lengths.  On open the
// log is scanned to rebuild the index; a torn tail (partial record from a
// crash) is truncated.  Exposed through a C ABI for ctypes.

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Entry {
  uint64_t offset;  // offset of the value bytes in the log
  uint32_t len;
};

struct Lockbox {
  std::string path;
  FILE* log = nullptr;
  std::map<std::string, Entry> index;  // ordered: prefix scans are ranges
  uint64_t log_size = 0;
  uint64_t live_bytes = 0;
  std::mutex mu;
};

bool read_exact(FILE* f, void* buf, size_t n) {
  return fread(buf, 1, n, f) == n;
}

// Open read/write honoring seeks.  "ab+" must not be used for the log:
// append mode writes at EOF regardless of fseeko, so after a failed append
// left torn bytes at EOF the next record would land *after* the torn bytes
// and every later record would be silently discarded by scan() on reopen.
FILE* open_rw(const char* path) {
  FILE* f = fopen(path, "rb+");
  if (!f) f = fopen(path, "wb+");
  return f;
}

// Scan the log, rebuilding the index.  Returns the offset of the first
// corrupt/torn record (== file size when the log is clean).
uint64_t scan(Lockbox* box) {
  FILE* f = box->log;
  fseeko(f, 0, SEEK_SET);
  uint64_t off = 0;
  for (;;) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_exact(f, &op, 1)) break;
    if (!read_exact(f, &klen, 4) || !read_exact(f, &vlen, 4)) break;
    if (op != 1 && op != 2) break;
    if (klen > (1u << 24) || vlen > (1u << 31)) break;
    std::string key(klen, '\0');
    if (!read_exact(f, key.data(), klen)) break;
    uint64_t voff = off + 9 + klen;
    if (op == 1) {
      if (fseeko(f, vlen, SEEK_CUR) != 0) break;
      auto it = box->index.find(key);
      if (it != box->index.end()) box->live_bytes -= it->second.len;
      box->index[key] = Entry{voff, vlen};
      box->live_bytes += vlen;
    } else {
      auto it = box->index.find(key);
      if (it != box->index.end()) {
        box->live_bytes -= it->second.len;
        box->index.erase(it);
      }
    }
    uint64_t next = voff + (op == 1 ? vlen : 0);
    // Verify we actually reached `next` (fseeko past EOF succeeds silently).
    if ((uint64_t)ftello(f) != next) break;
    off = next;
  }
  return off;
}

int append_record(Lockbox* box, uint8_t op, const char* key, uint32_t klen,
                  const char* val, uint32_t vlen) {
  FILE* f = box->log;
  if (!f) return -1;  // a failed compact may have left the log closed
  if (fseeko(f, box->log_size, SEEK_SET) != 0) return -1;
  if (fwrite(&op, 1, 1, f) != 1) return -1;
  if (fwrite(&klen, 4, 1, f) != 1) return -1;
  if (fwrite(&vlen, 4, 1, f) != 1) return -1;
  if (klen && fwrite(key, 1, klen, f) != klen) return -1;
  if (vlen && fwrite(val, 1, vlen, f) != vlen) return -1;
  box->log_size += 9 + klen + vlen;
  return 0;
}

}  // namespace

extern "C" {

void* lockbox_open(const char* path) {
  auto* box = new Lockbox();
  box->path = path;
  box->log = open_rw(path);
  if (!box->log) {
    delete box;
    return nullptr;
  }
  uint64_t clean = scan(box);
  fseeko(box->log, 0, SEEK_END);
  uint64_t size = ftello(box->log);
  if (clean < size) {
    // torn tail from a crash: truncate to the last clean record
    (void)!ftruncate(fileno(box->log), clean);
  }
  box->log_size = clean;
  return box;
}

void lockbox_close(void* h) {
  auto* box = static_cast<Lockbox*>(h);
  if (box->log) {
    fflush(box->log);
    fclose(box->log);
  }
  delete box;
}

int lockbox_put(void* h, const char* key, uint32_t klen, const char* val,
                uint32_t vlen) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  uint64_t voff = box->log_size + 9 + klen;
  if (append_record(box, 1, key, klen, val, vlen) != 0) return -1;
  auto it = box->index.find(std::string(key, klen));
  if (it != box->index.end()) box->live_bytes -= it->second.len;
  box->index[std::string(key, klen)] = Entry{voff, vlen};
  box->live_bytes += vlen;
  return 0;
}

// Returns value length, or -1 if absent.  Caller passes a buffer of
// capacity `cap`; if the value is larger, only the length is returned
// (call again with a big enough buffer).
int64_t lockbox_get(void* h, const char* key, uint32_t klen, char* out,
                    uint64_t cap) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  if (!box->log) return -2;  // failed compact may have left the log closed
  auto it = box->index.find(std::string(key, klen));
  if (it == box->index.end()) return -1;
  if (it->second.len <= cap) {
    fflush(box->log);
    if (fseeko(box->log, it->second.offset, SEEK_SET) != 0) return -2;
    if (!read_exact(box->log, out, it->second.len)) return -2;
  }
  return it->second.len;
}

int lockbox_delete(void* h, const char* key, uint32_t klen) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  auto it = box->index.find(std::string(key, klen));
  if (it == box->index.end()) return 0;
  if (append_record(box, 2, key, klen, nullptr, 0) != 0) return -1;
  box->live_bytes -= it->second.len;
  box->index.erase(it);
  return 0;
}

uint64_t lockbox_count(void* h) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  return box->index.size();
}

// Concatenated [u32 klen][key] for every key with the given prefix, in
// sorted order, written into `out` (capacity `cap`).  Returns required size.
uint64_t lockbox_keys(void* h, const char* prefix, uint32_t plen, char* out,
                      uint64_t cap) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  std::string pfx(prefix, plen);
  uint64_t need = 0;
  auto it = box->index.lower_bound(pfx);
  for (; it != box->index.end(); ++it) {
    if (it->first.compare(0, plen, pfx) != 0) break;
    uint64_t rec = 4 + it->first.size();
    if (need + rec <= cap) {
      uint32_t kl = (uint32_t)it->first.size();
      memcpy(out + need, &kl, 4);
      memcpy(out + need + 4, it->first.data(), kl);
    }
    need += rec;
  }
  return need;
}

int lockbox_flush(void* h) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  return fflush(box->log) == 0 ? 0 : -1;
}

// Rewrite the log with only live records (stop-the-world compaction —
// the maintenance analog of LevelDB's background compaction).
int lockbox_compact(void* h) {
  auto* box = static_cast<Lockbox*>(h);
  std::lock_guard<std::mutex> g(box->mu);
  if (!box->log) return -1;  // a prior failed compact closed the log
  std::string tmp_path = box->path + ".compact";
  FILE* tmp = fopen(tmp_path.c_str(), "wb");
  if (!tmp) return -1;
  std::map<std::string, Entry> new_index;
  uint64_t off = 0;
  fflush(box->log);
  std::vector<char> buf;
  for (auto& kv : box->index) {
    buf.resize(kv.second.len);
    if (fseeko(box->log, kv.second.offset, SEEK_SET) != 0 ||
        !read_exact(box->log, buf.data(), kv.second.len)) {
      fclose(tmp);
      remove(tmp_path.c_str());
      return -1;
    }
    uint8_t op = 1;
    uint32_t klen = (uint32_t)kv.first.size(), vlen = kv.second.len;
    fwrite(&op, 1, 1, tmp);
    fwrite(&klen, 4, 1, tmp);
    fwrite(&vlen, 4, 1, tmp);
    fwrite(kv.first.data(), 1, klen, tmp);
    fwrite(buf.data(), 1, vlen, tmp);
    new_index[kv.first] = Entry{off + 9 + klen, vlen};
    off += 9 + klen + vlen;
  }
  if (fflush(tmp) != 0) {
    fclose(tmp);
    remove(tmp_path.c_str());
    return -1;
  }
  fclose(tmp);
  fclose(box->log);
  if (rename(tmp_path.c_str(), box->path.c_str()) != 0) {
    box->log = open_rw(box->path.c_str());  // may be NULL; append_record guards
    remove(tmp_path.c_str());
    return -1;
  }
  box->log = open_rw(box->path.c_str());
  if (!box->log) return -1;
  box->index = std::move(new_index);
  box->log_size = off;
  return 0;
}

}  // extern "C"
