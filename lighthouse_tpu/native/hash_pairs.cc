// Batched SHA-256 for SSZ Merkleization: one call hashes N consecutive
// 64-byte blocks into N 32-byte digests (the "hash pairs" primitive every
// Merkle layer reduces with).  The hot loop lives in C so per-hash cost is
// the compression function, not interpreter overhead — the role the
// reference fills with ethereum_hashing's assembly/SIMD sha2 backends
// (reference: common crate `ethereum_hashing`, Cargo.toml:119).
//
// Strategy: dlopen the system libcrypto (whose SHA256 dispatches to SHA-NI
// on this hardware) and fall back to a portable scalar implementation when
// it is absent.  Large batches are split across a few worker threads.

#include <cstdint>
#include <cstring>
#include <dlfcn.h>
#include <mutex>
#include <thread>
#include <vector>

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

typedef unsigned char *(*sha256_fn)(const unsigned char *, size_t,
                                    unsigned char *);

static sha256_fn g_openssl_sha256 = nullptr;
static bool g_has_shani = false;
static std::once_flag g_resolve_once;

// ctypes releases the GIL, so first calls can race here — call_once makes
// backend selection safe and visible to all threads.
static void resolve_backends_impl() {
#if defined(__x86_64__)
  unsigned a, b, c, d;
  if (__get_cpuid_count(7, 0, &a, &b, &c, &d)) g_has_shani = (b >> 29) & 1;
#endif
  if (g_has_shani) return;  // fastest path, no libcrypto needed
  // OpenSSL 3.x one-shot SHA256() pays an EVP fetch per call (~10x slower
  // than the compression itself for 64-byte inputs) — it is only the
  // fallback when SHA-NI is absent, still beating the scalar loop.
  const char *names[] = {"libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"};
  for (const char *name : names) {
    void *handle = dlopen(name, RTLD_NOW | RTLD_GLOBAL);
    if (!handle) continue;
    void *sym = dlsym(handle, "SHA256");
    if (sym) {
      g_openssl_sha256 = reinterpret_cast<sha256_fn>(sym);
      return;
    }
  }
}

// ----------------------------------------------------------- scalar fallback

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void compress(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

static void sha256_64byte_scalar(const uint8_t *in, uint8_t *out) {
  uint32_t state[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                       0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  compress(state, in);
  // Padding block for an exactly-64-byte message: 0x80, zeros, bit length 512.
  uint8_t pad[64] = {0};
  pad[0] = 0x80;
  pad[62] = 0x02;  // 512 = 0x0200 big-endian in the final 8 bytes
  compress(state, pad);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(state[i] >> 24);
    out[4 * i + 1] = uint8_t(state[i] >> 16);
    out[4 * i + 2] = uint8_t(state[i] >> 8);
    out[4 * i + 3] = uint8_t(state[i]);
  }
}

// ------------------------------------------------------------ SHA-NI path

#if defined(__x86_64__)
// Canonical Intel SHA-NI two-rounds-per-instruction schedule (the same
// dataflow OpenSSL/blst's asm uses); processes one 64-byte block.
__attribute__((target("sha,sse4.1,ssse3")))
static inline void shani_block(__m128i &STATE0, __m128i &STATE1,
                               const __m128i W_in[4]) {
  const __m128i ABEF_SAVE = STATE0;
  const __m128i CDGH_SAVE = STATE1;
  __m128i MSGS[4] = {W_in[0], W_in[1], W_in[2], W_in[3]};
  __m128i MSG;
  for (int r = 0; r < 16; r++) {
    MSG = _mm_add_epi32(MSGS[r & 3],
                        _mm_loadu_si128(reinterpret_cast<const __m128i *>(&K[4 * r])));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    if (r < 12) {
      __m128i s = _mm_sha256msg1_epu32(MSGS[r & 3], MSGS[(r + 1) & 3]);
      s = _mm_add_epi32(s, _mm_alignr_epi8(MSGS[(r + 3) & 3], MSGS[(r + 2) & 3], 4));
      MSGS[r & 3] = _mm_sha256msg2_epu32(s, MSGS[(r + 3) & 3]);
    }
  }
  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
}

__attribute__((target("sha,sse4.1,ssse3")))
static void hash_range_shani(const uint8_t *in, uint8_t *out, uint64_t begin,
                             uint64_t end) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  // Initial state packed as ABEF / CDGH (sha256rnds2's register layout).
  const __m128i INIT0 = _mm_set_epi32(0x6a09e667, 0xbb67ae85, 0x510e527f, 0x9b05688c);
  const __m128i INIT1 = _mm_set_epi32(0x3c6ef372, 0xa54ff53a, 0x1f83d9ab, 0x5be0cd19);
  // Constant padding block for an exactly-64-byte message (big-endian words).
  const __m128i PAD0 = _mm_set_epi32(0, 0, 0, int(0x80000000));
  const __m128i PADZ = _mm_setzero_si128();
  const __m128i PAD3 = _mm_set_epi32(512, 0, 0, 0);
  const __m128i PAD[4] = {PAD0, PADZ, PADZ, PAD3};
  for (uint64_t i = begin; i < end; i++) {
    const uint8_t *block = in + 64 * i;
    __m128i W[4];
    for (int j = 0; j < 4; j++)
      W[j] = _mm_shuffle_epi8(
          _mm_loadu_si128(reinterpret_cast<const __m128i *>(block + 16 * j)), MASK);
    __m128i S0 = INIT0, S1 = INIT1;
    shani_block(S0, S1, W);
    shani_block(S0, S1, PAD);
    // Unpack ABEF/CDGH back to a..h big-endian bytes.
    uint32_t st[8];
    alignas(16) uint32_t abef[4], cdgh[4];
    _mm_store_si128(reinterpret_cast<__m128i *>(abef), S0);
    _mm_store_si128(reinterpret_cast<__m128i *>(cdgh), S1);
    st[0] = abef[3]; st[1] = abef[2]; st[4] = abef[1]; st[5] = abef[0];
    st[2] = cdgh[3]; st[3] = cdgh[2]; st[6] = cdgh[1]; st[7] = cdgh[0];
    uint8_t *dst = out + 32 * i;
    for (int j = 0; j < 8; j++) {
      dst[4 * j] = uint8_t(st[j] >> 24);
      dst[4 * j + 1] = uint8_t(st[j] >> 16);
      dst[4 * j + 2] = uint8_t(st[j] >> 8);
      dst[4 * j + 3] = uint8_t(st[j]);
    }
  }
}
#endif

// ------------------------------------------------------------------- driver

static void hash_range(const uint8_t *in, uint8_t *out, uint64_t begin,
                       uint64_t end) {
#if defined(__x86_64__)
  if (g_has_shani) {
    hash_range_shani(in, out, begin, end);
    return;
  }
#endif
  if (g_openssl_sha256) {
    for (uint64_t i = begin; i < end; i++)
      g_openssl_sha256(in + 64 * i, 64, out + 32 * i);
  } else {
    for (uint64_t i = begin; i < end; i++)
      sha256_64byte_scalar(in + 64 * i, out + 32 * i);
  }
}

extern "C" int hash_pairs(const uint8_t *in, uint64_t nblocks, uint8_t *out) {
  std::call_once(g_resolve_once, resolve_backends_impl);
  const uint64_t kParallelThreshold = 8192;
  unsigned hw = std::thread::hardware_concurrency();
  if (nblocks < kParallelThreshold || hw < 2) {
    hash_range(in, out, 0, nblocks);
    return 0;
  }
  unsigned nthreads = hw < 8 ? hw : 8;
  std::vector<std::thread> threads;
  uint64_t chunk = (nblocks + nthreads - 1) / nthreads;
  for (unsigned t = 0; t < nthreads; t++) {
    uint64_t begin = t * chunk;
    uint64_t end = begin + chunk < nblocks ? begin + chunk : nblocks;
    if (begin >= end) break;
    threads.emplace_back(hash_range, in, out, begin, end);
  }
  for (auto &th : threads) th.join();
  return 0;
}
