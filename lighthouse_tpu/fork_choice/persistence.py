"""Fork-choice persistence: snapshot/restore across restarts.

Equivalent of the reference's ``beacon_chain/src/persisted_fork_choice.rs``
(+ ``proto_array::SszContainer``): the proto array's DAG, the dense vote
tracker, checkpoints, and balances serialize to one JSON blob stored in the
hot DB, so a restarted node resumes fork choice exactly where it left off
instead of replaying from the anchor.
"""

from __future__ import annotations

import json
from typing import Optional, Tuple

import numpy as np

from .proto_array import ProtoNode


def _hex(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else bytes(b).hex()


def _unhex(s: Optional[str]) -> Optional[bytes]:
    return None if s is None else bytes.fromhex(s)


def _ckpt(c: Tuple[int, bytes]) -> list:
    return [int(c[0]), bytes(c[1]).hex()]


def _unckpt(x) -> Tuple[int, bytes]:
    return (int(x[0]), bytes.fromhex(x[1]))


def fork_choice_to_bytes(fc) -> bytes:
    proto = fc.proto
    nodes = [
        {
            "slot": int(n.slot),
            "root": _hex(n.root),
            "parent": n.parent,
            "state_root": _hex(n.state_root),
            "target_root": _hex(n.target_root),
            "jc": _ckpt(n.justified_checkpoint),
            "fc": _ckpt(n.finalized_checkpoint),
            "ujc": _ckpt(n.unrealized_justified_checkpoint),
            "ufc": _ckpt(n.unrealized_finalized_checkpoint),
            "exec": n.execution_status,
            "exec_hash": _hex(n.execution_block_hash),
            "weight": int(n.weight),
            "best_child": n.best_child,
            "best_descendant": n.best_descendant,
        }
        for n in proto.nodes
    ]
    obj = {
        "version": 1,
        "proto": {
            "nodes": nodes,
            "root_ids": {_hex(r): i for r, i in proto._root_ids.items()},
            "id_to_node": [int(x) for x in proto._id_to_node],
            "jc": _ckpt(proto.justified_checkpoint),
            "fc": _ckpt(proto.finalized_checkpoint),
        },
        "current_slot": int(fc.current_slot),
        "jc": _ckpt(fc.justified_checkpoint),
        "fc": _ckpt(fc.finalized_checkpoint),
        "ujc": _ckpt(fc.unrealized_justified_checkpoint),
        "ufc": _ckpt(fc.unrealized_finalized_checkpoint),
        "votes": {
            "current_root_id": fc.votes.current_root_id.tolist(),
            "next_root_id": fc.votes.next_root_id.tolist(),
            "next_epoch": fc.votes.next_epoch.tolist(),
            "equivocating": fc.votes.equivocating.tolist(),
        },
        "old_balances": fc._old_balances.tolist(),
        "justified_balances": np.asarray(fc.justified_balances).tolist(),
        "proposer_boost_root": _hex(fc.proposer_boost_root),
    }
    return json.dumps(obj).encode()


def restore_fork_choice(fc, raw: bytes) -> None:
    """Overwrite a freshly-anchored ForkChoice with the persisted snapshot."""
    obj = json.loads(raw)
    proto = fc.proto
    nodes = []
    for d in obj["proto"]["nodes"]:
        nodes.append(ProtoNode(
            slot=d["slot"],
            root=_unhex(d["root"]),
            parent=d["parent"],
            state_root=_unhex(d["state_root"]),
            target_root=_unhex(d["target_root"]),
            justified_checkpoint=_unckpt(d["jc"]),
            finalized_checkpoint=_unckpt(d["fc"]),
            unrealized_justified_checkpoint=_unckpt(d["ujc"]),
            unrealized_finalized_checkpoint=_unckpt(d["ufc"]),
            execution_status=d["exec"],
            execution_block_hash=_unhex(d["exec_hash"]),
            weight=d["weight"],
            best_child=d["best_child"],
            best_descendant=d["best_descendant"],
        ))
    proto.nodes = nodes
    proto.indices = {n.root: i for i, n in enumerate(nodes)}
    proto._root_ids = {_unhex(k): v for k, v in obj["proto"]["root_ids"].items()}
    proto._id_to_node = np.asarray(obj["proto"]["id_to_node"], dtype=np.int64)
    proto.justified_checkpoint = _unckpt(obj["proto"]["jc"])
    proto.finalized_checkpoint = _unckpt(obj["proto"]["fc"])

    fc.current_slot = obj["current_slot"]
    fc.justified_checkpoint = _unckpt(obj["jc"])
    fc.finalized_checkpoint = _unckpt(obj["fc"])
    fc.unrealized_justified_checkpoint = _unckpt(obj["ujc"])
    fc.unrealized_finalized_checkpoint = _unckpt(obj["ufc"])
    votes = obj["votes"]
    fc.votes.current_root_id = np.asarray(votes["current_root_id"], dtype=np.int64)
    fc.votes.next_root_id = np.asarray(votes["next_root_id"], dtype=np.int64)
    fc.votes.next_epoch = np.asarray(votes["next_epoch"], dtype=np.int64)
    fc.votes.equivocating = np.asarray(votes["equivocating"], dtype=bool)
    fc._old_balances = np.asarray(obj["old_balances"], dtype=np.int64)
    fc.justified_balances = np.asarray(obj["justified_balances"], dtype=np.int64)
    fc.proposer_boost_root = _unhex(obj["proposer_boost_root"])
