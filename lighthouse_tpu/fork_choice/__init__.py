"""Fork choice: proto-array DAG + spec wrapper.

Equivalent of the reference's ``consensus/proto_array`` and
``consensus/fork_choice`` crates.
"""

from .fork_choice import (
    ForkChoice,
    ForkChoiceError,
    InvalidAttestation,
    InvalidBlock,
    compute_unrealized_checkpoints,
    justified_balances,
)
from .proto_array import (
    ExecutionStatus,
    InvalidAncestorError,
    ProtoArray,
    ProtoArrayError,
    ProtoNode,
    VoteTracker,
)

__all__ = [
    "ForkChoice",
    "ForkChoiceError",
    "InvalidAttestation",
    "InvalidBlock",
    "compute_unrealized_checkpoints",
    "justified_balances",
    "ExecutionStatus",
    "InvalidAncestorError",
    "ProtoArray",
    "ProtoArrayError",
    "ProtoNode",
    "VoteTracker",
]
