"""Spec fork choice on top of the proto-array DAG.

Equivalent of the reference's ``consensus/fork_choice`` crate
(`fork_choice/src/fork_choice.rs`: ``get_head:468``, ``on_block:642``,
``on_attestation:1037``, ``update_time:1104``) — the stateful wrapper that owns
the proto-array, the latest-message vote store, queued attestations, proposer
boost, and justification/finalization bookkeeping.

The unrealized-justification ("pull-up") computation reuses the epoch
processing's participation math but without mutating the state — the
reference computes this from its progressive-balances cache
(`beacon_chain/src/beacon_fork_choice_store.rs``); here the target balances
are one vectorized mask-reduction over the dense participation arrays, which
is the same cost class.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Tuple

import numpy as np

from ..consensus import helpers as h
from ..consensus.per_epoch import (
    EpochArrays,
    _participation_array,
    _unslashed_participating_mask,
    compute_justification_and_finalization,
)
from ..types.spec import GENESIS_EPOCH, TIMELY_TARGET_FLAG_INDEX, ChainSpec
from .proto_array import ExecutionStatus, ProtoArray, ProtoArrayError, VoteTracker

Checkpoint = Tuple[int, bytes]  # (epoch, root)


class DoNotReOrg(Exception):
    """Proposer re-org declined; the message names the failed condition
    (reference ``proto_array_fork_choice.rs`` ``DoNotReOrg``)."""


class ForkChoiceError(Exception):
    pass


class InvalidBlock(ForkChoiceError):
    pass


class InvalidAttestation(ForkChoiceError):
    pass


# ---------------------------------------------------------------------------
# Unrealized justification (spec ``compute_pulled_up_tip``)
# ---------------------------------------------------------------------------


def compute_unrealized_checkpoints(
    state, spec: ChainSpec
) -> Tuple[Checkpoint, Checkpoint]:
    """Run justification/finalization math on the block's post-state *as if*
    the epoch ended now, without mutating the state.

    Mirrors ``weigh_justification_and_finalization``
    (``consensus/per_epoch.py``) on local variables only; reference:
    ``state_processing::per_epoch_processing::weigh_justification_and_finalization``
    driven by ``fork_choice.rs`` unrealized-justification handling.
    """
    current_epoch = h.get_current_epoch(state, spec)
    justified = (
        int(state.current_justified_checkpoint.epoch),
        bytes(state.current_justified_checkpoint.root),
    )
    finalized = (
        int(state.finalized_checkpoint.epoch),
        bytes(state.finalized_checkpoint.root),
    )
    if current_epoch <= GENESIS_EPOCH + 1:
        return justified, finalized

    previous_epoch = h.get_previous_epoch(state, spec)
    arrays = EpochArrays(state, spec)
    increment = spec.effective_balance_increment
    total_active = max(
        increment, int(arrays.effective_balance[arrays.active_mask(current_epoch)].sum())
    )

    if type(state).fork_name == "phase0":
        prev_target, curr_target = _phase0_target_balances(state, arrays, spec)
    else:
        n = arrays.n
        prev_part = _participation_array(state.previous_epoch_participation, n)
        curr_part = _participation_array(state.current_epoch_participation, n)
        prev_mask = _unslashed_participating_mask(
            arrays, prev_part, TIMELY_TARGET_FLAG_INDEX, previous_epoch
        )
        curr_mask = _unslashed_participating_mask(
            arrays, curr_part, TIMELY_TARGET_FLAG_INDEX, current_epoch
        )
        prev_target = max(increment, int(arrays.effective_balance[prev_mask].sum()))
        curr_target = max(increment, int(arrays.effective_balance[curr_mask].sum()))

    _, new_justified, new_finalized = compute_justification_and_finalization(
        bits=state.justification_bits,
        old_previous_justified=(
            int(state.previous_justified_checkpoint.epoch),
            bytes(state.previous_justified_checkpoint.root),
        ),
        old_current_justified=justified,
        previous_epoch=previous_epoch,
        current_epoch=current_epoch,
        previous_boundary_root=lambda: h.get_block_root(state, previous_epoch, spec),
        current_boundary_root=lambda: h.get_block_root(state, current_epoch, spec),
        total_active_balance=total_active,
        previous_target_balance=prev_target,
        current_target_balance=curr_target,
    )
    return (
        new_justified if new_justified is not None else justified,
        new_finalized if new_finalized is not None else finalized,
    )


def _phase0_target_balances(state, arrays: EpochArrays, spec: ChainSpec):
    """Phase0 target balances from pending attestations."""
    increment = spec.effective_balance_increment
    previous_epoch = h.get_previous_epoch(state, spec)
    current_epoch = h.get_current_epoch(state, spec)

    def target_indices(attestations, epoch):
        attestations = list(attestations)
        if not attestations:
            # No attestations ⇒ no boundary-root lookup: a state sitting on
            # the epoch-start slot has no current boundary root yet.
            return []
        out = set()
        boundary = h.get_block_root(state, epoch, spec)
        for a in attestations:
            if bytes(a.data.target.root) != boundary:
                continue
            for i in h.get_attesting_indices(state, a.data, a.aggregation_bits, spec):
                out.add(i)
        return [i for i in out if not arrays.slashed[i]]

    prev = target_indices(state.previous_epoch_attestations, previous_epoch)
    curr = target_indices(state.current_epoch_attestations, current_epoch)
    prev_bal = max(increment, int(arrays.effective_balance[prev].sum())) if prev else increment
    curr_bal = max(increment, int(arrays.effective_balance[curr].sum())) if curr else increment
    return prev_bal, curr_bal


# ---------------------------------------------------------------------------
# Queued attestations
# ---------------------------------------------------------------------------


@dataclass
class QueuedAttestation:
    """Attestation received in its own slot, applied one slot later
    (reference: ``fork_choice.rs`` ``QueuedAttestation``)."""

    slot: int
    attesting_indices: np.ndarray
    block_root: bytes
    target_epoch: int


def justified_balances(state, spec: ChainSpec) -> np.ndarray:
    """Effective balances of validators active at the justified state's
    current epoch; zeros elsewhere (reference: ``JustifiedBalances``,
    ``beacon_chain/src/beacon_fork_choice_store.rs``)."""
    epoch = h.get_current_epoch(state, spec)
    arrays = EpochArrays(state, spec)
    return np.where(arrays.active_mask(epoch), arrays.effective_balance, 0).astype(
        np.int64
    )


# ---------------------------------------------------------------------------
# ForkChoice
# ---------------------------------------------------------------------------


def _locked(fn):
    """Serialize a public ForkChoice entry point on the instance lock.

    The chain calls fork choice from several threads at once (processor
    workers importing blocks and applying attestations, sync lookup threads
    chasing parents, duty loops producing) and the proto-array walk is
    multi-step mutable arithmetic: two interleaved ``get_head`` calls
    double-consume vote deltas and drive node weights negative (observed as
    intermittent ``ProtoArrayError: negative weight`` under the scenario
    soak).  The reference wraps fork choice in an ``RwLock`` for exactly
    this reason; an RLock because the entry points nest (``on_block`` ->
    ``update_time``)."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


class ForkChoice:
    """Stateful fork choice: proto-array + votes + time + checkpoints."""

    def __init__(
        self,
        *,
        spec: ChainSpec,
        genesis_block_root: bytes,
        genesis_state,
        anchor_slot: Optional[int] = None,
    ):
        self.spec = spec
        self._lock = threading.RLock()  # see _locked
        anchor_slot = int(genesis_state.slot) if anchor_slot is None else anchor_slot
        anchor_epoch = anchor_slot // spec.slots_per_epoch
        # Spec ``get_forkchoice_store`` / reference ``ForkChoice::from_anchor``:
        # the anchor block IS the initial justified and finalized checkpoint —
        # the state's own checkpoint roots predate the anchor and are not in
        # the proto-array (checkpoint sync starts mid-chain).
        jc: Checkpoint = (anchor_epoch, genesis_block_root)
        fc: Checkpoint = (anchor_epoch, genesis_block_root)
        self.justified_checkpoint: Checkpoint = jc
        self.finalized_checkpoint: Checkpoint = fc
        self.unrealized_justified_checkpoint: Checkpoint = jc
        self.unrealized_finalized_checkpoint: Checkpoint = fc
        self.proposer_boost_root: Optional[bytes] = None
        self.current_slot = anchor_slot
        self.queued_attestations: List[QueuedAttestation] = []
        self.votes = VoteTracker()
        self._old_balances = np.zeros(0, dtype=np.int64)
        self.justified_balances = justified_balances(genesis_state, spec)

        self.proto = ProtoArray(
            slots_per_epoch=spec.slots_per_epoch,
            justified_checkpoint=jc,
            finalized_checkpoint=fc,
        )
        self.proto.on_block(
            slot=anchor_slot,
            root=genesis_block_root,
            parent_root=None,
            state_root=genesis_state.hash_tree_root(),
            target_root=genesis_block_root,
            justified_checkpoint=jc,
            finalized_checkpoint=fc,
            unrealized_justified_checkpoint=jc,
            unrealized_finalized_checkpoint=fc,
            execution_status=ExecutionStatus.IRRELEVANT,
            current_slot=anchor_slot,
        )
        # Maps justified root -> state for balance lookup; caller-provided.
        self._justified_state_provider = None

    def set_justified_state_provider(self, fn) -> None:
        """``fn(root: bytes) -> state`` used to refresh justified balances when
        the justified checkpoint advances (the reference reads these through
        ``ForkChoiceStore``; the chain provides them from its state cache)."""
        self._justified_state_provider = fn

    # ------------------------------------------------------------------ time

    @_locked
    def update_time(self, current_slot: int) -> None:
        """Reference: ``fork_choice.rs:1104`` ``update_time`` (spec
        ``on_tick_per_slot``), computed as ONE jump: per-slot iteration is
        equivalent because checkpoint promotion is a monotone max of the
        (unchanged) unrealized values and the queued-attestation dequeue at
        the final slot subsumes every intermediate dequeue.  The naive loop
        walks 10M+ slots on a wall-clock node booting from an old anchor —
        a multi-second stall inside block import."""
        if current_slot <= self.current_slot:
            return
        spe = self.spec.slots_per_epoch
        crossed_epoch = current_slot // spe > self.current_slot // spe
        self.current_slot = current_slot
        self.proposer_boost_root = None
        if crossed_epoch:
            self._update_checkpoints(
                self.unrealized_justified_checkpoint,
                self.unrealized_finalized_checkpoint,
            )
        self._process_queued_attestations()

    def _process_queued_attestations(self) -> None:
        remaining = []
        for qa in self.queued_attestations:
            if qa.slot < self.current_slot:
                self._apply_latest_messages(
                    qa.attesting_indices, qa.block_root, qa.target_epoch
                )
            else:
                remaining.append(qa)
        self.queued_attestations = remaining

    def _update_checkpoints(
        self, justified: Checkpoint, finalized: Checkpoint
    ) -> None:
        if justified[0] > self.justified_checkpoint[0]:
            self.justified_checkpoint = justified
            self._refresh_justified_balances()
        if finalized[0] > self.finalized_checkpoint[0]:
            self.finalized_checkpoint = finalized

    def _refresh_justified_balances(self) -> None:
        if self._justified_state_provider is None:
            return
        state = self._justified_state_provider(self.justified_checkpoint[1])
        if state is not None:
            self.justified_balances = justified_balances(state, self.spec)

    # ----------------------------------------------------------------- block

    @_locked
    def on_block(
        self,
        *,
        current_slot: int,
        block,
        block_root: bytes,
        state,
        payload_verification_status: str = ExecutionStatus.IRRELEVANT,
        block_delay_seconds: Optional[float] = None,
    ) -> None:
        """Reference: ``fork_choice.rs:642`` ``on_block``.

        ``state`` is the block's post-state.  ``block_delay_seconds`` (time
        since slot start when received) drives proposer boost.
        """
        self.update_time(current_slot)
        slot = int(block.slot)
        if slot > current_slot:
            raise InvalidBlock(f"block slot {slot} is in the future (now {current_slot})")
        f_epoch, f_root = self.finalized_checkpoint
        finalized_slot = f_epoch * self.spec.slots_per_epoch
        if slot <= finalized_slot:
            raise InvalidBlock(f"block slot {slot} not beyond finalized slot {finalized_slot}")
        parent_root = bytes(block.parent_root)
        if not self.proto.contains_block(parent_root):
            raise InvalidBlock(f"parent {parent_root.hex()[:16]} unknown")
        if f_epoch > 0 and self.proto.ancestor_at_slot(parent_root, finalized_slot) != f_root:
            raise InvalidBlock("block does not descend from finalized root")

        state_justified = (
            int(state.current_justified_checkpoint.epoch),
            bytes(state.current_justified_checkpoint.root),
        )
        state_finalized = (
            int(state.finalized_checkpoint.epoch),
            bytes(state.finalized_checkpoint.root),
        )
        unrealized_j, unrealized_f = compute_unrealized_checkpoints(state, self.spec)
        # Spec ``compute_pulled_up_tip``: unrealized store checkpoints always
        # advance; realized ones advance from the state, and for blocks from
        # prior epochs the unrealized values count as realized.
        if unrealized_j[0] > self.unrealized_justified_checkpoint[0]:
            self.unrealized_justified_checkpoint = unrealized_j
        if unrealized_f[0] > self.unrealized_finalized_checkpoint[0]:
            self.unrealized_finalized_checkpoint = unrealized_f
        self._update_checkpoints(state_justified, state_finalized)
        block_epoch = slot // self.spec.slots_per_epoch
        current_epoch = current_slot // self.spec.slots_per_epoch
        if block_epoch < current_epoch:
            self._update_checkpoints(unrealized_j, unrealized_f)

        # Proposer boost: first timely block for the current slot.
        if (
            slot == current_slot
            and self.proposer_boost_root is None
            and block_delay_seconds is not None
            and block_delay_seconds
            < self.spec.seconds_per_slot / self.spec.intervals_per_slot
        ):
            self.proposer_boost_root = block_root

        target_root = (
            block_root
            if slot % self.spec.slots_per_epoch == 0
            else self.proto.ancestor_at_slot(
                parent_root, block_epoch * self.spec.slots_per_epoch
            )
        )
        body = block.body
        exec_hash = None
        if hasattr(body, "execution_payload"):
            exec_hash = bytes(body.execution_payload.block_hash)
        self.proto.on_block(
            slot=slot,
            root=block_root,
            parent_root=parent_root,
            state_root=bytes(block.state_root),
            target_root=target_root,
            justified_checkpoint=state_justified,
            finalized_checkpoint=state_finalized,
            unrealized_justified_checkpoint=max(unrealized_j, state_justified),
            unrealized_finalized_checkpoint=max(unrealized_f, state_finalized),
            execution_status=payload_verification_status
            if exec_hash is not None and exec_hash != b"\x00" * 32
            else ExecutionStatus.IRRELEVANT,
            execution_block_hash=exec_hash,
            current_slot=current_slot,
        )

    # ----------------------------------------------------------- attestation

    @_locked
    def on_attestation(
        self,
        *,
        current_slot: int,
        attestation_slot: int,
        attesting_indices: Iterable[int],
        beacon_block_root: bytes,
        target_epoch: int,
        target_root: bytes,
        is_from_block: bool = False,
    ) -> None:
        """Reference: ``fork_choice.rs:1037`` ``on_attestation``.

        The caller has already signature-verified and indexed the attestation
        (the chain's attestation pipeline).  This applies LMD-GHOST votes.
        """
        self.update_time(current_slot)
        indices = np.asarray(list(attesting_indices), dtype=np.int64)
        if not is_from_block:
            current_epoch = current_slot // self.spec.slots_per_epoch
            if target_epoch not in (current_epoch, max(current_epoch - 1, 0)):
                raise InvalidAttestation(
                    f"target epoch {target_epoch} not current or previous"
                )
            if attestation_slot > current_slot:
                raise InvalidAttestation("attestation from the future")
        if attestation_slot // self.spec.slots_per_epoch != target_epoch:
            raise InvalidAttestation("attestation slot not in target epoch")
        block = self.proto.get_block(beacon_block_root)
        if block is None:
            raise InvalidAttestation("attestation head block unknown")
        if block.slot > attestation_slot:
            raise InvalidAttestation("attestation head newer than attestation slot")
        if target_root:
            # Spec ``validate_on_attestation``: the target block must be known
            # and be the checkpoint block of the attested head.
            if not self.proto.contains_block(target_root):
                raise InvalidAttestation("attestation target block unknown")
            epoch_start = target_epoch * self.spec.slots_per_epoch
            if self.proto.ancestor_at_slot(beacon_block_root, epoch_start) != target_root:
                raise InvalidAttestation("target root not an ancestor of head block")

        if attestation_slot >= current_slot and not is_from_block:
            self.queued_attestations.append(
                QueuedAttestation(
                    slot=attestation_slot,
                    attesting_indices=indices,
                    block_root=beacon_block_root,
                    target_epoch=target_epoch,
                )
            )
        else:
            self._apply_latest_messages(indices, beacon_block_root, target_epoch)

    def _apply_latest_messages(
        self, indices: np.ndarray, block_root: bytes, target_epoch: int
    ) -> None:
        if len(indices) == 0:
            return
        self.votes.ensure(int(indices.max()) + 1)
        rid = self.proto.root_id(block_root)
        newer = target_epoch > self.votes.next_epoch[indices]
        fresh = self.votes.next_epoch[indices] == -1
        m = (newer | fresh) & ~self.votes.equivocating[indices]
        upd = indices[m]
        self.votes.next_root_id[upd] = rid
        self.votes.next_epoch[upd] = target_epoch

    @_locked
    def on_attester_slashing(self, attesting_indices: Iterable[int]) -> None:
        """Mark equivocating validators; their weight is removed at the next
        ``get_head`` (reference: ``fork_choice.rs`` ``on_attester_slashing``)."""
        indices = np.asarray(list(attesting_indices), dtype=np.int64)
        if len(indices) == 0:
            return
        self.votes.ensure(int(indices.max()) + 1)
        self.votes.equivocating[indices] = True

    # ------------------------------------------------------------------ head

    @_locked
    def get_head(self, current_slot: Optional[int] = None) -> bytes:
        """Reference: ``fork_choice.rs:468`` ``get_head`` →
        ``proto_array_fork_choice`` delta computation + weight walk."""
        if current_slot is not None:
            self.update_time(current_slot)
        new_balances = self.justified_balances
        deltas = self.proto.compute_deltas(self.votes, self._old_balances, new_balances)
        boost = (None, 0)
        if self.proposer_boost_root is not None:
            total = int(new_balances.sum())
            committee_weight = total // self.spec.slots_per_epoch
            boost = (
                self.proposer_boost_root,
                committee_weight * self.spec.proposer_score_boost // 100,
            )
        self.proto.apply_score_changes(
            deltas,
            justified_checkpoint=self.justified_checkpoint,
            finalized_checkpoint=self.finalized_checkpoint,
            current_slot=self.current_slot,
            new_proposer_boost=boost,
        )
        self._old_balances = new_balances
        return self.proto.find_head(self.justified_checkpoint[1], self.current_slot)

    @_locked
    def get_proposer_head(
        self,
        current_slot: int,
        canonical_head: bytes,
        *,
        re_org_head_threshold: int = 20,
        re_org_parent_threshold: int = 160,
        max_epochs_since_finalization: int = 2,
        disallowed_offsets: tuple = (),
    ) -> bytes:
        """Late-block re-org decision for the proposer of ``current_slot``
        (reference ``proto_array_fork_choice.rs:508`` ``get_proposer_head``):
        returns the PARENT root to build on when the canonical head is a
        weakly-attested late block worth orphaning, else raises
        ``DoNotReOrg`` with the failed condition.  Thresholds are percent of
        one committee's weight (chain_config.rs:6-7 defaults: head < 20 %,
        parent > 160 %)."""
        spe = self.spec.slots_per_epoch
        head = self.proto.get_block(canonical_head)
        if head is None or head.parent is None:
            raise DoNotReOrg("missing head or parent node")
        parent = self.proto.nodes[head.parent]

        re_org_block_slot = head.slot + 1
        # Finalization distance (head's unrealized view).
        fin_cp = head.unrealized_finalized_checkpoint or head.finalized_checkpoint
        epochs_since_finalization = (
            re_org_block_slot // spe - int(fin_cp[0])
        )
        if epochs_since_finalization > max_epochs_since_finalization:
            raise DoNotReOrg(
                f"chain not finalizing ({epochs_since_finalization} epochs)"
            )
        if parent.slot + 1 != head.slot:
            raise DoNotReOrg("parent is not a single slot behind the head")
        if re_org_block_slot % spe == 0:
            raise DoNotReOrg("shuffling unstable at the epoch boundary")
        if (re_org_block_slot % spe) in disallowed_offsets:
            raise DoNotReOrg(f"slot offset {re_org_block_slot % spe} disallowed")
        # FFG competitiveness: orphaning the head must not lose justification.
        if (parent.unrealized_justified_checkpoint
                != head.unrealized_justified_checkpoint
                or parent.unrealized_finalized_checkpoint
                != head.unrealized_finalized_checkpoint):
            raise DoNotReOrg("justification/finalization not competitive")
        # Single-slot re-org only (prevents cascades during asynchrony).
        if head.slot + 1 != current_slot:
            raise DoNotReOrg("head is not from the previous slot")

        committee_weight = int(self.justified_balances.sum()) // spe
        head_threshold = committee_weight * re_org_head_threshold // 100
        parent_threshold = committee_weight * re_org_parent_threshold // 100
        if head.weight >= head_threshold:
            raise DoNotReOrg(
                f"head not weak ({head.weight} >= {head_threshold})"
            )
        if parent.weight <= parent_threshold:
            raise DoNotReOrg(
                f"parent not strong ({parent.weight} <= {parent_threshold})"
            )
        return parent.root

    # -------------------------------------------------------- optimistic sync

    @_locked
    def on_valid_execution_payload(self, block_root: bytes) -> None:
        self.proto.on_valid_execution_payload(block_root)

    @_locked
    def on_invalid_execution_payload(
        self, block_root: bytes, latest_valid_hash: Optional[bytes] = None
    ) -> None:
        self.proto.on_invalid_execution_payload(block_root, latest_valid_hash)

    # ----------------------------------------------------------------- misc

    def locked(self):
        """The instance lock, for callers doing multi-step reads straight
        off ``self.proto`` (HTTP debug dumps, migration walks): ``prune``
        rebuilds the node array in place, so an unlocked walker can read
        parent indices mid-remap."""
        return self._lock

    @_locked
    def ancestor_at_slot(self, root: bytes, slot: int) -> Optional[bytes]:
        """Locked canonical-ancestor walk (the ``block_root_at_slot`` and
        migration seam — see :meth:`locked`)."""
        return self.proto.ancestor_at_slot(root, slot)

    @_locked
    def contains_block(self, root: bytes) -> bool:
        return self.proto.contains_block(root)

    @_locked
    def is_descendant(self, ancestor: bytes, descendant: bytes) -> bool:
        return self.proto.is_descendant(ancestor, descendant)

    @_locked
    def prune(self) -> None:
        self.proto.prune(self.finalized_checkpoint[1])
