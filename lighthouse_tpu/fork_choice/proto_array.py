"""Proto-array fork choice DAG.

TPU-first re-design of the reference's ``consensus/proto_array`` crate
(`proto_array/src/proto_array.rs:369` ``on_block``,
`proto_array/src/proto_array_fork_choice.rs:900` ``compute_deltas``).

Key departures from the reference:

- **Votes are dense arrays, not per-validator structs.** The reference keeps a
  ``Vec<VoteTracker>`` and walks it in a scalar loop; here votes live in three
  numpy arrays (``current_root_id``, ``next_root_id``, ``next_epoch``) indexed
  by validator, and ``compute_deltas`` is a vectorized scatter-add
  (``np.add.at`` over balances).  At 1M validators this is the hot loop of
  ``get_head`` and maps directly onto an XLA ``segment_sum`` if it ever needs
  to move on-device; the node-count-sized work (weight back-propagation) stays
  a host loop since the block DAG is small (hundreds of nodes).
- **Roots are interned.** Block roots are mapped to stable small integer ids
  (append-only table) so the vote arrays hold int32s instead of 32-byte
  objects; ids survive pruning even when node indices shift.

Semantics follow the Ethereum consensus spec (Deneb-era fork choice, with
unrealized-justification viability and proposer boost), which is what the
reference implements.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

NONE = -1  # sentinel for "no index" in int arrays


class ExecutionStatus:
    """Execution-payload status of a block, for optimistic sync
    (reference: ``proto_array/src/proto_array.rs`` ``ExecutionStatus``)."""

    VALID = "valid"
    INVALID = "invalid"
    OPTIMISTIC = "optimistic"  # payload present, EL verdict unknown
    IRRELEVANT = "irrelevant"  # pre-merge block (no payload)


@dataclass
class ProtoNode:
    slot: int
    root: bytes
    parent: Optional[int]  # index into ProtoArray.nodes
    state_root: bytes
    target_root: bytes
    justified_checkpoint: tuple  # (epoch, root)
    finalized_checkpoint: tuple
    unrealized_justified_checkpoint: tuple
    unrealized_finalized_checkpoint: tuple
    execution_status: str = ExecutionStatus.IRRELEVANT
    execution_block_hash: Optional[bytes] = None
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


class ProtoArrayError(Exception):
    pass


class InvalidAncestorError(ProtoArrayError):
    """Payload invalidation named an ancestor that is already VALID."""


@dataclass
class VoteTracker:
    """Dense latest-message store (reference keeps ``Vec<VoteTracker>``)."""

    current_root_id: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int64)
    )
    next_root_id: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=np.int64))
    next_epoch: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=np.int64))
    equivocating: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=bool))

    def ensure(self, n: int) -> None:
        cur = len(self.current_root_id)
        if n <= cur:
            return
        grow = n - cur
        self.current_root_id = np.concatenate(
            [self.current_root_id, np.full(grow, NONE, dtype=np.int64)]
        )
        self.next_root_id = np.concatenate(
            [self.next_root_id, np.full(grow, NONE, dtype=np.int64)]
        )
        self.next_epoch = np.concatenate(
            [self.next_epoch, np.full(grow, NONE, dtype=np.int64)]
        )
        self.equivocating = np.concatenate([self.equivocating, np.zeros(grow, dtype=bool)])


class ProtoArray:
    """The block DAG with cached weights and best-descendant links."""

    def __init__(
        self,
        *,
        slots_per_epoch: int,
        justified_checkpoint: tuple,
        finalized_checkpoint: tuple,
        prune_threshold: int = 256,
    ):
        self.slots_per_epoch = slots_per_epoch
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        self.prune_threshold = prune_threshold
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[bytes, int] = {}
        # Root interning: id -> root is implicit (append order); root -> id:
        self._root_ids: Dict[bytes, int] = {}
        # root_id -> node index (NONE when pruned/unknown); grows with ids.
        self._id_to_node: np.ndarray = np.empty(0, dtype=np.int64)
        self.previous_proposer_boost: tuple = (None, 0)  # (root, score)

    # ------------------------------------------------------------ interning

    def root_id(self, root: bytes) -> int:
        rid = self._root_ids.get(root)
        if rid is None:
            rid = len(self._root_ids)
            self._root_ids[root] = rid
            self._id_to_node = np.concatenate(
                [self._id_to_node, np.full(1, NONE, dtype=np.int64)]
            )
        return rid

    def _set_id_mapping(self, root: bytes, node_index: int) -> None:
        rid = self.root_id(root)  # may reallocate _id_to_node; intern first
        self._id_to_node[rid] = node_index

    # ------------------------------------------------------------ mutation

    def on_block(
        self,
        *,
        slot: int,
        root: bytes,
        parent_root: Optional[bytes],
        state_root: bytes,
        target_root: bytes,
        justified_checkpoint: tuple,
        finalized_checkpoint: tuple,
        unrealized_justified_checkpoint: tuple,
        unrealized_finalized_checkpoint: tuple,
        execution_status: str = ExecutionStatus.IRRELEVANT,
        execution_block_hash: Optional[bytes] = None,
        current_slot: Optional[int] = None,
    ) -> None:
        """Register a block (reference: ``proto_array.rs:369``). Idempotent."""
        if root in self.indices:
            return
        parent = self.indices.get(parent_root) if parent_root is not None else None
        node_index = len(self.nodes)
        node = ProtoNode(
            slot=slot,
            root=root,
            parent=parent,
            state_root=state_root,
            target_root=target_root,
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            unrealized_justified_checkpoint=unrealized_justified_checkpoint,
            unrealized_finalized_checkpoint=unrealized_finalized_checkpoint,
            execution_status=execution_status,
            execution_block_hash=execution_block_hash,
        )
        # A block whose payload was already known invalid cannot enter.
        if parent is not None and self.nodes[parent].execution_status == ExecutionStatus.INVALID:
            node.execution_status = ExecutionStatus.INVALID
        self.nodes.append(node)
        self.indices[root] = node_index
        self._set_id_mapping(root, node_index)
        if parent is not None:
            self._maybe_update_best_child_and_descendant(
                parent, node_index, current_slot if current_slot is not None else slot
            )

    def apply_score_changes(
        self,
        deltas: np.ndarray,
        *,
        justified_checkpoint: tuple,
        finalized_checkpoint: tuple,
        current_slot: int,
        new_proposer_boost: tuple = (None, 0),
    ) -> None:
        """Back-propagate vote deltas and refresh best-child/descendant links
        (reference: ``proto_array.rs:212`` ``apply_score_changes``).

        ``deltas`` is one int64 per node.  Reference semantics preserved
        exactly: the zero-hash root (genesis alias in scripted tests) is
        skipped; a payload-INVALID node's delta is replaced with ``-weight``
        so its weight pins to zero and the removal propagates to ancestors
        (vote deltas ON the invalid node are discarded, not propagated);
        proposer boost is never applied to, nor removed from, invalid
        nodes."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError(
                f"delta length {len(deltas)} != node count {len(self.nodes)}"
            )
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint

        prev_root, prev_score = self.previous_proposer_boost
        boost_root, boost_score = new_proposer_boost
        applied_boost = 0  # recorded only if the boost node was credited
        zero_root = b"\x00" * 32

        # Children always have higher indices than parents (append order), so a
        # single reverse pass both applies deltas and propagates to parents.
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.root == zero_root:
                continue
            is_invalid = node.execution_status == ExecutionStatus.INVALID
            if is_invalid:
                d = -node.weight
            else:
                d = int(deltas[i])
                if prev_root is not None and prev_root == node.root:
                    d -= prev_score
                if boost_root is not None and boost_root == node.root and boost_score:
                    d += boost_score
                    applied_boost = boost_score
            if is_invalid:
                node.weight = 0
            else:
                node.weight += d
                if node.weight < 0:
                    raise ProtoArrayError(f"negative weight at node {i}")
            if node.parent is not None:
                deltas[node.parent] += d
        self.previous_proposer_boost = (
            (boost_root, applied_boost) if boost_root else (None, 0)
        )
        for i in range(len(self.nodes) - 1, -1, -1):
            parent = self.nodes[i].parent
            if parent is not None:
                self._maybe_update_best_child_and_descendant(parent, i, current_slot)

    def find_head(self, justified_root: bytes, current_slot: int) -> bytes:
        """Walk best-descendant from the justified root
        (reference: ``proto_array.rs`` ``find_head``)."""
        ji = self.indices.get(justified_root)
        if ji is None:
            raise ProtoArrayError(f"justified root unknown: {justified_root.hex()[:16]}")
        justified = self.nodes[ji]
        if justified.execution_status == ExecutionStatus.INVALID:
            # No valid descendant of an invalid justified block can exist:
            # fork choice is broken until a new justified root is set
            # (reference find_head, proto_array.rs:712).
            raise ProtoArrayError("justified block has an invalid payload")
        best = justified.best_descendant
        node = self.nodes[best] if best is not None else justified
        if not self._node_is_viable_for_head(node, current_slot):
            raise ProtoArrayError(
                "best descendant is not viable for head (justified "
                f"{self.justified_checkpoint}, node jc {node.justified_checkpoint})"
            )
        return node.root

    # ------------------------------------------------------------ viability

    def _voting_source(self, node: ProtoNode, current_slot: int) -> tuple:
        """Spec ``get_voting_source``: blocks from prior epochs are 'pulled up'
        to their unrealized justification."""
        current_epoch = current_slot // self.slots_per_epoch
        node_epoch = node.slot // self.slots_per_epoch
        if current_epoch > node_epoch:
            # Unrealized justification may be untracked (reference keeps an
            # Option and falls back to the realized checkpoint).
            if node.unrealized_justified_checkpoint is not None:
                return node.unrealized_justified_checkpoint
        return node.justified_checkpoint

    def _node_is_viable_for_head(self, node: ProtoNode, current_slot: int) -> bool:
        """Spec ``filter_block_tree`` viability; reference
        ``proto_array.rs`` ``node_is_viable_for_head``."""
        if node.execution_status == ExecutionStatus.INVALID:
            return False
        j_epoch, _ = self.justified_checkpoint
        f_epoch, f_root = self.finalized_checkpoint
        current_epoch = current_slot // self.slots_per_epoch
        voting_source = self._voting_source(node, current_slot)
        correct_justified = (
            j_epoch == 0
            or voting_source[0] == j_epoch
            # spec allowance: voting source within 2 epochs of current
            or voting_source[0] + 2 >= current_epoch
        )
        if not correct_justified:
            return False
        if f_epoch == 0:
            return True
        return self.is_finalized_checkpoint_or_descendant(node.root)

    def _node_leads_to_viable_head(self, node: ProtoNode, current_slot: int) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(
                self.nodes[node.best_descendant], current_slot
            )
        return self._node_is_viable_for_head(node, current_slot)

    def _maybe_update_best_child_and_descendant(
        self, parent_index: int, child_index: int, current_slot: int
    ) -> None:
        """Reference: ``proto_array.rs`` ``maybe_update_best_child_and_descendant``."""
        child = self.nodes[child_index]
        parent = self.nodes[parent_index]
        child_leads = self._node_leads_to_viable_head(child, current_slot)
        child_best_desc = (
            child.best_descendant if child.best_descendant is not None else child_index
        )

        def make_best() -> None:
            parent.best_child = child_index
            parent.best_descendant = child_best_desc

        def unset() -> None:
            parent.best_child = None
            parent.best_descendant = None

        if parent.best_child is None:
            if child_leads:
                make_best()
            return
        if parent.best_child == child_index:
            if not child_leads:
                unset()
            else:
                make_best()  # refresh best_descendant link
            return
        best = self.nodes[parent.best_child]
        best_leads = self._node_leads_to_viable_head(best, current_slot)
        if child_leads and not best_leads:
            make_best()
        elif child_leads and best_leads:
            if child.weight > best.weight or (
                child.weight == best.weight and child.root >= best.root
            ):
                make_best()
        elif not child_leads and not best_leads:
            # keep current (both non-viable); reference keeps the stale link too
            pass

    # ------------------------------------------------------------ ancestry

    def _ancestor_at_slot(self, node: ProtoNode, slot: int) -> Optional[bytes]:
        while node.slot > slot:
            if node.parent is None:
                return node.root
            node = self.nodes[node.parent]
        return node.root

    def ancestor_at_slot(self, root: bytes, slot: int) -> Optional[bytes]:
        idx = self.indices.get(root)
        if idx is None:
            return None
        return self._ancestor_at_slot(self.nodes[idx], slot)

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        ai = self.indices.get(ancestor_root)
        di = self.indices.get(descendant_root)
        if ai is None or di is None:
            return False
        return (
            self._ancestor_at_slot(self.nodes[di], self.nodes[ai].slot) == ancestor_root
        )

    def contains_block(self, root: bytes) -> bool:
        return root in self.indices

    def get_block(self, root: bytes) -> Optional[ProtoNode]:
        idx = self.indices.get(root)
        return self.nodes[idx] if idx is not None else None

    # ----------------------------------------------------- optimistic sync

    def on_valid_execution_payload(self, root: bytes) -> None:
        """Mark a block's payload VALID; validity propagates to all ancestors
        (reference: ``proto_array.rs`` ``propagate_execution_payload_validation``)."""
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status == ExecutionStatus.INVALID:
                raise InvalidAncestorError(
                    f"marking VALID but ancestor {node.root.hex()[:16]} is INVALID"
                )
            if node.execution_status in (ExecutionStatus.VALID, ExecutionStatus.IRRELEVANT):
                break
            node.execution_status = ExecutionStatus.VALID
            idx = node.parent

    def execution_block_hash_to_beacon_block_root(
        self, block_hash: bytes
    ) -> Optional[bytes]:
        """Latest block whose payload hash matches (reference searches nodes
        in reverse — most recent wins)."""
        for node in reversed(self.nodes):
            if node.execution_block_hash == block_hash:
                return node.root
        return None

    def is_finalized_checkpoint_or_descendant(self, root: bytes) -> bool:
        """Reference ``proto_array.rs:1024``: checkpoint shortcuts first,
        then an ancestry walk down to the finalized slot."""
        f_epoch, f_root = self.finalized_checkpoint
        f_slot = f_epoch * self.slots_per_epoch
        idx = self.indices.get(root)
        if idx is None:
            return False
        node = self.nodes[idx]
        for cp in (
            node.finalized_checkpoint,
            node.justified_checkpoint,
            node.unrealized_finalized_checkpoint,
            node.unrealized_justified_checkpoint,
        ):
            if cp is not None and tuple(cp) == tuple(self.finalized_checkpoint):
                return True
        while True:
            if node.slot <= f_slot:
                return node.root == f_root
            if node.parent is None:
                return False
            node = self.nodes[node.parent]

    def on_invalid_execution_payload(
        self,
        head_root: bytes,
        latest_valid_hash: Optional[bytes] = None,
        always_invalidate_head: bool = True,
    ) -> None:
        """Mark payloads INVALID (reference:
        ``propagate_execution_payload_invalidation``, proto_array.rs:499).

        ``latest_valid_hash=None`` is the reference's ``InvalidateOne``:
        only ``head_root`` and its descendants are invalidated, never
        ancestors.  With a hash, ancestors between head and the latest valid
        ancestor are invalidated — but ONLY if that ancestor is known and is
        a finalized-checkpoint descendant; an unknown/junk hash invalidates
        just the head (the alternative — invalidating every ancestor — could
        brand the justified checkpoint invalid and halt the client)."""
        start = self.indices.get(head_root)
        if start is None:
            raise ProtoArrayError("invalidated block unknown")
        invalid: set = set()

        lva_root = (
            self.execution_block_hash_to_beacon_block_root(latest_valid_hash)
            if latest_valid_hash is not None
            else None
        )
        lva_is_descendant = lva_root is not None and (
            self.is_descendant(lva_root, head_root)
            and self.is_finalized_checkpoint_or_descendant(lva_root)
        )

        # Step 1: walk ancestors from the head, collecting invalidations.
        idx: Optional[int] = start
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status == ExecutionStatus.IRRELEVANT:
                break
            if not lva_is_descendant and node.root != head_root:
                break
            if (
                latest_valid_hash is not None
                and node.execution_block_hash == latest_valid_hash
            ):
                # The latest valid ancestor itself: scrub best links that
                # point into the invalidated set, then stop.
                if node.best_child in invalid:
                    node.best_child = None
                if node.best_descendant in invalid:
                    node.best_descendant = None
                break
            if (
                node.root != head_root
                or always_invalidate_head
                or lva_is_descendant
            ):
                if node.execution_status == ExecutionStatus.VALID:
                    raise InvalidAncestorError(
                        f"invalidation reaches VALID block {node.root.hex()[:16]}"
                    )
                if node.execution_status == ExecutionStatus.OPTIMISTIC:
                    invalid.add(idx)
                    node.execution_status = ExecutionStatus.INVALID
                    node.best_child = None
                    node.best_descendant = None
                # already INVALID: keep walking so ancestors update too
            idx = node.parent

        # Step 2: forward sweep — descendants of any invalidated node are
        # invalid (children always have higher indices than parents).
        start_root = lva_root if lva_is_descendant else head_root
        si = self.indices[start_root]
        for i in range(si + 1, len(self.nodes)):
            node = self.nodes[i]
            if node.parent in invalid:
                if node.execution_status == ExecutionStatus.VALID:
                    raise InvalidAncestorError(
                        f"VALID descendant {node.root.hex()[:16]} of invalid block"
                    )
                if node.execution_status == ExecutionStatus.IRRELEVANT:
                    raise ProtoArrayError(
                        f"irrelevant (pre-merge) descendant {node.root.hex()[:16]} "
                        "of a post-merge block"
                    )
                node.execution_status = ExecutionStatus.INVALID
                node.best_child = None
                node.best_descendant = None
                invalid.add(i)

    # -------------------------------------------------------------- prune

    def prune(self, finalized_root: bytes) -> List[ProtoNode]:
        """Drop nodes before the finalized root once enough have accumulated
        (reference: ``proto_array.rs`` ``maybe_prune``). Returns pruned nodes."""
        fi = self.indices.get(finalized_root)
        if fi is None:
            raise ProtoArrayError("finalized root unknown")
        if fi < self.prune_threshold:
            return []
        keep = self.nodes[fi:]
        pruned = self.nodes[:fi]
        shift = fi
        remap: Dict[int, int] = {old: old - shift for old in range(fi, len(self.nodes))}
        for node in keep:
            node.parent = remap.get(node.parent) if node.parent is not None else None
            node.best_child = (
                remap.get(node.best_child) if node.best_child is not None else None
            )
            node.best_descendant = (
                remap.get(node.best_descendant)
                if node.best_descendant is not None
                else None
            )
        self.nodes = keep
        self.indices = {n.root: i for i, n in enumerate(self.nodes)}
        self._id_to_node[:] = NONE
        for n, i in self.indices.items():
            self._id_to_node[self._root_ids[n]] = i
        return pruned

    # ----------------------------------------------------- delta computation

    def compute_deltas(
        self,
        votes: VoteTracker,
        old_balances: np.ndarray,
        new_balances: np.ndarray,
    ) -> np.ndarray:
        """Vectorized vote-delta computation (reference:
        ``proto_array_fork_choice.rs:900`` ``compute_deltas``).

        For every validator whose latest message moved (or whose balance
        changed), subtract the old balance from the old vote's node and add the
        new balance to the new vote's node.  Scalar loop in the reference;
        scatter-add here."""
        deltas = np.zeros(len(self.nodes), dtype=np.int64)
        n = len(votes.current_root_id)
        if n == 0:
            return deltas
        ob = np.zeros(n, dtype=np.int64)
        nb = np.zeros(n, dtype=np.int64)
        ob[: min(n, len(old_balances))] = old_balances[:n]
        nb[: min(n, len(new_balances))] = new_balances[:n]
        # Equivocating validators contribute nothing ever again.
        nb[votes.equivocating] = 0
        has_next = votes.next_root_id != NONE
        changed = (votes.current_root_id != votes.next_root_id) | (ob != nb)
        changed &= has_next | (votes.current_root_id != NONE)

        cur_idx = np.full(n, NONE, dtype=np.int64)
        m = votes.current_root_id != NONE
        cur_idx[m] = self._id_to_node[votes.current_root_id[m]]
        nxt_idx = np.full(n, NONE, dtype=np.int64)
        m = has_next
        nxt_idx[m] = self._id_to_node[votes.next_root_id[m]]

        sub_m = changed & (cur_idx != NONE)
        np.subtract.at(deltas, cur_idx[sub_m], ob[sub_m])
        add_m = changed & (nxt_idx != NONE)
        np.add.at(deltas, nxt_idx[add_m], nb[add_m])

        # Advance current <- next for everyone with a next vote.
        votes.current_root_id = np.where(
            has_next, votes.next_root_id, votes.current_root_id
        )
        # Equivocating votes are consumed: their balance was subtracted once
        # above; clearing both roots keeps later rounds from re-subtracting
        # (the reference empties the VoteTracker on equivocation too).
        eq = votes.equivocating
        votes.current_root_id[eq] = NONE
        votes.next_root_id[eq] = NONE
        return deltas
