"""Self-tuning device runtime: the telemetry→knob control plane (ISSUE 15).

Every signal this module consumes already exists — the flight recorder's
per-batch occupancy (PR 4), the padding-waste percentiles, the admission
wait histograms (PR 14) — but until now only adaptive linger closed a loop
from any of them.  This controller closes three more, each decision
observable (``GET /lighthouse/autotune``, ``autotune_decisions_total``)
and pinnable:

1. **Live bucket vocabulary.**  The ``bucket_tuning.py`` heuristics run
   against the flight recorder at runtime: an effective bucket whose
   median dispatched batch fills under half its lanes earns a midpoint
   bucket (only where the vocabulary has a real >2x gap — a ratio-2
   vocabulary cannot waste more than half from bucket quantization).
   Adoption is guarded twice: the candidate must carry a committed
   ``hlo_budget_baseline.json`` entry (an unbudgeted shape would silently
   escape the static lowering gate — the controller refuses instead), and
   in live mode its compile cost must have been paid off-path through the
   AOT-warmup machinery (``ops/compile_cache.aot_warmup_op``) before the
   first production batch can land on it.  Adopted buckets overlay the
   static vocabularies (``ops/verify.py`` / ``ops/sha256_device.py`` /
   ``ops/epoch_device.py`` consult :func:`bucket_vocabulary`); the static
   tuples remain the floor and their top bucket the ceiling — the overlay
   never changes ``MAX_SETS_PER_DISPATCH`` semantics.

2. **Measured fq backend selection.**  ``LIGHTHOUSE_TPU_FQ_BACKEND=auto``
   used to be a platform guess (int8 on TPU, int32 elsewhere).
   :func:`measure_fq_backend` runs a short in-situ A/B microbench — one
   small operand batch through BOTH lowerings, supervised dispatch
   (``device_supervisor.run("autotune_probe", ...)`` so a hung device
   cannot stall startup) — and caches the winner per
   ``(device_kind, jax version)`` in the persistent compile-cache dir.
   ``ops/fq.active_fq_backend`` consults that cache before guessing.

3. **Latency-driven admission.**  Implemented in
   ``scheduler/admission.py`` against this module's mode: in live mode the
   per-class inflight bounds and dequeue deadlines track observed handler
   latency EWMAs inside a bounded band around the configured statics
   (which remain the floor/ceiling), and Retry-After always reflects the
   class's observed drain rate (constant fallback below the sample floor).

**Determinism by construction.**  ``LIGHTHOUSE_TPU_AUTOTUNE=0|pinned|live``
(default ``pinned``).  ``0`` disables everything — static behavior, zero
overhead.  ``pinned`` applies only decisions replayed from an installed
pin (a recorded decision list keyed by *evaluation index*, never
wall-clock — the scenario 2-run determinism gate is fragile to wall-clock
shifts, so the controller's clock inside scenarios is the evaluation
counter the runner drives once per slot); with no pin installed, pinned
mode is exactly static behavior.  ``live`` reads the telemetry.  A live
run's decisions export as a pin (:meth:`Controller.export_pin`), so a
tuned configuration replays bit-identically.

This module is HOST-side only: it reads telemetry rings and JSON files and
never materializes a device value — the host-sync and lock-order static
passes scan it (``scripts/analysis/{host_sync,lock_order}_pass.py``) and
must stay at zero findings.  The device-touching legs live where device
code belongs: the warmup in ``ops/compile_cache.py``, the A/B probe in
``ops/fq.py`` (both reached only from live-mode control actions).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import blackbox, locksmith, metrics
from .logs import get_logger

log = get_logger("autotune")

ENV = "LIGHTHOUSE_TPU_AUTOTUNE"
PIN_ENV = "LIGHTHOUSE_TPU_AUTOTUNE_PIN"
INTERVAL_ENV = "LIGHTHOUSE_TPU_AUTOTUNE_INTERVAL_S"
MODES = ("0", "pinned", "live")

#: bucket_tuning.py's densify threshold, applied at runtime: a bucket whose
#: median dispatched batch fills under half its lanes is waste-dominated.
DENSIFY_BELOW = 0.5
#: Minimum dispatched batches at one bucket before its occupancy is
#: evidence (same floor as bucket_tuning.py).
MIN_SAMPLES = 8
#: An adopted bucket with zero hits over a full recorder window while its
#: op stayed busy (>= MIN_SAMPLES batches) has stopped earning its keep.
DROP_IDLE_MIN_OP_SAMPLES = MIN_SAMPLES

#: Decision-log ring bound (the artifact of record for pins/ scenarios).
MAX_DECISIONS = 256

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: The committed StableHLO budget baseline — the adoption gate reads its
#: KEYS (an adopted bucket must already be a build-gated lowering).
BUDGET_BASELINE_PATH = os.path.join(
    _REPO_ROOT, "scripts", "analysis", "hlo_budget_baseline.json")

AUTOTUNE_EVALUATIONS = metrics.counter(
    "autotune_evaluations_total",
    "controller evaluation passes (live telemetry reads or pin replays)",
)
AUTOTUNE_DECISIONS = metrics.counter(
    "autotune_decisions_total",
    "controller decisions, by knob and outcome (adopted|dropped|"
    "refused_no_budget|warmup_started|warmup_pending|refused_warmup_failed|"
    "refused_above_top|refused_meshed|measured)",
)
AUTOTUNE_OVERLAY_BUCKETS = metrics.gauge(
    "autotune_overlay_buckets",
    "live bucket-vocabulary overlay size, by vocabulary",
)
AUTOTUNE_FQ_BACKEND = metrics.gauge(
    "autotune_fq_backend_selected",
    "measured fq-backend selection (1 = selected), by backend",
)
AUTOTUNE_FQ_MEASURE_SECONDS = metrics.histogram(
    "autotune_fq_backend_measure_seconds",
    "per-backend duration of the fq A/B microbench, by backend",
)


# ------------------------------------------------------------------- mode

_MODE: Optional[str] = None
_MODE_LOCK = locksmith.lock("autotune._MODE_LOCK")


def mode() -> str:
    """The controller mode, resolved lazily from ``LIGHTHOUSE_TPU_AUTOTUNE``
    (default ``pinned`` — with no pin installed that is exactly static
    behavior, so tests and scenarios see no wall-clock-driven change)."""
    global _MODE
    if _MODE is None:
        with _MODE_LOCK:
            if _MODE is None:
                raw = os.environ.get(ENV, "pinned").strip().lower() or "pinned"
                if raw not in MODES:
                    # resolved lazily from hot paths (admission bounds,
                    # /lighthouse/device) — a config typo must degrade to
                    # the do-nothing default with a loud log line, never
                    # 500 the serving surface at runtime
                    log.warning("invalid autotune mode, using 'pinned'",
                                env=ENV, value=raw, expected=list(MODES))
                    raw = "pinned"
                _MODE = raw
    return _MODE


def set_mode(new_mode: Optional[str]) -> Optional[str]:
    """Force the mode (tests/scenarios/bench) or reset to env (None).
    Returns the previous forced value."""
    global _MODE
    if new_mode is not None and new_mode not in MODES:
        raise ValueError(f"unknown autotune mode {new_mode!r}")
    with _MODE_LOCK:
        prev, _MODE = _MODE, new_mode
    _refresh_active()
    return prev


def enabled() -> bool:
    return mode() != "0"


def live() -> bool:
    return mode() == "live"


# ------------------------------------------------- bucket vocabulary overlay


class VocabSpec:
    """One tunable bucket vocabulary: its static tuple (the floor), the
    telemetry op names whose flight records evidence it, the committed-
    budget key for a candidate bucket, and the off-path warmup hook."""

    __slots__ = ("name", "static", "telemetry_ops", "budget_key", "warmup")

    def __init__(self, name: str, static: Sequence[int],
                 telemetry_ops: Sequence[str],
                 budget_key: Callable[[int], str],
                 warmup: Optional[Callable[[int], None]]):
        self.name = name
        self.static = tuple(int(b) for b in static)
        self.telemetry_ops = tuple(telemetry_ops)
        self.budget_key = budget_key
        self.warmup = warmup


#: vocabulary name -> VocabSpec; populated by the ops modules at import
#: time, so the controller only ever sees vocabularies that are actually
#: loaded in this process.  Survives reset_for_tests (it mirrors imports).
_VOCABS: Dict[str, VocabSpec] = {}

#: vocabulary name -> merged (static + adopted) tuple.  Copy-on-write: the
#: hot bucket_vocabulary() path reads it without the lock.
_MERGED: Dict[str, Tuple[int, ...]] = {}
_OVERLAY: Dict[str, Tuple[int, ...]] = {}
_OVERLAY_LOCK = locksmith.lock("autotune._OVERLAY_LOCK")

#: Fast-path flag: True iff the overlay is non-empty AND the mode allows
#: it — bucket_vocabulary() is on every device dispatch, so the off case
#: must cost one attribute read.
_ACTIVE = False


def register_vocabulary(name: str, static: Sequence[int], *,
                        telemetry_ops: Sequence[str],
                        budget_key: Callable[[int], str],
                        warmup: Optional[Callable[[int], None]] = None,
                        ) -> None:
    """Called by an ops module at import time to enroll its bucket
    vocabulary in the control plane.  Idempotent (re-imports keep the
    latest registration)."""
    _VOCABS[name] = VocabSpec(name, static, telemetry_ops, budget_key, warmup)


def _refresh_active() -> None:
    global _ACTIVE
    _ACTIVE = bool(_OVERLAY) and mode() != "0"


def bucket_vocabulary(name: str, static: Tuple[int, ...]) -> Tuple[int, ...]:
    """The vocabulary a dispatch should bucket against: the static tuple,
    merged with any adopted overlay buckets.  The off path (no overlay, or
    autotune disabled) returns ``static`` untouched."""
    if not _ACTIVE:
        return static
    merged = _MERGED.get(name)
    return merged if merged is not None else static


def overlay() -> Dict[str, Tuple[int, ...]]:
    with _OVERLAY_LOCK:
        return dict(_OVERLAY)


def _set_overlay(name: str, buckets: Tuple[int, ...]) -> None:
    """Replace one vocabulary's overlay (copy-on-write merge rebuild)."""
    spec = _VOCABS[name]
    with _OVERLAY_LOCK:
        if buckets:
            _OVERLAY[name] = tuple(sorted(buckets))
            _MERGED[name] = tuple(sorted(set(spec.static) | set(buckets)))
        else:
            _OVERLAY.pop(name, None)
            _MERGED.pop(name, None)
    AUTOTUNE_OVERLAY_BUCKETS.set(len(buckets), vocabulary=name)
    _refresh_active()


# -------------------------------------------------------------- budget gate

_BUDGET_CACHE: Tuple[Optional[float], frozenset] = (None, frozenset())
_BUDGET_LOCK = locksmith.lock("autotune._BUDGET_LOCK")


def budget_keys() -> frozenset:
    """The committed hlo_budget baseline keys (mtime-cached).  An empty set
    when the baseline is unreadable — then NOTHING can be adopted, which is
    the honest failure mode for a build gate."""
    global _BUDGET_CACHE
    try:
        mtime = os.path.getmtime(BUDGET_BASELINE_PATH)
    except OSError:
        return frozenset()
    with _BUDGET_LOCK:
        cached_mtime, keys = _BUDGET_CACHE
        if cached_mtime == mtime:
            return keys
        try:
            with open(BUDGET_BASELINE_PATH, "r", encoding="utf-8") as f:
                keys = frozenset(json.load(f))
        except (OSError, ValueError):
            keys = frozenset()
        _BUDGET_CACHE = (mtime, keys)
        return keys


# --------------------------------------------------------------- controller


class Controller:
    """The one decision-maker.  ``evaluate()`` is the clock: scenarios call
    it once per slot, the live background thread on an interval, bench
    loops explicitly — decisions key on the evaluation index, so a pinned
    replay is wall-clock-free by construction."""

    def __init__(self) -> None:
        self._lock = locksmith.lock("Controller._lock")
        self.evaluations = 0
        self._decisions: List[dict] = []
        self._decision_seq = 0
        self._pin: List[dict] = []
        self._pin_applied = 0
        self._pin_loaded_env = False
        #: (vocab, bucket) -> "pending" | "done" | "failed"
        self._warmups: Dict[Tuple[str, int], str] = {}
        #: last recorded outcome per (knob, vocab, action, bucket): a
        #: STANDING live-mode refusal (no committed budget, warmup still
        #: compiling) re-evaluates every tick — without dedup it would
        #: flood the bounded decision ring (the artifact of record) with
        #: identical entries and evict the real adopt/drop history.
        self._last_outcome: Dict[Tuple, str] = {}
        #: (vocab, bucket) -> flight-recorder recorded_total at adoption:
        #: a fresh adoption gets a full recorder window of evidence before
        #: the idle-drop heuristic may judge it (otherwise the drop fires
        #: in the same evaluation that adopted — zero hits yet, trivially)
        self._adopted_seq: Dict[Tuple[str, int], int] = {}
        self._fq_decision: Optional[dict] = None

    # ------------------------------------------------------------- records

    def _record(self, dedupe: bool = False, **fields) -> dict:
        entry = dict(fields)
        key = (entry.get("knob"), entry.get("vocab"), entry.get("action"),
               entry.get("bucket"))
        with self._lock:
            duplicate = (dedupe
                         and self._last_outcome.get(key) == entry.get("outcome"))
            if not duplicate:
                self._last_outcome[key] = entry.get("outcome")
                # a recorded adopt/drop resets the sibling action's memory,
                # so a genuine drop→re-adopt cycle records every leg
                if entry.get("action") in ("adopt", "drop"):
                    sibling = "drop" if entry["action"] == "adopt" else "adopt"
                    self._last_outcome.pop(
                        (entry.get("knob"), entry.get("vocab"), sibling,
                         entry.get("bucket")), None)
                self._decision_seq += 1
                entry["seq"] = self._decision_seq
                self._decisions.append(entry)
                if len(self._decisions) > MAX_DECISIONS:
                    self._decisions = self._decisions[-MAX_DECISIONS:]
        AUTOTUNE_DECISIONS.inc(knob=entry.get("knob", "?"),
                               outcome=entry.get("outcome", "?"))
        if duplicate:
            # a standing decision re-reached on a later evaluation: counted
            # on the metric, not re-appended to the ring
            return entry
        log.info("autotune decision", **{
            k: v for k, v in entry.items() if k != "measurements_s"})
        blackbox.emit("autotune", "decision", knob=entry.get("knob"),
                      action=entry.get("action"), outcome=entry.get("outcome"),
                      vocab=entry.get("vocab"), bucket=entry.get("bucket"),
                      decision_seq=entry.get("seq"))
        return entry

    def decision_log(self) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._decisions]

    def export_pin(self) -> List[dict]:
        """The applied bucket decisions as a replayable pin: adopt/drop
        actions with their evaluation indices.  Feed the result to
        :meth:`install_pin` (or ``LIGHTHOUSE_TPU_AUTOTUNE_PIN``) and a
        pinned run replays the same vocabulary trajectory with no
        telemetry and no wall-clock."""
        out = []
        for d in self.decision_log():
            if d.get("knob") == "bucket" and d.get("outcome") in (
                    "adopted", "dropped"):
                out.append({
                    "after_evaluation": d["evaluation"],
                    "vocab": d["vocab"],
                    "action": "adopt" if d["outcome"] == "adopted" else "drop",
                    "bucket": d["bucket"],
                })
        return out

    def install_pin(self, decisions: Sequence[dict]) -> None:
        """Install a pinned decision list (sorted by evaluation index).
        Only consulted in ``pinned`` mode."""
        pin = sorted((dict(d) for d in decisions),
                     key=lambda d: int(d.get("after_evaluation", 0)))
        with self._lock:
            self._pin = pin
            self._pin_applied = 0

    def _maybe_load_env_pin(self) -> None:
        with self._lock:
            if self._pin_loaded_env or self._pin:
                return
            self._pin_loaded_env = True
        path = os.environ.get(PIN_ENV, "").strip()
        if not path:
            return
        try:
            with open(path, "r", encoding="utf-8") as f:
                self.install_pin(json.load(f))
            log.info("autotune pin loaded", path=path)
        except (OSError, ValueError) as e:
            log.warning("autotune pin unreadable", path=path, error=str(e))

    # ------------------------------------------------------------ evaluate

    def evaluate(self) -> List[dict]:
        """One control pass.  Live: read the flight recorder, walk the
        densify/drop heuristics through the guardrails.  Pinned: apply the
        pin entries whose evaluation index has arrived.  Off: nothing."""
        m = mode()
        if m == "0":
            return []
        with self._lock:
            self.evaluations += 1
            n = self.evaluations
        AUTOTUNE_EVALUATIONS.inc()
        if m == "pinned":
            self._maybe_load_env_pin()
            return self._apply_pin(n)
        return self._evaluate_live(n)

    # --- pinned replay

    def _apply_pin(self, evaluation: int) -> List[dict]:
        applied: List[dict] = []
        while True:
            with self._lock:
                if self._pin_applied >= len(self._pin):
                    return applied
                entry = self._pin[self._pin_applied]
                if int(entry.get("after_evaluation", 0)) > evaluation:
                    return applied
                self._pin_applied += 1
            applied.append(self._apply_pinned_entry(entry, evaluation))

    def _apply_pinned_entry(self, entry: dict, evaluation: int) -> dict:
        name = entry.get("vocab")
        action = entry.get("action")
        bucket = int(entry.get("bucket", 0))
        spec = _VOCABS.get(name)
        if spec is None:
            return self._record(knob="bucket", vocab=name, action=action,
                                bucket=bucket, evaluation=evaluation,
                                via="pin", outcome="refused_unknown_vocab",
                                reason=f"no registered vocabulary {name!r}")
        if action == "drop":
            current = set(overlay().get(name, ()))
            current.discard(bucket)
            _set_overlay(name, tuple(current))
            return self._record(knob="bucket", vocab=name, action="drop",
                                bucket=bucket, evaluation=evaluation,
                                via="pin", outcome="dropped",
                                reason="pinned replay")
        # adopt: the committed-budget gate holds even for a replay — a pin
        # must never smuggle an unbudgeted lowering past the static gate.
        # The warmup gate does NOT apply: the pin replays a run whose
        # compile cost was already paid (wall-clock must not re-enter).
        refused = self._refuse_adopt(spec, bucket, require_warmup=False)
        if refused is not None:
            return self._record(knob="bucket", vocab=name, action="adopt",
                                bucket=bucket, evaluation=evaluation,
                                via="pin", **refused)
        self._adopt(spec, bucket)
        return self._record(knob="bucket", vocab=name, action="adopt",
                            bucket=bucket, evaluation=evaluation,
                            via="pin", outcome="adopted",
                            reason="pinned replay (budget gate held)")

    # --- live telemetry

    def _evaluate_live(self, evaluation: int) -> List[dict]:
        decisions: List[dict] = []
        for name, spec in sorted(_VOCABS.items()):
            stats = _bucket_live_stats(spec)
            effective = bucket_vocabulary(name, spec.static)
            decisions.extend(
                self._densify(spec, effective, stats, evaluation))
            decisions.extend(
                self._drop_idle(spec, stats, evaluation))
        return decisions

    def _densify(self, spec: VocabSpec, effective: Tuple[int, ...],
                 stats: Dict[int, List[int]], evaluation: int) -> List[dict]:
        out: List[dict] = []
        for i, nb in enumerate(effective):
            live = stats.get(nb, ())
            if len(live) < MIN_SAMPLES:
                continue
            ordered = sorted(live)
            p50 = ordered[len(ordered) // 2] / nb
            if p50 >= DENSIFY_BELOW:
                continue
            prev = effective[i - 1] if i else 0
            if prev <= 0 or nb <= 2 * prev:
                # ratio-2 dense below this bucket: quantization cannot
                # waste more than half — the low p50 is a traffic question
                # (linger/coalescing), not a vocabulary one.
                continue
            mid = (prev + nb) // 2
            if mid in effective:
                continue
            out.append(self._try_adopt(
                spec, mid, evaluation,
                reason=(f"bucket {nb}: p50 occupancy {p50:.2f} < "
                        f"{DENSIFY_BELOW} over {len(live)} batches — "
                        f"midpoint {mid} bounds quantization waste at "
                        "~50%")))
        return out

    def _drop_idle(self, spec: VocabSpec, stats: Dict[int, List[int]],
                   evaluation: int) -> List[dict]:
        adopted = overlay().get(spec.name, ())
        if not adopted:
            return []
        op_samples = sum(len(v) for v in stats.values())
        if op_samples < DROP_IDLE_MIN_OP_SAMPLES:
            return []
        from . import device_telemetry

        recorded = device_telemetry.FLIGHT_RECORDER.recorded_total
        window = device_telemetry.FLIGHT_RECORDER.capacity
        out: List[dict] = []
        for bucket in adopted:
            if stats.get(bucket):
                continue
            seq = self._adopted_seq.get((spec.name, bucket))
            if seq is not None and recorded - seq < window:
                continue  # adopted inside the current evidence window
            current = set(overlay().get(spec.name, ()))
            current.discard(bucket)
            _set_overlay(spec.name, tuple(current))
            out.append(self._record(
                knob="bucket", vocab=spec.name, action="drop",
                bucket=bucket, evaluation=evaluation, via="live",
                outcome="dropped",
                reason=(f"zero dispatches at {bucket} across the last "
                        f"{op_samples} recorded batches — the traffic "
                        "that earned it has moved")))
        return out

    def _refuse_adopt(self, spec: VocabSpec, bucket: int,
                      require_warmup: bool) -> Optional[dict]:
        """The guardrails, in order.  Returns outcome/reason fields when
        the adoption must be refused (or deferred), None when it may
        proceed."""
        if bucket >= spec.static[-1]:
            return {"outcome": "refused_above_top",
                    "reason": (f"{bucket} >= static top {spec.static[-1]} — "
                               "the top bucket bounds chunking semantics "
                               "and stays a reviewed-diff decision")}
        from . import device_mesh

        if device_mesh.enabled():
            # A meshed dispatch at the new bucket would compile a DISTINCT
            # sharded executable (e.g. 640@dp8) that neither the warmup
            # nor the budget baseline covers — on-path compile through an
            # unaudited lowering.  Mesh-aware adoption (per-topology
            # warmup + |dpN| budget keys) is the TPU round's work
            # (ROADMAP item 2); until then the controller refuses.
            return {"outcome": "refused_meshed",
                    "reason": (f"device mesh is enabled (size "
                               f"{device_mesh.size()}): adoption would "
                               "compile an unwarmed, unbudgeted sharded "
                               "executable on-path — mesh-aware adoption "
                               "is ROADMAP item 2's hardware round")}
        if bucket in bucket_vocabulary(spec.name, spec.static):
            return {"outcome": "noop", "reason": "already in the vocabulary"}
        # budget_key may name several keys (the epoch vocabulary compiles
        # one lowering per leak mode) — every one must be committed.
        keys = spec.budget_key(bucket)
        if isinstance(keys, str):
            keys = (keys,)
        committed = budget_keys()
        missing = [k for k in keys if k not in committed]
        if missing:
            return {"outcome": "refused_no_budget",
                    "reason": (f"no committed hlo_budget entry {missing!r} — "
                               "adopting would route production batches "
                               "through a lowering the static gate never "
                               "audited; commit the budget first "
                               "(scripts/analysis/hlo_budget.py)")}
        if not require_warmup:
            return None
        with self._lock:
            state = self._warmups.get((spec.name, bucket))
        if state == "done":
            return None
        if state == "failed":
            return {"outcome": "refused_warmup_failed",
                    "reason": "off-path AOT warmup failed — see logs"}
        if state == "pending":
            return {"outcome": "warmup_pending",
                    "reason": "off-path AOT warmup still compiling"}
        if spec.warmup is None:
            return {"outcome": "refused_warmup_failed",
                    "reason": "vocabulary registered no warmup hook"}
        self._start_warmup(spec, bucket)
        return {"outcome": "warmup_started",
                "reason": ("compile cost must be paid off-path before the "
                           "first production batch lands on the bucket — "
                           "AOT warmup kicked on a background thread")}

    def _try_adopt(self, spec: VocabSpec, bucket: int, evaluation: int,
                   reason: str) -> dict:
        refused = self._refuse_adopt(spec, bucket, require_warmup=True)
        if refused is not None:
            # dedupe: a standing refusal re-reached every evaluation must
            # not flood the bounded ring (it records once per outcome)
            return self._record(dedupe=True, knob="bucket", vocab=spec.name,
                                action="adopt", bucket=bucket,
                                evaluation=evaluation, via="live",
                                trigger=reason, **refused)
        self._adopt(spec, bucket)
        return self._record(knob="bucket", vocab=spec.name, action="adopt",
                            bucket=bucket, evaluation=evaluation, via="live",
                            outcome="adopted", reason=reason)

    def _adopt(self, spec: VocabSpec, bucket: int) -> None:
        from . import device_telemetry

        current = set(overlay().get(spec.name, ()))
        current.add(bucket)
        _set_overlay(spec.name, tuple(current))
        self._adopted_seq[(spec.name, bucket)] = \
            device_telemetry.FLIGHT_RECORDER.recorded_total

    def _start_warmup(self, spec: VocabSpec, bucket: int) -> None:
        key = (spec.name, bucket)
        with self._lock:
            self._warmups[key] = "pending"

        def work() -> None:
            try:
                spec.warmup(bucket)
            except Exception:
                log.warning("autotune warmup failed", vocab=spec.name,
                            bucket=bucket, exc_info=True)
                self._finish_warmup(key, "failed")
            else:
                self._finish_warmup(key, "done")

        threading.Thread(
            target=work, daemon=True,
            name=f"autotune-warm-{spec.name}-{bucket}").start()

    def _finish_warmup(self, key: Tuple[str, int], state: str) -> None:
        """Completion callback, locked — and generation-safe: a compile
        thread finishing AFTER a reset (scenario cleanup, tests) finds its
        'pending' entry gone and must NOT resurrect a stale done/failed
        state into the fresh controller."""
        with self._lock:
            if self._warmups.get(key) == "pending":
                self._warmups[key] = state

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        with self._lock:
            pin = [dict(d) for d in self._pin]
            pin_applied = self._pin_applied
            evaluations = self.evaluations
            warmups = {f"{k[0]}:{k[1]}": v for k, v in self._warmups.items()}
            fq = dict(self._fq_decision) if self._fq_decision else None
        return {
            "mode": mode(),
            "evaluations": evaluations,
            "vocabularies": {
                name: {
                    "static": list(spec.static),
                    "overlay": list(overlay().get(name, ())),
                    "effective": list(
                        bucket_vocabulary(name, spec.static)),
                }
                for name, spec in sorted(_VOCABS.items())
            },
            "warmups": warmups,
            "decisions": self.decision_log(),
            "pin": {"installed": len(pin), "applied": pin_applied,
                    "entries": pin},
            "fq_backend": fq or cached_fq_backend(),
        }

    def reset(self) -> None:
        with self._lock:
            self.evaluations = 0
            self._decisions = []
            self._decision_seq = 0
            self._pin = []
            self._pin_applied = 0
            self._pin_loaded_env = False
            self._warmups = {}
            self._adopted_seq = {}
            self._last_outcome = {}
            self._fq_decision = None


CONTROLLER = Controller()


def _bucket_live_stats(spec: VocabSpec) -> Dict[int, List[int]]:
    """bucket size -> live sizes of the dispatched batches that ran at it,
    over the flight-recorder window of the spec's telemetry ops.  Records
    the breaker routed to the host never dispatched and stay out (their
    ``occupancy_sets`` is absent — same rule the padding-waste metrics
    follow)."""
    from . import device_telemetry

    stats: Dict[int, List[int]] = {}
    for op in spec.telemetry_ops:
        for r in device_telemetry.FLIGHT_RECORDER.recent(
                limit=device_telemetry.FLIGHT_RECORDER.capacity, op=op):
            if "occupancy_sets" not in r:
                continue
            shape = str(r.get("shape", ""))
            try:
                nb = int(shape.split("@")[0].split("x")[0])
            except ValueError:
                continue
            stats.setdefault(nb, []).append(int(r.get("n_live", 0)))
    return stats


# ----------------------------------------------- measured backend selection


def fq_backend_cache_path() -> str:
    """The decision cache rides in the persistent compile-cache dir — the
    same lifetime as the compiled programs the decision shapes."""
    from .ops.compile_cache import default_cache_dir

    return os.path.join(default_cache_dir(), "autotune_fq_backend.json")


_FQ_KEY: Optional[str] = None


def _fq_cache_key() -> str:
    """(device_kind, jax version) — TOUCHES jax (``jax.devices()`` can
    hang on a dead tunnel), so callers on host-only paths must use the
    memoized value via ``cached_fq_backend(compute_key=False)``."""
    global _FQ_KEY
    if _FQ_KEY is None:
        import jax

        d = jax.devices()[0]
        kind = getattr(d, "device_kind", "") or d.platform
        _FQ_KEY = f"{kind}|jax-{jax.__version__}"
    return _FQ_KEY


def cached_fq_backend(compute_key: bool = False) -> Optional[dict]:
    """The cached measured decision for THIS (device_kind, jax version),
    or None (no measurement yet / cache unreadable / autotune off).

    ``compute_key=True`` may initialize jax to derive the cache key —
    only the fq ``auto`` resolution passes it (that path queries the jax
    platform right after anyway).  The default reuses the memoized key,
    so host-side surfaces (``/lighthouse/autotune``, check_metrics'
    import) can never hang a thread on a dead device tunnel."""
    if not enabled():
        return None
    key = None
    if compute_key:
        try:
            key = _fq_cache_key()
        except Exception:
            return None
    else:
        key = _FQ_KEY
    if key is None:
        return None
    try:
        with open(fq_backend_cache_path(), "r", encoding="utf-8") as f:
            doc = json.load(f)
        entry = doc.get(key)
    except Exception:
        return None
    if not isinstance(entry, dict) or entry.get("backend") not in (
            "int8", "int32"):
        return None
    return entry


def measure_fq_backend(force: bool = False, rows: int = 512,
                       reps: int = 3) -> dict:
    """Run (or reuse) the in-situ fq-backend A/B microbench.

    Both lowerings run the same small operand batch through a supervised
    dispatch (op ``autotune_probe`` — watchdogged, so a hung device cannot
    stall node startup past the deadline); the winner is cached per
    ``(device_kind, jax version)`` next to the persistent compile cache
    and consulted by ``ops/fq.active_fq_backend`` in place of the old
    platform guess.  Raises on device failure — the caller falls back to
    the guess."""
    if not force:
        cached = cached_fq_backend()
        if cached is not None:
            return cached
    from . import device_supervisor
    from .ops import fq

    key = _fq_cache_key()
    measurements: Dict[str, float] = {}
    for backend in ("int32", "int8"):
        seconds = device_supervisor.run(
            "autotune_probe",
            lambda b=backend: fq.measure_backend_seconds(
                b, rows=rows, reps=reps),
        )
        measurements[backend] = round(float(seconds), 6)
        AUTOTUNE_FQ_MEASURE_SECONDS.observe(seconds, backend=backend)
    winner = min(measurements, key=measurements.get)
    decision = {
        "backend": winner,
        "measurements_s": measurements,
        "source": "measured",
        "key": key,
        "rows": rows,
        "reps": reps,
    }
    for backend in ("int32", "int8"):
        AUTOTUNE_FQ_BACKEND.set(1.0 if backend == winner else 0.0,
                                backend=backend)
    _write_fq_cache(key, decision)
    with CONTROLLER._lock:
        CONTROLLER._fq_decision = decision
    CONTROLLER._record(knob="fq_backend", action="select", backend=winner,
                       outcome="measured", measurements_s=measurements,
                       reason=f"A/B microbench at rows={rows} (best of "
                              f"{reps} supervised dispatches per backend)")
    return decision


def _write_fq_cache(key: str, decision: dict) -> None:
    path = fq_backend_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except Exception:
            doc = {}
        doc[key] = decision
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        log.warning("fq backend decision cache not written", path=path)


# ----------------------------------------------------------- startup hook

_THREAD: Optional[threading.Thread] = None
_THREAD_STOP: Optional[threading.Event] = None
_THREAD_LOCK = locksmith.lock("autotune._THREAD_LOCK")


def maybe_start_from_env() -> Optional[threading.Thread]:
    """Node-startup hook (``ClientBuilder.build`` for jax nodes): in live
    mode, run the measured backend selection (``FQ_BACKEND`` unset/auto
    only; cached across restarts) and start the periodic controller
    thread.  Pinned/off modes start nothing — scenario and test processes
    stay free of wall-clock control loops."""
    global _THREAD, _THREAD_STOP
    if not live():
        return None
    interval = float(os.environ.get(INTERVAL_ENV, "30"))
    with _THREAD_LOCK:
        if (_THREAD is not None and _THREAD.is_alive()
                and _THREAD_STOP is not None and not _THREAD_STOP.is_set()):
            return _THREAD
        # each controller thread owns its OWN stop event: a stop() racing
        # a restart can only kill the thread it targeted, never strand the
        # fresh one against a stale still-set global flag
        stop_event = threading.Event()

        def loop() -> None:
            from .ops.fq import FQ_BACKEND_ENV

            if os.environ.get(FQ_BACKEND_ENV, "auto").strip().lower() in (
                    "", "auto"):
                try:
                    decision = measure_fq_backend()
                except Exception:
                    log.warning("fq backend measurement failed; the "
                                "platform guess stands", exc_info=True)
                else:
                    # apply the winner to THIS process: traces cut after
                    # this point use the measured lowering.  Shapes that
                    # traced during the probe window keep the guess's
                    # lowering until restart (jax's trace cache) — the
                    # cached decision makes the restart right from the
                    # first trace.
                    from .ops import fq

                    fq.set_fq_backend(decision["backend"])
                    log.info("measured fq backend applied",
                             backend=decision["backend"])
            while not stop_event.wait(interval):
                try:
                    CONTROLLER.evaluate()
                except Exception:
                    log.warning("autotune evaluation failed", exc_info=True)

        _THREAD_STOP = stop_event
        _THREAD = threading.Thread(target=loop, daemon=True, name="autotune")
        _THREAD.start()
        return _THREAD


def stop() -> None:
    with _THREAD_LOCK:
        if _THREAD_STOP is not None:
            _THREAD_STOP.set()


def snapshot() -> dict:
    return CONTROLLER.snapshot()


def reset_for_tests() -> None:
    """Clear controller state, overlay, and forced mode (registrations
    persist — they mirror module imports)."""
    stop()
    CONTROLLER.reset()
    with _OVERLAY_LOCK:
        _OVERLAY.clear()
        _MERGED.clear()
    set_mode(None)
