"""EIP-3076 slashing-protection database.

Equivalent of the reference's ``validator_client/slashing_protection``
(``slashing_database.rs`` — SQLite; here the same interlock semantics over
our own ``lockbox`` KV engine, or in-memory for tests):

- a signed **block** is safe iff its slot is strictly greater than any
  previously signed block's slot for that pubkey (same-slot re-broadcast of
  the identical signing_root is allowed);
- a signed **attestation** is safe iff it is not a double vote (same target,
  different signing_root), does not surround and is not surrounded by any
  previously signed attestation, and its source/target do not move backwards
  from the recorded maxima.

Safety checks and the insert are atomic under one lock — the DB is the last
line of defense, exactly like the reference (interchange spec
https://eips.ethereum.org/EIPS/eip-3076, format version 5).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

INTERCHANGE_VERSION = "5"


class SlashingProtectionError(Exception):
    """Refusing to sign: doing so could be slashable."""


class _ValidatorRecord:
    __slots__ = ("blocks", "attestations")

    def __init__(self):
        # slot -> signing_root (may be None for imported min entries)
        self.blocks: Dict[int, Optional[bytes]] = {}
        # (source, target) -> signing_root
        self.attestations: Dict[Tuple[int, int], Optional[bytes]] = {}


class SlashingProtectionDB:
    """``store=None`` keeps everything in memory; otherwise a ``LockboxStore``
    (or any object with put/get/iter_column) persists each record."""

    BLK = b"spb"
    ATT = b"spa"

    def __init__(self, store=None):
        self._store = store
        self._lock = threading.Lock()
        self._records: Dict[bytes, _ValidatorRecord] = {}
        if store is not None:
            self._load()

    # ------------------------------------------------------------- loading

    def _load(self) -> None:
        for key, value in self._store.iter_column(self.BLK):
            pubkey, slot = key[:-8], int.from_bytes(key[-8:], "big")
            root = value if value else None
            self._rec(pubkey).blocks[slot] = root
        for key, value in self._store.iter_column(self.ATT):
            pubkey = key[:-16]
            source = int.from_bytes(key[-16:-8], "big")
            target = int.from_bytes(key[-8:], "big")
            root = value if value else None
            self._rec(pubkey).attestations[(source, target)] = root

    def _rec(self, pubkey: bytes) -> _ValidatorRecord:
        rec = self._records.get(pubkey)
        if rec is None:
            rec = self._records[pubkey] = _ValidatorRecord()
        return rec

    def _persist_block(self, pubkey: bytes, slot: int, root: Optional[bytes]) -> None:
        if self._store is not None:
            self._store.put(self.BLK, pubkey + slot.to_bytes(8, "big"), root or b"")

    def _persist_att(self, pubkey: bytes, source: int, target: int,
                     root: Optional[bytes]) -> None:
        if self._store is not None:
            key = pubkey + source.to_bytes(8, "big") + target.to_bytes(8, "big")
            self._store.put(self.ATT, key, root or b"")

    # ------------------------------------------------------------ blocks

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        """Raise ``SlashingProtectionError`` unless signing is safe; record it."""
        with self._lock:
            rec = self._rec(pubkey)
            if rec.blocks:
                max_slot = max(rec.blocks)
                existing = rec.blocks.get(slot)
                if slot == max_slot and existing is not None and existing == signing_root:
                    return  # identical re-sign is safe (idempotent broadcast)
                if slot <= max_slot:
                    raise SlashingProtectionError(
                        f"block at slot {slot} <= max signed slot {max_slot}"
                    )
            rec.blocks[slot] = signing_root
            self._persist_block(pubkey, slot, signing_root)

    # -------------------------------------------------------- attestations

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("attestation source > target")
        with self._lock:
            rec = self._rec(pubkey)
            existing = rec.attestations.get((source_epoch, target_epoch))
            if existing is not None and existing == signing_root:
                return  # identical re-sign
            for (s, t), root in rec.attestations.items():
                if t == target_epoch and root != signing_root:
                    raise SlashingProtectionError(
                        f"double vote at target {target_epoch}"
                    )
                if source_epoch < s and target_epoch > t:
                    raise SlashingProtectionError(
                        f"({source_epoch},{target_epoch}) surrounds ({s},{t})"
                    )
                if source_epoch > s and target_epoch < t:
                    raise SlashingProtectionError(
                        f"({source_epoch},{target_epoch}) surrounded by ({s},{t})"
                    )
            # EIP-3076 minimal conditions: never move source/target backwards.
            if rec.attestations:
                max_source = max(s for s, _ in rec.attestations)
                max_target = max(t for _, t in rec.attestations)
                if source_epoch < max_source:
                    raise SlashingProtectionError(
                        f"source {source_epoch} < max signed source {max_source}"
                    )
                if target_epoch <= max_target:
                    raise SlashingProtectionError(
                        f"target {target_epoch} <= max signed target {max_target}"
                    )
            rec.attestations[(source_epoch, target_epoch)] = signing_root
            self._persist_att(pubkey, source_epoch, target_epoch, signing_root)

    # -------------------------------------------------------- interchange

    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        """EIP-3076 interchange JSON (complete format)."""
        with self._lock:
            data = []
            for pubkey, rec in sorted(self._records.items()):
                data.append({
                    "pubkey": "0x" + pubkey.hex(),
                    "signed_blocks": [
                        {
                            "slot": str(slot),
                            **(
                                {"signing_root": "0x" + root.hex()}
                                if root is not None
                                else {}
                            ),
                        }
                        for slot, root in sorted(rec.blocks.items())
                    ],
                    "signed_attestations": [
                        {
                            "source_epoch": str(s),
                            "target_epoch": str(t),
                            **(
                                {"signing_root": "0x" + root.hex()}
                                if root is not None
                                else {}
                            ),
                        }
                        for (s, t), root in sorted(rec.attestations.items())
                    ],
                })
        return {
            "metadata": {
                "interchange_format_version": INTERCHANGE_VERSION,
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, obj: dict, genesis_validators_root: bytes) -> int:
        """Merge an interchange document; returns #validators imported.
        Records are unioned (the reference's ``minify``-free import): existing
        protections are never weakened."""
        meta = obj.get("metadata", {})
        gvr = meta.get("genesis_validators_root", "")
        if gvr and gvr.lower() != "0x" + genesis_validators_root.hex():
            raise SlashingProtectionError(
                f"interchange for different chain (gvr {gvr})"
            )
        count = 0
        with self._lock:
            for entry in obj.get("data", []):
                pubkey = bytes.fromhex(entry["pubkey"][2:])
                rec = self._rec(pubkey)
                for blk in entry.get("signed_blocks", []):
                    slot = int(blk["slot"])
                    root = (
                        bytes.fromhex(blk["signing_root"][2:])
                        if "signing_root" in blk
                        else None
                    )
                    if slot not in rec.blocks or rec.blocks[slot] is None:
                        rec.blocks[slot] = root
                        self._persist_block(pubkey, slot, root)
                for att in entry.get("signed_attestations", []):
                    s, t = int(att["source_epoch"]), int(att["target_epoch"])
                    root = (
                        bytes.fromhex(att["signing_root"][2:])
                        if "signing_root" in att
                        else None
                    )
                    if (s, t) not in rec.attestations or rec.attestations[(s, t)] is None:
                        rec.attestations[(s, t)] = root
                        self._persist_att(pubkey, s, t, root)
                count += 1
        return count

    def export_json(self, genesis_validators_root: bytes) -> str:
        return json.dumps(self.export_interchange(genesis_validators_root), indent=2)

    def import_json(self, text: str, genesis_validators_root: bytes) -> int:
        return self.import_interchange(json.loads(text), genesis_validators_root)
