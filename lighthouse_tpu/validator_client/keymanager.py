"""VC keymanager HTTP API.

Equivalent of the reference's ``validator_client/src/http_api`` (the
standard keymanager-API surface ``validator_manager`` drives): list / import
/ delete keystores and remote (Web3Signer) keys, Bearer-token
authenticated (the reference's ``api-token.txt``).

Routes (keymanager-specs):
    GET    /eth/v1/keystores
    POST   /eth/v1/keystores            {keystores[], passwords[], slashing_protection?}
    DELETE /eth/v1/keystores            {pubkeys[]} -> slashing_protection export
    GET    /eth/v1/remotekeys
    POST   /eth/v1/remotekeys           {remote_keys: [{pubkey, url}]}
    DELETE /eth/v1/remotekeys           {pubkeys[]}
"""

from __future__ import annotations

import json
import re
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .validator_store import ValidatorStore
from .web3signer import Web3SignerClient


_SETTINGS_ROUTE = re.compile(
    r"/eth/v1/validator/(0x[0-9a-fA-F]{96})/"
    r"(feerecipient|gas_limit|graffiti)$")


class KeymanagerServer:
    def __init__(self, *, store: ValidatorStore, genesis_validators_root: bytes,
                 port: int = 0, token: Optional[str] = None,
                 preparation=None, blocks=None):
        self.store = store
        self.genesis_validators_root = bytes(genesis_validators_root)
        self.token = token if token is not None else secrets.token_hex(16)
        self._port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._remote_urls: Dict[bytes, str] = {}
        # per-validator settings (keymanager-specs feerecipient/gas_limit/
        # graffiti routes).  When the VC's services are wired in, settings
        # are LIVE: fee recipients flow into proposer preparations and
        # graffiti overrides the file/flag at proposal time.
        self.preparation = preparation
        self.blocks = blocks
        # standalone fallback stores, used only when the corresponding VC
        # service is not wired in (ONE owner per setting otherwise)
        self._fee_recipients: Dict[bytes, bytes] = {}
        self._gas_limits: Dict[bytes, int] = {}
        self._graffiti: Dict[bytes, bytes] = {}

    def _fee_map(self) -> Dict[bytes, bytes]:
        if self.preparation is not None:
            return self.preparation.per_validator
        return self._fee_recipients

    def _purge_validator_settings(self, pubkey: bytes) -> None:
        """A deleted key's settings must not survive to a future
        re-import (a new operator would silently inherit them)."""
        self._fee_map().pop(pubkey, None)
        self._fee_recipients.pop(pubkey, None)
        self._gas_limits.pop(pubkey, None)
        self._graffiti.pop(pubkey, None)
        if self.blocks is not None:
            self.blocks.keymanager_graffiti.pop(pubkey, None)

    # ------------------------------------------------------------ handlers

    def _list_keystores(self) -> dict:
        return {"data": [
            {"validating_pubkey": "0x" + pk.hex(), "derivation_path": "", "readonly": False}
            for pk in self.store._by_pubkey
        ]}

    def _import_keystores(self, body: dict) -> dict:
        from ..crypto import keystore as ks

        keystores = body.get("keystores") or []
        passwords = body.get("passwords") or []
        if len(keystores) != len(passwords):
            raise ValueError("keystores and passwords length mismatch")
        interchange = body.get("slashing_protection")
        if interchange:
            self.store.slashing_db.import_json(
                interchange if isinstance(interchange, str) else json.dumps(interchange),
                self.genesis_validators_root,
            )
        statuses = []
        for raw, password in zip(keystores, passwords):
            try:
                obj = json.loads(raw) if isinstance(raw, str) else raw
                sk = ks.load_keystore_signing_key(obj, password)
                pk = self.store.add_key(sk)
                statuses.append({"status": "imported", "message": "0x" + pk.hex()})
            except Exception as e:
                statuses.append({"status": "error", "message": str(e)})
        return {"data": statuses}

    def _delete_keystores(self, body: dict) -> dict:
        pubkeys = [bytes.fromhex(p[2:]) for p in (body.get("pubkeys") or [])]
        statuses = []
        for pk in pubkeys:
            # typed endpoint: only LOCAL keystores; remote keys have their
            # own DELETE with different (no-protection-export) semantics
            removed = self.store.remove_local_key(pk)
            if removed:
                self._purge_validator_settings(pk)
            statuses.append({"status": "deleted" if removed else "not_found"})
        # Per keymanager-specs, deletion returns the protection history so
        # keys can migrate without double-sign risk.
        export = self.store.slashing_db.export_json(self.genesis_validators_root)
        return {"data": statuses, "slashing_protection": export}

    def _list_remotekeys(self) -> dict:
        return {"data": [
            {"pubkey": "0x" + pk.hex(), "url": url, "readonly": False}
            for pk, url in self._remote_urls.items()
        ]}

    def _import_remotekeys(self, body: dict) -> dict:
        statuses = []
        for entry in body.get("remote_keys") or []:
            try:
                pk = bytes.fromhex(entry["pubkey"][2:])
                url = entry["url"]
                if self.store.has_key(pk):
                    # keymanager-specs: duplicates are reported, never
                    # silently rerouting a locally-held key to a remote
                    statuses.append({"status": "duplicate"})
                    continue
                self.store.add_remote_key(pk, Web3SignerClient(url))
                self._remote_urls[pk] = url
                statuses.append({"status": "imported"})
            except Exception as e:
                statuses.append({"status": "error", "message": str(e)})
        return {"data": statuses}

    def _delete_remotekeys(self, body: dict) -> dict:
        statuses = []
        for p in body.get("pubkeys") or []:
            pk = bytes.fromhex(p[2:])
            removed = self.store.remove_remote_key(pk)
            self._remote_urls.pop(pk, None)
            if removed:
                self._purge_validator_settings(pk)
            statuses.append({"status": "deleted" if removed else "not_found"})
        return {"data": statuses}

    def _validator_setting(self, method: str, pubkey: bytes, kind: str,
                           body: dict):
        """keymanager-specs per-validator settings.  GET returns the value,
        POST sets (202), DELETE resets (204)."""
        hexkey = "0x" + pubkey.hex()
        if kind == "feerecipient":
            if method == "GET":
                cur = self._fee_map().get(pubkey)
                if cur is None and self.preparation is not None:
                    # the EFFECTIVE value: the VC-level default applies
                    # when no per-validator override exists
                    cur = self.preparation.fee_recipient
                return 200, {"data": {"pubkey": hexkey,
                                      "ethaddress": "0x" + (cur or b"\x00" * 20).hex()}}
            if method == "POST":
                addr = bytes.fromhex(str(body["ethaddress"])[2:])
                if len(addr) != 20:
                    raise ValueError("ethaddress must be 20 bytes")
                self._fee_map()[pubkey] = addr
                return 202, None
            self._fee_map().pop(pubkey, None)
            return 204, None
        if kind == "gas_limit":
            if method == "GET":
                return 200, {"data": {"pubkey": hexkey,
                                      "gas_limit": str(self._gas_limits.get(
                                          pubkey, 30_000_000))}}
            if method == "POST":
                self._gas_limits[pubkey] = int(body["gas_limit"])
                return 202, None
            self._gas_limits.pop(pubkey, None)
            return 204, None
        # graffiti — the SERVER owns the setting (it must round-trip even
        # standalone); the block service mirror makes it live at proposal
        if method == "GET":
            cur = self._graffiti.get(pubkey)
            if cur is None and self.blocks is not None:
                cur = self.blocks._graffiti_for(pubkey)  # effective value
            return 200, {"data": {"pubkey": hexkey,
                                  "graffiti": (cur or b"").rstrip(b"\x00").decode(
                                      "utf-8", "replace")}}
        if method == "POST":
            raw = str(body["graffiti"]).encode()
            if len(raw) > 32:
                raise ValueError("graffiti exceeds 32 bytes")
            padded = raw.ljust(32, b"\x00")
            self._graffiti[pubkey] = padded
            if self.blocks is not None:
                self.blocks.keymanager_graffiti[pubkey] = padded
            return 202, None
        self._graffiti.pop(pubkey, None)
        if self.blocks is not None:
            self.blocks.keymanager_graffiti.pop(pubkey, None)
        return 204, None

    # -------------------------------------------------------------- server

    def start(self) -> "KeymanagerServer":
        km = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, obj=None):
                body = b"" if obj is None else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _authed(self) -> bool:
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {km.token}"

            def _dispatch(self, method: str):
                if not self._authed():
                    self._reply(401, {"message": "invalid or missing Bearer token"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length)) if length else {}
                except json.JSONDecodeError as e:
                    self._reply(400, {"message": f"malformed JSON body: {e}"})
                    return
                path = self.path.split("?")[0].rstrip("/")
                try:
                    if path.endswith("/eth/v1/keystores"):
                        if method == "GET":
                            self._reply(200, km._list_keystores())
                        elif method == "POST":
                            self._reply(200, km._import_keystores(body))
                        else:
                            self._reply(200, km._delete_keystores(body))
                        return
                    if path.endswith("/eth/v1/remotekeys"):
                        if method == "GET":
                            self._reply(200, km._list_remotekeys())
                        elif method == "POST":
                            self._reply(200, km._import_remotekeys(body))
                        else:
                            self._reply(200, km._delete_remotekeys(body))
                        return
                    m = _SETTINGS_ROUTE.search(path)
                    if m:
                        pubkey = bytes.fromhex(m.group(1)[2:])
                        if not km.store.has_key(pubkey):
                            self._reply(404, {"message": "unknown validator"})
                            return
                        code, obj = km._validator_setting(
                            method, pubkey, m.group(2), body)
                        self._reply(code, obj)
                        return
                except (ValueError, KeyError) as e:
                    self._reply(400, {"message": str(e)})
                    return
                self._reply(404, {"message": "unknown route"})

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._server = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


class KeymanagerClient:
    """The ``validator_manager``-side client."""

    def __init__(self, base_url: str, token: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _request(self, method: str, path: str, body=None):
        import urllib.request

        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {self.token}",
            },
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            raw = resp.read()
            return json.loads(raw) if raw else None

    def list_keystores(self) -> List[dict]:
        return self._request("GET", "/eth/v1/keystores")["data"]

    def import_keystores(self, keystores: List[dict], passwords: List[str],
                         slashing_protection: Optional[str] = None) -> List[dict]:
        body = {"keystores": [json.dumps(k) for k in keystores],
                "passwords": passwords}
        if slashing_protection:
            body["slashing_protection"] = slashing_protection
        return self._request("POST", "/eth/v1/keystores", body)["data"]

    def delete_keystores(self, pubkeys: List[bytes]) -> dict:
        return self._request(
            "DELETE", "/eth/v1/keystores",
            {"pubkeys": ["0x" + bytes(p).hex() for p in pubkeys]},
        )

    def list_remotekeys(self) -> List[dict]:
        return self._request("GET", "/eth/v1/remotekeys")["data"]

    def import_remotekeys(self, entries: List[dict]) -> List[dict]:
        return self._request("POST", "/eth/v1/remotekeys",
                             {"remote_keys": entries})["data"]
