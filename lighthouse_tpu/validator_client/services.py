"""Validator-client services: duties polling, attesting, aggregating,
proposing — the per-slot production loop.

Equivalent of the reference's ``validator_client/src/{duties_service,
attestation_service, block_service}.rs``: duties are polled per epoch and
keyed by dependent_root; attestations are produced at slot+1/3, aggregates at
slot+2/3, blocks at slot start (``attestation_service.rs:1-60``,
``duties_service.rs:1-47``).  All beacon-node access goes through the
fallback (multi-BN redundancy, ``beacon_node_fallback.rs``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..http_api.client import ApiClientError, BeaconNodeHttpClient
from ..http_api.serde import container_from_json
from .validator_store import ValidatorStore


from ..logs import get_logger

log = get_logger("vc")


class NoViableBeaconNode(Exception):
    pass


class BeaconNodeFallback:
    """Try each configured BN in order; first success wins
    (reference ``beacon_node_fallback.rs`` first_success)."""

    def __init__(self, clients: List[BeaconNodeHttpClient]):
        assert clients, "at least one beacon node required"
        self.clients = list(clients)

    def first_success(self, fn: Callable[[BeaconNodeHttpClient], object]):
        errors = []
        for client in self.clients:
            try:
                return fn(client)
            except (ApiClientError, OSError) as e:
                errors.append(f"{client.base_url}: {e}")
        raise NoViableBeaconNode("; ".join(errors))

    def measure_latency(self) -> List[dict]:
        """Round-trip time to every candidate BN (reference
        ``latency.rs``/``measure_latency``: a cheap GET per candidate, run
        11/12ths through the slot).  ``latency`` is None for unreachable
        nodes."""
        import time as _time

        out = []
        for client in self.clients:
            t0 = _time.monotonic()
            try:
                client.node_version()
                latency = _time.monotonic() - t0
            except (ApiClientError, OSError):
                latency = None
            out.append({"endpoint": client.base_url, "latency": latency})
        return out


class AttesterDuty:
    __slots__ = (
        "pubkey", "validator_index", "slot", "committee_index",
        "committee_length", "committees_at_slot", "validator_committee_index",
    )

    def __init__(self, d: dict):
        self.pubkey = bytes.fromhex(d["pubkey"][2:])
        self.validator_index = int(d["validator_index"])
        self.slot = int(d["slot"])
        self.committee_index = int(d["committee_index"])
        self.committee_length = int(d["committee_length"])
        self.committees_at_slot = int(d["committees_at_slot"])
        self.validator_committee_index = int(d["validator_committee_index"])


class DutiesService:
    def __init__(self, *, store: ValidatorStore, fallback: BeaconNodeFallback):
        self.store = store
        self.fallback = fallback
        # epoch -> {pubkey: AttesterDuty}
        self._attesters: Dict[int, Dict[bytes, List[AttesterDuty]]] = {}
        # epoch -> {slot: pubkey} (only our validators)
        self._proposers: Dict[int, Dict[int, bytes]] = {}
        self._dependent_roots: Dict[int, str] = {}
        self._indices: Dict[bytes, int] = {}  # pubkey -> validator index

    # ------------------------------------------------------------- indices

    def resolve_indices(self) -> Dict[bytes, int]:
        unknown = [pk for pk in self.store.pubkeys if pk not in self._indices]
        if unknown:
            ids = ["0x" + pk.hex() for pk in unknown]
            data = self.fallback.first_success(
                lambda c: c.validators("head", ids=ids)
            )
            for entry in data:
                pk = bytes.fromhex(entry["validator"]["pubkey"][2:])
                self._indices[pk] = int(entry["index"])
        return self._indices

    # -------------------------------------------------------------- duties

    def update(self, epoch: int) -> None:
        """Poll proposer + attester duties for ``epoch`` (and attesters for
        ``epoch+1`` so the first slot of the next epoch is never missed)."""
        indices = self.resolve_indices()
        if not indices:
            return
        self._poll_attesters(epoch, indices)
        self._poll_attesters(epoch + 1, indices)
        self._poll_proposers(epoch)
        for old in [e for e in self._attesters if e + 2 < epoch]:
            del self._attesters[old]
        for old in [e for e in self._proposers if e + 2 < epoch]:
            del self._proposers[old]
        for old in [e for e in self._dependent_roots if e + 2 < epoch]:
            del self._dependent_roots[old]

    def _poll_attesters(self, epoch: int, indices: Dict[bytes, int]) -> None:
        resp = self.fallback.first_success(
            lambda c: c.attester_duties(epoch, sorted(indices.values()))
        )
        dep = resp.get("dependent_root", "")
        if self._dependent_roots.get(epoch) == dep and epoch in self._attesters:
            return  # unchanged — same shuffling decision root
        self._dependent_roots[epoch] = dep
        by_pk: Dict[bytes, List[AttesterDuty]] = {}
        for d in resp["data"]:
            duty = AttesterDuty(d)
            by_pk.setdefault(duty.pubkey, []).append(duty)
        self._attesters[epoch] = by_pk

    def _poll_proposers(self, epoch: int) -> None:
        resp = self.fallback.first_success(lambda c: c.proposer_duties(epoch))
        ours: Dict[int, bytes] = {}
        for d in resp["data"]:
            pk = bytes.fromhex(d["pubkey"][2:])
            if self.store.has_key(pk):
                ours[int(d["slot"])] = pk
        self._proposers[epoch] = ours

    def attester_duties_at_slot(self, slot: int, spec) -> List[AttesterDuty]:
        epoch = slot // spec.slots_per_epoch
        out = []
        for duties in self._attesters.get(epoch, {}).values():
            out.extend(d for d in duties if d.slot == slot)
        return out

    def proposer_at_slot(self, slot: int, spec) -> Optional[bytes]:
        epoch = slot // spec.slots_per_epoch
        return self._proposers.get(epoch, {}).get(slot)


class PreparationService:
    """Fee-recipient preparations (reference ``preparation_service.rs``):
    POST prepare_beacon_proposer for every managed validator each epoch so
    the BN builds payloads paying OUR recipient.  (Builder/relay validator
    registration is a separate flow: register_validator, tests/test_builder.)"""

    def __init__(self, *, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback,
                 fee_recipient: bytes = b"\x00" * 20):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.fee_recipient = bytes(fee_recipient)
        self.per_validator: Dict[bytes, bytes] = {}  # pubkey -> recipient

    def set_fee_recipient(self, pubkey: bytes, recipient: bytes) -> None:
        self.per_validator[bytes(pubkey)] = bytes(recipient)

    def prepare(self) -> int:
        indices = self.duties.resolve_indices()
        entries = []
        for pk, idx in indices.items():
            recipient = self.per_validator.get(pk, self.fee_recipient)
            entries.append({
                "validator_index": str(idx),
                "fee_recipient": "0x" + recipient.hex(),
            })
        if entries:
            self.fallback.first_success(
                lambda c: c.prepare_beacon_proposer(entries)
            )
        return len(entries)


class SyncDuty:
    __slots__ = ("pubkey", "validator_index", "positions")

    def __init__(self, d: dict):
        self.pubkey = bytes.fromhex(d["pubkey"][2:])
        self.validator_index = int(d["validator_index"])
        self.positions = [int(p) for p in d["validator_sync_committee_indices"]]


class SyncCommitteeService:
    """Sync-committee duties (reference ``sync_committee_service.rs``):
    broadcast ``SyncCommitteeMessage``s over the head root at slot+1/3, and
    for elected sync aggregators, fetch + wrap + publish
    ``SignedContributionAndProof`` at slot+2/3."""

    def __init__(self, *, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback, types):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.types = types
        self._sync_duties: Dict[int, List[SyncDuty]] = {}  # period -> duties

    def _period(self, epoch: int) -> int:
        return epoch // self.store.spec.preset.epochs_per_sync_committee_period

    def update_duties(self, epoch: int) -> None:
        period = self._period(epoch)
        if period in self._sync_duties:
            return
        indices = self.duties.resolve_indices()
        if not indices:
            # Don't cache emptiness: indices may simply not be resolvable yet
            # (BN syncing, validators pending) — retry on the next call
            # instead of skipping the whole ~27h period.
            return
        resp = self.fallback.first_success(
            lambda c: c.sync_duties(epoch, sorted(indices.values()))
        )
        self._sync_duties[period] = [SyncDuty(d) for d in resp["data"]]
        for old in [p for p in self._sync_duties if p + 2 < period]:
            del self._sync_duties[old]

    def _duties_now(self, slot: int) -> List[SyncDuty]:
        epoch = slot // self.store.spec.slots_per_epoch
        self.update_duties(epoch)
        return self._sync_duties.get(self._period(epoch), [])

    def produce_messages(self, slot: int) -> int:
        """Sign the current head root per sync duty and submit; returns count
        (the slot+1/3 half of the service)."""
        duties = self._duties_now(slot)
        if not duties:
            return 0
        head_root = self.fallback.first_success(lambda c: c.block_root("head"))
        messages = []
        for duty in duties:
            try:
                sig = self.store.sign_sync_committee_message(
                    duty.pubkey, slot, head_root
                )
            except Exception:
                continue  # missing key
            messages.append(self.types.SyncCommitteeMessage(
                slot=slot,
                beacon_block_root=head_root,
                validator_index=duty.validator_index,
                signature=sig,
            ))
        if messages:
            self.fallback.first_success(
                lambda c: c.submit_sync_committee_messages(messages)
            )
        return len(messages)

    def aggregate(self, slot: int) -> int:
        """For subcommittees where a duty is an elected sync aggregator:
        fetch the pool contribution and publish the signed wrap (the
        slot+2/3 half)."""
        spec = self.store.spec
        duties = self._duties_now(slot)
        if not duties:
            return 0
        sub_size = spec.preset.sync_committee_size // spec.sync_committee_subnet_count
        head_root = self.fallback.first_success(lambda c: c.block_root("head"))
        published = []
        fetched: Dict[int, Optional[object]] = {}
        for duty in duties:
            for sub in sorted({p // sub_size for p in duty.positions}):
                proof = self.store.sync_selection_proof(
                    duty.pubkey, slot, sub, self.types
                )
                if not self.store.is_sync_aggregator(proof):
                    continue
                if sub not in fetched:
                    try:
                        fetched[sub] = self.fallback.first_success(
                            lambda c: c.sync_committee_contribution(
                                slot, sub, head_root, types=self.types
                            )
                        )
                    except NoViableBeaconNode:
                        fetched[sub] = None
                contribution = fetched[sub]
                if contribution is None:
                    continue
                message = self.types.ContributionAndProof(
                    aggregator_index=duty.validator_index,
                    contribution=contribution,
                    selection_proof=proof,
                )
                sig = self.store.sign_contribution_and_proof(duty.pubkey, message)
                published.append(self.types.SignedContributionAndProof(
                    message=message, signature=sig
                ))
        if published:
            self.fallback.first_success(
                lambda c: c.publish_contribution_and_proofs(published)
            )
        return len(published)


class DoppelgangerService:
    """Doppelganger protection (reference ``doppelganger_service.rs:1-13``):
    on startup, REFUSE all signing until our validators have shown no
    liveness on the network for ``DETECTION_EPOCHS`` full epochs — if another
    instance is attesting with our keys, signing would self-slash.

    The gate wraps the validator store: ``signing_enabled`` starts False and
    flips only after clean checks; a detection latches permanently until the
    operator intervenes."""

    DETECTION_EPOCHS = 2

    def __init__(self, *, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback, start_epoch: int):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.start_epoch = start_epoch
        self.detected: List[int] = []  # validator indices seen live elsewhere
        self.complete = False  # satisfied: checks stop permanently
        self._clean_epochs: set = set()
        store.signing_enabled = False

    def check(self, current_epoch: int) -> bool:
        """Run a liveness round; returns True once signing is enabled.
        Call once per epoch (the reference polls at 3/4 of the last slot).
        Once satisfied, checks stop for good — after the gate lifts, OUR OWN
        duties show up as liveness and must not re-latch the block."""
        if self.complete:
            return True
        if self.detected:
            return False
        if current_epoch <= self.start_epoch:
            return False  # the startup epoch itself is never clean evidence
        indices = sorted(self.duties.resolve_indices().values())
        if not indices:
            # Indices not resolvable yet (BN syncing, validators pending):
            # keep the gate DOWN — 'unknown' must never mean 'safe'.
            return False
        # Check the *previous* epoch: it is complete, so absence is meaningful.
        # The startup epoch itself never counts — another instance may have
        # attested in it before we started watching.
        epoch_to_check = current_epoch - 1
        if epoch_to_check <= self.start_epoch:
            return False
        data = self.fallback.first_success(
            lambda c: c.liveness(epoch_to_check, indices)
        )
        live = [int(d["index"]) for d in data if d["is_live"]]
        if live:
            self.detected = live
            self.store.signing_enabled = False
            return False
        self._clean_epochs.add(epoch_to_check)
        if len(self._clean_epochs) >= self.DETECTION_EPOCHS:
            self.store.signing_enabled = True
            self.complete = True
            return True
        return False


class AttestationService:
    """Produce + publish attestations at slot+1/3, aggregates at slot+2/3
    (reference ``attestation_service.rs`` spawn_attestation_tasks)."""

    def __init__(self, *, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback, types):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.types = types

    def attest(self, slot: int) -> int:
        """Sign + submit one attestation per duty at ``slot``; returns count."""
        spec = self.store.spec
        duties = self.duties.attester_duties_at_slot(slot, spec)
        if not duties:
            return 0
        by_committee: Dict[int, List[AttesterDuty]] = {}
        for d in duties:
            by_committee.setdefault(d.committee_index, []).append(d)
        attestations = []
        for committee_index, committee_duties in sorted(by_committee.items()):
            data = self.fallback.first_success(
                lambda c: c.attestation_data(slot, committee_index, types=self.types)
            )
            for duty in committee_duties:
                try:
                    sig = self.store.sign_attestation(duty.pubkey, data)
                except Exception:
                    continue  # slashing-protected or missing key: skip
                bits = [False] * duty.committee_length
                bits[duty.validator_committee_index] = True
                attestations.append(self.types.Attestation(
                    aggregation_bits=bits, data=data, signature=sig
                ))
        if attestations:
            self.fallback.first_success(
                lambda c: c.submit_attestations(attestations)
            )
            log.info("attestations published", slot=int(slot),
                     count=len(attestations))
        return len(attestations)

    def aggregate(self, slot: int) -> int:
        """For duties where we are the aggregator: fetch the pool aggregate,
        wrap in SignedAggregateAndProof, publish; returns count."""
        spec = self.store.spec
        duties = self.duties.attester_duties_at_slot(slot, spec)
        signed_aggregates = []
        fetched: Dict[int, Optional[object]] = {}  # committee -> aggregate (dedup fetch only)
        for duty in duties:
            proof = self.store.selection_proof(duty.pubkey, slot)
            if not self.store.is_aggregator(duty.committee_length, proof):
                continue
            # Every elected aggregator publishes, even when several of our
            # validators share a committee; only the FETCH is deduplicated.
            if duty.committee_index not in fetched:
                data = self.fallback.first_success(
                    lambda c: c.attestation_data(slot, duty.committee_index, types=self.types)
                )
                try:
                    fetched[duty.committee_index] = self.fallback.first_success(
                        lambda c: c.aggregate_attestation(
                            slot, data.hash_tree_root(), types=self.types,
                            committee_index=duty.committee_index,
                        )
                    )
                except NoViableBeaconNode:
                    fetched[duty.committee_index] = None
            aggregate = fetched[duty.committee_index]
            if aggregate is None:
                continue  # no aggregate in the pool for this data
            message = self.types.AggregateAndProof(
                aggregator_index=duty.validator_index,
                aggregate=aggregate,
                selection_proof=proof,
            )
            sig = self.store.sign_aggregate_and_proof(duty.pubkey, message)
            signed_aggregates.append(self.types.SignedAggregateAndProof(
                message=message, signature=sig
            ))
        if signed_aggregates:
            self.fallback.first_success(
                lambda c: c.publish_aggregate_and_proofs(signed_aggregates)
            )
        return len(signed_aggregates)


class BlockService:
    """Propose when we hold the proposer's key (``block_service.rs``)."""

    def __init__(self, *, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback, types,
                 graffiti: bytes = b"lighthouse-tpu".ljust(32, b"\x00"),
                 builder_proposals: bool = False, graffiti_file=None):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.types = types
        self.graffiti = graffiti
        self.builder_proposals = builder_proposals
        # reference precedence: keymanager-set graffiti > per-validator
        # file entry > file default > VC-level graffiti flag
        self.graffiti_file = graffiti_file
        self.keymanager_graffiti = {}  # pubkey -> 32-byte graffiti

    def _graffiti_for(self, pubkey: bytes) -> bytes:
        km = self.keymanager_graffiti.get(bytes(pubkey))
        if km is not None:
            return km
        if self.graffiti_file is not None:
            try:
                g = self.graffiti_file.graffiti_for(pubkey)
            except Exception as e:
                # a broken file must not stop proposals — but it must be
                # LOUD: the operator configured per-validator graffiti and
                # is silently not getting it
                log.warning("graffiti file unusable, using default: %s", e)
                g = None
            if g is not None:
                return g
        return self.graffiti

    def propose(self, slot: int) -> Optional[bytes]:
        """Produce, sign (slashing-gated) and publish a block if it is our
        duty; returns the block root or None.  With ``builder_proposals``,
        try the blinded/MEV path first and fall back to local production
        (reference ``block_service.rs`` blinded-vs-full)."""
        spec = self.store.spec
        pubkey = self.duties.proposer_at_slot(slot, spec)
        if pubkey is None:
            return None
        epoch = slot // spec.slots_per_epoch
        reveal = self.store.randao_reveal(pubkey, epoch)
        if self.builder_proposals:
            try:
                return self._propose_blinded(slot, pubkey, reveal)
            except (ApiClientError, NoViableBeaconNode, KeyError, ValueError):
                pass  # builder path unavailable: local production below
        graffiti = self._graffiti_for(pubkey)
        resp = self.fallback.first_success(
            lambda c: c.produce_block(slot, reveal, graffiti=graffiti)
        )
        fork = resp["version"]
        if resp.get("execution_payload_blinded"):
            # A builder-enabled BN may serve a BLINDED body from v3 — sign
            # and publish it down the blinded path (spec v3 contract).
            block = container_from_json(self.types.blinded_block[fork], resp["data"])
            sig = self.store.sign_block(pubkey, block)
            signed = self.types.signed_blinded_block[fork](message=block, signature=sig)
            self.fallback.first_success(lambda c: c.publish_blinded_block(signed))
            return block.hash_tree_root()
        block = container_from_json(self.types.block[fork], resp["data"])
        sig = self.store.sign_block(pubkey, block)  # slashing DB veto point
        signed = self.types.signed_block[fork](message=block, signature=sig)
        self.fallback.first_success(lambda c: c.publish_block(signed))
        root = block.hash_tree_root()
        log.info("block proposed", slot=int(slot),
                 root="0x" + root.hex()[:16], path="local")
        return root

    def _propose_blinded(self, slot: int, pubkey: bytes, reveal: bytes) -> bytes:
        graffiti = self._graffiti_for(pubkey)
        resp = self.fallback.first_success(
            lambda c: c.produce_blinded_block(slot, reveal, graffiti=graffiti)
        )
        fork = resp["version"]
        block = container_from_json(self.types.blinded_block[fork], resp["data"])
        sig = self.store.sign_block(pubkey, block)  # same slashing veto
        signed = self.types.signed_blinded_block[fork](message=block, signature=sig)
        self.fallback.first_success(lambda c: c.publish_blinded_block(signed))
        root = block.hash_tree_root()
        log.info("block proposed", slot=int(slot),
                 root="0x" + root.hex()[:16], path="builder")
        return root
