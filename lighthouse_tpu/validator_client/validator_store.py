"""Validator store: keys + domain-aware signing, gated by slashing protection.

Equivalent of the reference's ``validator_client/src/validator_store.rs`` —
every signature a validator produces flows through here so the
EIP-3076 DB can veto it (``sign_block``/``sign_attestation`` →
``slashing_protection.check_and_insert_*``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..consensus import helpers as h
from ..crypto.bls import api as bls
from ..types.spec import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    ChainSpec,
)
from ..types.ssz import UintType
from .slashing_protection import SlashingProtectionDB

uint64 = UintType(8)


class DoppelgangerBlocked(Exception):
    """Signing refused: doppelganger protection has not cleared yet."""


class ValidatorStore:
    def __init__(
        self,
        *,
        keys: List[bls.SecretKey],
        spec: ChainSpec,
        genesis_validators_root: bytes,
        slashing_db: Optional[SlashingProtectionDB] = None,
        fake_signatures: bool = False,
    ):
        self.spec = spec
        self.genesis_validators_root = genesis_validators_root
        self.slashing_db = slashing_db if slashing_db is not None else SlashingProtectionDB()
        self._by_pubkey: Dict[bytes, bls.SecretKey] = {
            sk.public_key().to_bytes(): sk for sk in keys
        }
        self._fake = fake_signatures
        self._remote: Dict[bytes, object] = {}  # pubkey -> remote signer
        # Doppelganger gate: DoppelgangerService flips this to False at
        # startup and back to True only after clean liveness epochs.
        self.signing_enabled = True
        if fake_signatures:
            from ..crypto.bls import curve, serde

            self._canned = serde.g2_compress(curve.G2)

    @property
    def pubkeys(self) -> List[bytes]:
        return list(self._by_pubkey) + list(self._remote)

    def has_key(self, pubkey: bytes) -> bool:
        return bytes(pubkey) in self._by_pubkey or bytes(pubkey) in self._remote

    # -------------------------------------------------------- key lifecycle
    # (reference initialized_validators.rs + signing_method.rs: local
    # keystores and Web3Signer remotes behind one signing facade)

    def add_key(self, secret_key) -> bytes:
        pk = secret_key.public_key().to_bytes()
        self._by_pubkey[pk] = secret_key
        return pk

    def remove_local_key(self, pubkey: bytes) -> bool:
        return self._by_pubkey.pop(bytes(pubkey), None) is not None

    def remove_remote_key(self, pubkey: bytes) -> bool:
        return self._remote.pop(bytes(pubkey), None) is not None

    def remove_key(self, pubkey: bytes) -> bool:
        """Remove in either backing (CLI convenience; the keymanager API's
        typed DELETE endpoints use the specific removers)."""
        local = self.remove_local_key(pubkey)
        remote = self.remove_remote_key(pubkey)
        return local or remote

    def add_remote_key(self, pubkey: bytes, signer) -> None:
        """Register a Web3Signer-backed key: ``signer.sign(pubkey, root)``
        produces the signature bytes remotely (signing_method.rs:80-91)."""
        self._remote[bytes(pubkey)] = signer

    # ------------------------------------------------------------- signing

    def _domain(self, domain_type: bytes, epoch: int) -> bytes:
        fork_version = self.spec.fork_version_for(self.spec.fork_name_at_epoch(epoch))
        return h.compute_domain(domain_type, fork_version, self.genesis_validators_root)

    def _raw_sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        if not self.signing_enabled:
            raise DoppelgangerBlocked(
                "signing disabled: doppelganger protection has not cleared"
            )
        if self._fake:
            return self._canned
        remote = self._remote.get(bytes(pubkey))
        if remote is not None:
            return remote.sign(bytes(pubkey), signing_root)
        sk = self._by_pubkey.get(bytes(pubkey))
        if sk is None:
            raise KeyError(f"no key for pubkey {bytes(pubkey).hex()[:16]}")
        return sk.sign(signing_root).to_bytes()

    def sign_block(self, pubkey: bytes, block) -> bytes:
        """Slashing-gated block signature (validator_store.rs sign_block)."""
        slot = int(block.slot)
        epoch = slot // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_BEACON_PROPOSER, epoch)
        signing_root = h.compute_signing_root(block.hash_tree_root(), domain)
        self.slashing_db.check_and_insert_block_proposal(
            bytes(pubkey), slot, signing_root
        )
        return self._raw_sign(pubkey, signing_root)

    def sign_attestation(self, pubkey: bytes, data) -> bytes:
        """Slashing-gated attestation signature over ``AttestationData``."""
        domain = self._domain(DOMAIN_BEACON_ATTESTER, int(data.target.epoch))
        signing_root = h.compute_signing_root(data.hash_tree_root(), domain)
        self.slashing_db.check_and_insert_attestation(
            bytes(pubkey), int(data.source.epoch), int(data.target.epoch), signing_root
        )
        return self._raw_sign(pubkey, signing_root)

    # ------------------------------------------------- unsafe signing seam
    #
    # The ONLY way around the EIP-3076 veto.  Exists for the byzantine
    # actor layer (adversary.py): scenario adversaries must be able to
    # produce genuinely slashable messages while the honest sign_block /
    # sign_attestation path keeps its protection intact (and asserted —
    # the controller first proves the honest path refuses, then signs
    # here).  Nothing in the production duty path may ever call these;
    # neither checks NOR records in the slashing DB, so an adversary's
    # slashable signature cannot poison the honest history either.

    def sign_block_unsafe(self, pubkey: bytes, block) -> bytes:
        """UNSAFE: proposer signature with the slashing-protection veto
        bypassed.  Byzantine test seam only — see the section comment."""
        epoch = int(block.slot) // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_BEACON_PROPOSER, epoch)
        signing_root = h.compute_signing_root(block.hash_tree_root(), domain)
        return self._raw_sign(pubkey, signing_root)

    def sign_attestation_unsafe(self, pubkey: bytes, data) -> bytes:
        """UNSAFE: attestation signature with the slashing-protection veto
        bypassed.  Byzantine test seam only — see the section comment."""
        domain = self._domain(DOMAIN_BEACON_ATTESTER, int(data.target.epoch))
        signing_root = h.compute_signing_root(data.hash_tree_root(), domain)
        return self._raw_sign(pubkey, signing_root)

    def sign_aggregate_and_proof_unsafe(self, pubkey: bytes,
                                        aggregate_and_proof) -> bytes:
        """UNSAFE alias of ``sign_aggregate_and_proof`` for the byzantine
        seam.  Aggregate wraps are not EIP-3076-gated (there is no veto to
        bypass), but adversarial signing must stay greppable as ``_unsafe``
        — the audit invariant the byzantine layer is built on."""
        return self.sign_aggregate_and_proof(pubkey, aggregate_and_proof)

    def randao_reveal(self, pubkey: bytes, epoch: int) -> bytes:
        domain = self._domain(DOMAIN_RANDAO, epoch)
        root = h.compute_signing_root(uint64.hash_tree_root(epoch), domain)
        return self._raw_sign(pubkey, root)

    def selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        """Aggregation-slot selection proof (sign the slot number)."""
        epoch = slot // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_SELECTION_PROOF, epoch)
        root = h.compute_signing_root(uint64.hash_tree_root(slot), domain)
        return self._raw_sign(pubkey, root)

    def sign_aggregate_and_proof(self, pubkey: bytes, aggregate_and_proof) -> bytes:
        epoch = int(aggregate_and_proof.aggregate.data.slot) // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF, epoch)
        root = h.compute_signing_root(aggregate_and_proof.hash_tree_root(), domain)
        return self._raw_sign(pubkey, root)

    def sign_voluntary_exit(self, pubkey: bytes, voluntary_exit,
                            current_epoch: int) -> bytes:
        """EIP-7044: once the CHAIN is at deneb+, exits are perpetually signed
        over the CAPELLA fork domain regardless of the exit's own epoch — must
        match the verify side (signature_sets.voluntary_exit_signature_set,
        which keys off the state's fork), else the BN rejects our own exits
        (round-2 advisor finding).  ``current_epoch`` is the wall-clock epoch
        (required — the caller owns the slot clock); an exit may legally carry
        any past epoch, so the fork decision uses the later of the two."""
        epoch = int(voluntary_exit.epoch)
        decision_epoch = max(epoch, int(current_epoch))
        if self.spec.fork_name_at_epoch(decision_epoch) in ("deneb", "electra"):
            domain = h.compute_domain(
                DOMAIN_VOLUNTARY_EXIT,
                self.spec.capella_fork_version,
                self.genesis_validators_root,
            )
        else:
            domain = self._domain(DOMAIN_VOLUNTARY_EXIT, epoch)
        root = h.compute_signing_root(voluntary_exit.hash_tree_root(), domain)
        return self._raw_sign(pubkey, root)

    def sign_sync_committee_message(self, pubkey: bytes, slot: int,
                                    block_root: bytes) -> bytes:
        epoch = slot // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_SYNC_COMMITTEE, epoch)
        root = h.compute_signing_root(bytes(block_root), domain)
        return self._raw_sign(pubkey, root)

    # ---------------------------------------------------------- aggregation

    def is_aggregator(self, committee_length: int, selection_proof: bytes) -> bool:
        """spec ``is_aggregator``: hash(selection_proof) mod max(1, len//16) == 0."""
        import hashlib

        modulo = max(1, committee_length // self.spec.target_aggregators_per_committee)
        digest = hashlib.sha256(selection_proof).digest()
        return int.from_bytes(digest[:8], "little") % modulo == 0

    # ------------------------------------------------------ sync committee

    def sync_selection_proof(self, pubkey: bytes, slot: int,
                             subcommittee_index: int, types) -> bytes:
        """Sign ``SyncAggregatorSelectionData`` (the sync-duty analog of the
        attestation selection proof)."""
        data = types.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subcommittee_index
        )
        epoch = slot // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
        root = h.compute_signing_root(data.hash_tree_root(), domain)
        return self._raw_sign(pubkey, root)

    def is_sync_aggregator(self, selection_proof: bytes) -> bool:
        """spec ``is_sync_committee_aggregator``."""
        import hashlib

        sub_size = (
            self.spec.preset.sync_committee_size
            // self.spec.sync_committee_subnet_count
        )
        modulo = max(1, sub_size // self.spec.target_aggregators_per_sync_subcommittee)
        digest = hashlib.sha256(selection_proof).digest()
        return int.from_bytes(digest[:8], "little") % modulo == 0

    def sign_contribution_and_proof(self, pubkey: bytes, message) -> bytes:
        epoch = int(message.contribution.slot) // self.spec.slots_per_epoch
        domain = self._domain(DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
        root = h.compute_signing_root(message.hash_tree_root(), domain)
        return self._raw_sign(pubkey, root)

    def sign_contribution_and_proof_unsafe(self, pubkey: bytes,
                                           message) -> bytes:
        """UNSAFE alias of ``sign_contribution_and_proof`` for the byzantine
        seam — see ``sign_aggregate_and_proof_unsafe``."""
        return self.sign_contribution_and_proof(pubkey, message)
