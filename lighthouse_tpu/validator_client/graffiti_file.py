"""Per-validator graffiti loaded from a file.

Equivalent of the reference's ``validator_client/src/graffiti_file.rs``:
a flat file mapping pubkeys to graffiti with an optional default,

    default: Lighthouse
    0x<48-byte-pubkey-hex>: my graffiti
    ...

reloaded on EVERY lookup so operators can edit it without restarting the
VC (the reference's ``load_graffiti`` re-reads per proposal).  Graffiti is
UTF-8, at most 32 bytes, zero-padded for the block body.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


class GraffitiFileError(Exception):
    pass


def _encode_graffiti(text: str) -> bytes:
    raw = text.encode()
    if len(raw) > 32:
        raise GraffitiFileError(f"graffiti exceeds 32 bytes: {text!r}")
    return raw.ljust(32, b"\x00")


class GraffitiFile:
    def __init__(self, path: str):
        self.path = path

    def _load(self):
        """Parse the file fresh.  Raises GraffitiFileError on a malformed
        line, an invalid pubkey, or oversize graffiti — a bad file must be
        LOUD, not silently skipped (reference Error::InvalidLine)."""
        default: Optional[bytes] = None
        per_key: Dict[bytes, bytes] = {}
        if not os.path.exists(self.path):
            raise GraffitiFileError(f"graffiti file missing: {self.path}")
        with open(self.path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if ":" not in line:
                    raise GraffitiFileError(f"line {lineno}: missing ':'")
                key, _, value = line.partition(":")
                key = key.strip()
                value = value.strip()
                if key == "default":
                    default = _encode_graffiti(value)
                    continue
                hexkey = key[2:] if key.startswith("0x") else key
                try:
                    pubkey = bytes.fromhex(hexkey)
                except ValueError as e:
                    raise GraffitiFileError(
                        f"line {lineno}: bad pubkey hex: {e}") from e
                if len(pubkey) != 48:
                    raise GraffitiFileError(
                        f"line {lineno}: pubkey must be 48 bytes, got {len(pubkey)}")
                per_key[pubkey] = _encode_graffiti(value)
        return default, per_key

    def graffiti_for(self, pubkey: bytes) -> Optional[bytes]:
        """The graffiti for ``pubkey``: its own line, else the file default,
        else None (caller falls back to the VC-level graffiti)."""
        default, per_key = self._load()
        return per_key.get(bytes(pubkey), default)
