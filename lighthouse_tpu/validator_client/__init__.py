"""The validator client: duties-driven attesting, aggregating and proposing
against one or more beacon nodes over the HTTP API, with EIP-3076 slashing
protection vetoing every signature.

Equivalent of the reference's ``validator_client`` crate
(``src/lib.rs`` ``ProductionValidatorClient`` — duties service + attestation
service + block service over ``BeaconNodeHttpClient`` with multi-BN
fallback).  ``run_slot`` is the manual-tick entry the simulator and tests
drive; ``run_forever`` adds the wall-clock pacing (attest at +1/3, aggregate
at +2/3) for a real deployment.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..crypto.bls import api as bls
from ..http_api.client import BeaconNodeHttpClient
from ..types.spec import ChainSpec
from .services import (
    AttestationService,
    BeaconNodeFallback,
    BlockService,
    DoppelgangerService,
    DutiesService,
    NoViableBeaconNode,
    PreparationService,
    SyncCommitteeService,
)
from .slashing_protection import SlashingProtectionDB, SlashingProtectionError
from .validator_store import ValidatorStore

__all__ = [
    "BeaconNodeFallback",
    "NoViableBeaconNode",
    "SlashingProtectionDB",
    "SlashingProtectionError",
    "ValidatorClient",
    "ValidatorStore",
]


class ValidatorClient:
    def __init__(
        self,
        *,
        keys: List[bls.SecretKey],
        beacon_nodes: List[BeaconNodeHttpClient],
        spec: ChainSpec,
        types,
        genesis_validators_root: bytes,
        slashing_db: Optional[SlashingProtectionDB] = None,
        fake_signatures: bool = False,
        fee_recipient: bytes = b"\x00" * 20,
        graffiti_file_path: Optional[str] = None,
    ):
        self.spec = spec
        self.types = types
        self.store = ValidatorStore(
            keys=keys,
            spec=spec,
            genesis_validators_root=genesis_validators_root,
            slashing_db=slashing_db,
            fake_signatures=fake_signatures,
        )
        self.fallback = BeaconNodeFallback(beacon_nodes)
        self.duties = DutiesService(store=self.store, fallback=self.fallback)
        self.attester = AttestationService(
            store=self.store, duties=self.duties, fallback=self.fallback, types=types
        )
        graffiti_file = None
        if graffiti_file_path is not None:
            from .graffiti_file import GraffitiFile

            graffiti_file = GraffitiFile(graffiti_file_path)
        self.blocks = BlockService(
            store=self.store, duties=self.duties, fallback=self.fallback,
            types=types, graffiti_file=graffiti_file,
        )
        self.sync_committee = SyncCommitteeService(
            store=self.store, duties=self.duties, fallback=self.fallback, types=types
        )
        self.preparation = PreparationService(
            store=self.store, duties=self.duties, fallback=self.fallback,
            fee_recipient=fee_recipient,
        )
        self.doppelganger: Optional[DoppelgangerService] = None
        self._last_duties_epoch: Optional[int] = None
        self.latencies: List[dict] = []  # last per-BN RTT measurements
        self._latency_slot = -1  # slot of the freshest completed probe
        self._latency_lock = threading.Lock()

    def enable_doppelganger_protection(self, start_epoch: int) -> None:
        """Block ALL signing until liveness checks prove no other instance is
        running our keys (reference ``doppelganger_service.rs``)."""
        self.doppelganger = DoppelgangerService(
            store=self.store, duties=self.duties, fallback=self.fallback,
            start_epoch=start_epoch,
        )

    # ------------------------------------------------------------ manual

    def update_duties(self, epoch: int) -> None:
        self.duties.update(epoch)
        self._last_duties_epoch = epoch

    def run_slot(self, slot: int) -> dict:
        """One full slot of validator work, in protocol order: propose at
        slot start, attest (+1/3), aggregate (+2/3).  Duties refresh on epoch
        change.  Returns a summary dict (the notifier line)."""
        epoch = slot // self.spec.slots_per_epoch
        if self._last_duties_epoch != epoch:
            self.update_duties(epoch)
            if self.doppelganger is not None:
                self.doppelganger.check(epoch)
            try:
                self.preparation.prepare()
            except NoViableBeaconNode:
                pass  # preparations are best-effort; retried next epoch
        if not self.store.signing_enabled:
            # Doppelganger gate down: perform NO duties (the whole point),
            # but keep polling duties/liveness above.
            return {
                "slot": slot, "proposed": None, "attestations": 0,
                "aggregates": 0, "sync_messages": 0, "sync_contributions": 0,
                "doppelganger_blocked": True,
            }
        proposed = self.blocks.propose(slot)
        attested = self.attester.attest(slot)
        sync_messages = self.sync_committee.produce_messages(slot)
        aggregated = self.attester.aggregate(slot)
        sync_contributions = self.sync_committee.aggregate(slot)
        return {
            "slot": slot,
            "proposed": proposed.hex() if proposed else None,
            "attestations": attested,
            "aggregates": aggregated,
            "sync_messages": sync_messages,
            "sync_contributions": sync_contributions,
        }

    # ---------------------------------------------------------- real time

    def run_forever(self, *, genesis_time: int, stop_after_slots: Optional[int] = None):
        """Wall-clock loop: propose at slot start, attest at +1/3, aggregate
        at +2/3 (the reference's slot-timing contract)."""
        from ..logs import get_logger

        log = get_logger("vc")
        sps = self.spec.seconds_per_slot

        def safely(what, fn, *args):
            # One failed duty (BN restart, slashing veto, ...) must never
            # kill the loop — log and carry on to the next phase/slot.
            try:
                return fn(*args)
            except Exception as e:
                log.warning("%s failed at slot task: %s", what, e)
                return None

        done = 0
        while stop_after_slots is None or done < stop_after_slots:
            now = time.time()
            slot = max(0, int((now - genesis_time) // sps))
            slot_start = genesis_time + slot * sps
            epoch = slot // self.spec.slots_per_epoch
            if self._last_duties_epoch != epoch:
                safely("duties update", self.update_duties, epoch)
                if self.doppelganger is not None:
                    safely("doppelganger check", self.doppelganger.check, epoch)
                safely("proposer preparation", self.preparation.prepare)
            if not self.store.signing_enabled:
                # doppelganger gate down: no duties at all — running them
                # would even pollute the slashing DB with roots that were
                # never signed (check_and_insert precedes the signing gate)
                time.sleep(max(0.0, slot_start + sps - time.time()))
                done += 1
                continue
            safely("propose", self.blocks.propose, slot)
            time.sleep(max(0.0, slot_start + sps / 3 - time.time()))
            safely("attest", self.attester.attest, slot)
            safely("sync messages", self.sync_committee.produce_messages, slot)
            time.sleep(max(0.0, slot_start + 2 * sps / 3 - time.time()))
            safely("aggregate", self.attester.aggregate, slot)
            safely("sync contributions", self.sync_committee.aggregate, slot)
            # 11/12ths through the slot: measure per-BN latency (reference
            # latency.rs SLOT_DELAY_MULTIPLIER/DENOMINATOR) — duty traffic
            # is done by now, so the probe reads steady-state RTT.  The
            # measurement runs OFF the duty path (a blackholed BN's probe
            # blocks ~10 s; serialized in-loop it would push every later
            # duty past its deadline — the exact failure it exists to see).
            time.sleep(max(0.0, slot_start + sps * 11 / 12 - time.time()))
            probe_slot = slot

            def _measure(my_slot=probe_slot):
                out = safely("latency measurement",
                             self.fallback.measure_latency) or []
                # a slow probe finishing AFTER a later slot's probe must not
                # overwrite the fresher result (blackholed-BN threads can
                # outlive their slot); compare-and-set under the lock —
                # unlocked, two finishing threads can interleave the check
                # and the writes and reintroduce exactly this bug
                with self._latency_lock:
                    if my_slot >= self._latency_slot:
                        self._latency_slot = my_slot
                        self.latencies = out
                for m in out:
                    if m["latency"] is not None:
                        log.info("beacon node latency", endpoint=m["endpoint"],
                                 ms=round(m["latency"] * 1000, 1))

            threading.Thread(target=_measure, name="vc-latency",
                             daemon=True).start()
            time.sleep(max(0.0, slot_start + sps - time.time()))
            done += 1
