"""Web3Signer remote signing.

Equivalent of the reference's ``signing_method.rs:80-91`` (the
``SigningMethod::Web3Signer`` arm) + the ``testing/web3signer_tests`` rig:
signatures come from an external signer over HTTP; the VC never holds the
secret key.  The mock server plays the Java Web3Signer's role in tests and
asserts remote signatures are byte-identical to local ones — the reference's
own acceptance criterion (``web3signer_tests/src/lib.rs:1-13``).

Wire format (Web3Signer ETH2 API subset): POST
``/api/v1/eth2/sign/0x{pubkey}`` with ``{"signing_root": "0x…"}`` →
``{"signature": "0x…"}``.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional


class Web3SignerError(Exception):
    pass


class Web3SignerClient:
    """The VC-side remote signer (pluggable into
    ``ValidatorStore.add_remote_key``).

    Requests carry a timeout and, on *connection* errors only, one
    jittered-backoff retry (``web3signer_retries_total{kind}``) — the same
    degrade-and-recover discipline as ``Engine.upcheck``'s cooldown in
    ``execution_layer/engines.py``.  HTTP-level errors (4xx/5xx) are signer
    verdicts and never retried; a duty window is ~4 s, so the backoff is
    capped well below it.
    """

    def __init__(self, base_url: str, timeout: float = 5.0,
                 retries: int = 1, backoff_s: float = 0.2):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = max(0, int(retries))
        self.backoff_s = backoff_s

    def _request(self, req: "urllib.request.Request", kind: str):
        """urlopen + parse with bounded connection-error retries."""
        from .. import fault_injection, metrics

        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                # Jittered backoff: a fleet of VCs hammered by the same
                # signer blip must not retry in lockstep.
                time.sleep(self.backoff_s * (1.0 + random.random()))
                metrics.WEB3SIGNER_RETRIES.inc(kind=kind)
            try:
                fault_injection.check("signer.request")
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                # The signer answered: its verdict stands, no retry.
                raise Web3SignerError(
                    f"signer {e.code}: {e.read().decode(errors='replace')}"
                ) from None
            except (OSError, fault_injection.InjectedFault) as e:
                last = e
        raise Web3SignerError(f"signer unreachable: {last}") from None

    def sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        body = json.dumps({"signing_root": "0x" + bytes(signing_root).hex()}).encode()
        req = urllib.request.Request(
            f"{self.base_url}/api/v1/eth2/sign/0x{bytes(pubkey).hex()}",
            data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        obj = self._request(req, kind="sign")
        try:
            return bytes.fromhex(obj["signature"][2:])
        except (KeyError, TypeError, ValueError) as e:
            raise Web3SignerError(f"malformed signer response: {e}") from None

    def public_keys(self) -> list:
        req = urllib.request.Request(
            f"{self.base_url}/api/v1/eth2/publicKeys", method="GET"
        )
        return [
            bytes.fromhex(s[2:])
            for s in self._request(req, kind="public_keys")
        ]


class MockWeb3Signer:
    """In-process signer holding real secret keys (the Java Web3Signer's
    role in the reference's test rig)."""

    def __init__(self, secret_keys):
        self._keys: Dict[bytes, object] = {
            sk.public_key().to_bytes(): sk for sk in secret_keys
        }
        self.sign_requests = 0
        self._server: Optional[ThreadingHTTPServer] = None

    def start(self) -> "MockWeb3Signer":
        signer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, obj=None):
                body = b"" if obj is None else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                if self.path.endswith("/api/v1/eth2/publicKeys"):
                    self._reply(200, ["0x" + pk.hex() for pk in signer._keys])
                    return
                self._reply(404, {"error": "unknown route"})

            def do_POST(self):
                if "/api/v1/eth2/sign/0x" not in self.path:
                    self._reply(404, {"error": "unknown route"})
                    return
                pk = bytes.fromhex(self.path.rsplit("/0x", 1)[1])
                sk = signer._keys.get(pk)
                if sk is None:
                    self._reply(404, {"error": "unknown key"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(length))
                root = bytes.fromhex(obj["signing_root"][2:])
                signer.sign_requests += 1
                self._reply(200, {"signature": "0x" + sk.sign(root).to_bytes().hex()})

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
