"""Process-wide metrics registry: counters, gauges, histograms, stage timers.

Equivalent of the reference's ``common/lighthouse_metrics`` (lib.rs:1-18 —
thin helpers over a global prometheus registry) plus the hot-path stage
timers the chain inlines throughout import/verification
(``beacon_node/beacon_chain/src/metrics.rs:40-271``).

Design: a plain-Python registry with lock-free-enough updates (single
attribute stores under the GIL), rendered on demand in the Prometheus text
exposition format by the HTTP server's ``/metrics`` route.  No external
dependency; histograms use fixed log-spaced buckets like the reference's
``exponential_buckets``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: "Dict[str, _Metric]" = {}


def _labels_key(labels: Optional[dict]) -> Tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._series: Dict[Tuple, object] = {}
        self._lock = threading.Lock()

    def render(self) -> List[str]:  # pragma: no cover - overridden
        raise NotImplementedError


def _fmt_labels(key: Tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def get(self, **labels) -> float:
        return self._series.get(_labels_key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_labels_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def get(self, **labels) -> float:
        return self._series.get(_labels_key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


# Log-spaced from 1ms to ~65s — the reference's exponential_buckets shape.
DEFAULT_BUCKETS = tuple(0.001 * (2.0 ** i) for i in range(17))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = {"counts": [0] * len(self.buckets), "sum": 0.0, "n": 0}
                self._series[key] = series
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    series["counts"][i] += 1
            series["sum"] += value
            series["n"] += 1

    def time(self, **labels) -> "_HistTimer":
        return _HistTimer(self, labels)

    def stats(self, **labels) -> Tuple[int, float]:
        """(count, total_seconds) for a label set."""
        s = self._series.get(_labels_key(labels))
        return (0, 0.0) if s is None else (s["n"], s["sum"])

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = sorted(
                (key, {"counts": list(s["counts"]), "sum": s["sum"], "n": s["n"]})
                for key, s in self._series.items()
            )
        for key, s in snapshot:
            for i, ub in enumerate(self.buckets):
                lk = key + (("le", repr(ub)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {s['counts'][i]}")
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {s['n']}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {s['sum']}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {s['n']}")
        return out


class _HistTimer:
    """``with HIST.time():`` stage timer (reference ``start_timer``)."""

    def __init__(self, hist: Histogram, labels: dict):
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)
        return False


def _register(metric: _Metric) -> _Metric:
    with _REGISTRY_LOCK:
        existing = _REGISTRY.get(metric.name)
        if existing is not None:
            return existing
        _REGISTRY[metric.name] = metric
        return metric


def counter(name: str, help_text: str = "") -> Counter:
    return _register(Counter(name, help_text))  # type: ignore[return-value]


def gauge(name: str, help_text: str = "") -> Gauge:
    return _register(Gauge(name, help_text))  # type: ignore[return-value]


def histogram(name: str, help_text: str = "", buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    return _register(Histogram(name, help_text, buckets))  # type: ignore[return-value]


def render_prometheus() -> str:
    """The full registry in Prometheus text exposition format."""
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    lines: List[str] = []
    for m in metrics:
        lines.extend(m.render())
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- hot-path
# Chain stage timers (reference beacon_chain/src/metrics.rs:40-271).

BLOCK_IMPORT_SECONDS = histogram(
    "beacon_block_import_seconds", "Full block import pipeline time"
)
BLOCK_STATE_TRANSITION_SECONDS = histogram(
    "beacon_block_state_transition_seconds", "state_transition() inside import"
)
BLOCK_FORK_CHOICE_SECONDS = histogram(
    "beacon_block_fork_choice_seconds", "fork choice on_block + head recompute"
)
EPOCH_PROCESSING_SECONDS = histogram(
    "beacon_epoch_processing_seconds", "per-epoch processing time"
)
ATTESTATION_BATCH_SECONDS = histogram(
    "beacon_attestation_batch_verify_seconds", "device batch signature verification"
)
ATTESTATION_BATCH_SIZE = histogram(
    "beacon_attestation_batch_size", "signature sets per device batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
SIGNATURE_SETS_VERIFIED = counter(
    "beacon_signature_sets_verified_total", "signature sets through the batch verifier"
)
DEVICE_BATCH_INVOCATIONS = counter(
    "beacon_device_batch_invocations_total", "batched device program invocations"
)
HTTP_REQUESTS = counter("http_api_requests_total", "Beacon API requests")
HTTP_REQUEST_SECONDS = histogram("http_api_request_seconds", "Beacon API request time")

# Device batch pipeline stages (reference metrics.rs:247-271 batch setup /
# verify timers) — exactly what TPU perf debugging needs: where a slow batch
# spends its time (host marshalling vs dispatch vs device execution).
DEVICE_BATCH_SETUP_SECONDS = histogram(
    "device_batch_setup_seconds",
    "host-side batch marshalling (validation, hash-to-curve, limb packing)",
)
DEVICE_DISPATCH_SECONDS = histogram(
    "device_batch_dispatch_seconds",
    "async program dispatch (returns before execution completes)",
)
DEVICE_BLOCK_UNTIL_READY_SECONDS = histogram(
    "device_batch_block_until_ready_seconds",
    "wait for device results (the actual device execution window)",
)
DEVICE_VERDICT_SECONDS = histogram(
    "device_batch_verdict_seconds",
    "host-side verdict (W-at-infinity check + final-exp-is-one)",
)

# Additional block import stages (reference metrics.rs:40-161 has ~15).
BLOCK_DA_CHECK_SECONDS = histogram(
    "beacon_block_da_check_seconds", "blob availability check inside import"
)
BLOCK_STORE_WRITE_SECONDS = histogram(
    "beacon_block_store_write_seconds", "block+state persistence inside import"
)
HEAD_RECOMPUTE_SECONDS = histogram(
    "beacon_head_recompute_seconds", "fork-choice get_head + head swap"
)
STATE_ADVANCE_SECONDS = histogram(
    "beacon_state_advance_seconds",
    "tail-of-slot head-state pre-advance (state_advance_timer role)",
)
