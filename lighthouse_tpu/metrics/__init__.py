"""Process-wide metrics registry: counters, gauges, histograms, stage timers.

Equivalent of the reference's ``common/lighthouse_metrics`` (lib.rs:1-18 —
thin helpers over a global prometheus registry) plus the hot-path stage
timers the chain inlines throughout import/verification
(``beacon_node/beacon_chain/src/metrics.rs:40-271``).

Design: a plain-Python registry with lock-free-enough updates (single
attribute stores under the GIL), rendered on demand in the Prometheus text
exposition format by the HTTP server's ``/metrics`` route.  No external
dependency; histograms use fixed log-spaced buckets like the reference's
``exponential_buckets``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: "Dict[str, _Metric]" = {}

# Registration conflicts (same name, different kind or help) recorded for
# scripts/check_metrics.py — the registry itself stays first-wins.
DUPLICATE_REGISTRATIONS: List[Tuple[str, str, str]] = []

# Callbacks run before each render (process metrics and other sampled-on-
# scrape values register here; see system_health.py).
_COLLECTORS: List[Callable[[], None]] = []


def register_collector(fn: Callable[[], None]) -> None:
    with _REGISTRY_LOCK:
        if fn not in _COLLECTORS:
            _COLLECTORS.append(fn)


def _labels_key(labels: Optional[dict]) -> Tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._series: Dict[Tuple, object] = {}
        self._lock = threading.Lock()

    def render(self) -> List[str]:  # pragma: no cover - overridden
        raise NotImplementedError


def _escape_label_value(v) -> str:
    """Escape a label value per the Prometheus text exposition format:
    backslash, double-quote, and line-feed must be escaped inside the
    double-quoted value."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(key: Tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key) + "}"


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def get(self, **labels) -> float:
        with self._lock:
            return self._series.get(_labels_key(labels), 0.0)

    def set_total(self, value: float, **labels) -> None:
        """Overwrite the running total — for collectors mirroring an external
        monotonic counter (e.g. /proc CPU seconds) onto the registry."""
        with self._lock:
            self._series[_labels_key(labels)] = float(value)

    def snapshot(self) -> Dict[Tuple, float]:
        """All series values right now — pair with :meth:`delta` so a check
        inside one run of a long-lived process asserts on THAT run's
        increments, not on the process-cumulative totals."""
        with self._lock:
            return dict(self._series)

    def delta(self, baseline: Dict[Tuple, float], **labels) -> float:
        """This label set's increment since ``baseline`` (a snapshot())."""
        return self.get(**labels) - baseline.get(_labels_key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_labels_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def get(self, **labels) -> float:
        with self._lock:
            return self._series.get(_labels_key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


# Log-spaced from 1ms to ~65s — the reference's exponential_buckets shape.
DEFAULT_BUCKETS = tuple(0.001 * (2.0 ** i) for i in range(17))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = {"counts": [0] * len(self.buckets), "sum": 0.0, "n": 0}
                self._series[key] = series
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    series["counts"][i] += 1
            series["sum"] += value
            series["n"] += 1

    def time(self, **labels) -> "_HistTimer":
        return _HistTimer(self, labels)

    def stats(self, **labels) -> Tuple[int, float]:
        """(count, total_seconds) for a label set."""
        with self._lock:
            s = self._series.get(_labels_key(labels))
            return (0, 0.0) if s is None else (s["n"], s["sum"])

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = sorted(
                (key, {"counts": list(s["counts"]), "sum": s["sum"], "n": s["n"]})
                for key, s in self._series.items()
            )
        for key, s in snapshot:
            for i, ub in enumerate(self.buckets):
                lk = key + (("le", repr(ub)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {s['counts'][i]}")
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {s['n']}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {s['sum']}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {s['n']}")
        return out


class _HistTimer:
    """``with HIST.time():`` stage timer (reference ``start_timer``)."""

    def __init__(self, hist: Histogram, labels: dict):
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)
        return False


class LocalTally:
    """A scope-local metrics view: name → labeled counter totals, next to
    (not instead of) the process-global registry.

    ``telemetry_scope.TelemetryScope`` holds one per node so a fleet run
    can answer "how many journal events did node B emit" without parsing
    process-cumulative series — the per-node precursor of the per-process
    registry the ROADMAP item 2 device-service split needs.  Never
    rendered on ``/metrics``; surfaced through scope snapshots and the
    fleet artifact."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series: Dict[Tuple, float] = {}

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = (name,) + _labels_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def get(self, name: str, **labels) -> float:
        with self._lock:
            return self._series.get((name,) + _labels_key(labels), 0.0)

    def snapshot(self) -> Dict[str, float]:
        """``name{label="v",...} -> total`` in stable sorted order."""
        with self._lock:
            items = sorted(self._series.items())
        return {key[0] + _fmt_labels(key[1:]): v for key, v in items}


def _register(metric: _Metric) -> _Metric:
    with _REGISTRY_LOCK:
        existing = _REGISTRY.get(metric.name)
        if existing is not None:
            if existing.kind != metric.kind or (
                metric.help and existing.help != metric.help
            ):
                DUPLICATE_REGISTRATIONS.append(
                    (metric.name, existing.kind, metric.kind)
                )
            return existing
        _REGISTRY[metric.name] = metric
        return metric


def counter(name: str, help_text: str = "") -> Counter:
    return _register(Counter(name, help_text))  # type: ignore[return-value]


def gauge(name: str, help_text: str = "") -> Gauge:
    return _register(Gauge(name, help_text))  # type: ignore[return-value]


def histogram(name: str, help_text: str = "", buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    return _register(Histogram(name, help_text, buckets))  # type: ignore[return-value]


def render_prometheus() -> str:
    """The full registry in Prometheus text exposition format."""
    # Ensure the standard process-metric collector is registered (lazy: a
    # top-level import would be circular — system_health imports metrics).
    from .. import system_health  # noqa: F401

    with _REGISTRY_LOCK:
        collectors = list(_COLLECTORS)
        metrics = list(_REGISTRY.values())
    for fn in collectors:
        try:
            fn()
        except Exception:
            pass  # a broken collector must never take /metrics down
    lines: List[str] = []
    for m in metrics:
        lines.extend(m.render())
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- hot-path
# Chain stage timers (reference beacon_chain/src/metrics.rs:40-271).

BLOCK_IMPORT_SECONDS = histogram(
    "beacon_block_import_seconds", "Full block import pipeline time"
)
BLOCK_STATE_TRANSITION_SECONDS = histogram(
    "beacon_block_state_transition_seconds", "state_transition() inside import"
)
BLOCK_FORK_CHOICE_SECONDS = histogram(
    "beacon_block_fork_choice_seconds", "fork choice on_block inside import"
)
EPOCH_PROCESSING_SECONDS = histogram(
    "beacon_epoch_processing_seconds", "per-epoch processing time"
)
ATTESTATION_BATCH_SECONDS = histogram(
    "beacon_attestation_batch_verify_seconds", "device batch signature verification"
)
ATTESTATION_BATCH_SIZE = histogram(
    "beacon_attestation_batch_size", "signature sets per device batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
SIGNATURE_SETS_VERIFIED = counter(
    "beacon_signature_sets_verified_total", "signature sets through the batch verifier"
)
DEVICE_BATCH_INVOCATIONS = counter(
    "beacon_device_batch_invocations_total", "batched device program invocations"
)
HTTP_REQUESTS = counter(
    "http_api_requests_total",
    "Beacon API requests, by method and route template",
)
HTTP_REQUEST_SECONDS = histogram(
    "http_api_request_seconds",
    "Beacon API request time, by method and route template",
)

# Device batch pipeline stages (reference metrics.rs:247-271 batch setup /
# verify timers) — exactly what TPU perf debugging needs: where a slow batch
# spends its time (host marshalling vs dispatch vs device execution).
DEVICE_BATCH_SETUP_SECONDS = histogram(
    "device_batch_setup_seconds",
    "host-side batch marshalling (validation, hash-to-curve, limb packing)",
)
DEVICE_DISPATCH_SECONDS = histogram(
    "device_batch_dispatch_seconds",
    "async program dispatch (returns before execution completes)",
)
DEVICE_BLOCK_UNTIL_READY_SECONDS = histogram(
    "device_batch_block_until_ready_seconds",
    "wait for device results (the actual device execution window)",
)
DEVICE_VERDICT_SECONDS = histogram(
    "device_batch_verdict_seconds",
    "host-side verdict (W-at-infinity check + final-exp-is-one)",
)

# Device-layer telemetry (device_telemetry.py): XLA compile-cache
# observability, padding-waste accounting, host-fallback tracking, and
# device memory gauges — the "why was device_batch_wait slow" layer.
DEVICE_PROGRAM_COMPILES = counter(
    "device_program_compiles_total",
    "first-seen (op, bucket shape) jit compilations, by op and shape",
)
DEVICE_PROGRAM_COMPILE_SECONDS = histogram(
    "device_program_compile_seconds",
    "trace+compile time of a first-seen bucket shape (the compiling dispatch)",
)
# Occupancy ratios live in (0, 1]: linear buckets, not the time-spaced set.
OCCUPANCY_BUCKETS = (0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
DEVICE_BATCH_OCCUPANCY_RATIO = histogram(
    "device_batch_occupancy_ratio",
    "live/padded occupancy per device batch, by op and axis (sets|keys)",
    buckets=OCCUPANCY_BUCKETS,
)
DEVICE_BATCH_WASTED_LANES = counter(
    "device_batch_wasted_lanes_total",
    "padding lanes dispatched with no live work, by op and axis (sets|keys)",
)
DEVICE_HOST_FALLBACK = counter(
    "device_batch_host_fallback_total",
    "device batches re-verified entirely on the host, by reason",
)
# AOT warmup (ops/compile_cache.py): standard buckets compiled at startup so
# production traffic never pays a cold XLA compile.  ``outcome`` separates a
# persistent-cache deserialize (hit) from a real compile (miss).
DEVICE_AOT_WARMUP = counter(
    "device_aot_warmup_total",
    "ahead-of-time bucket compilations at startup, by op, shape and outcome (hit|miss)",
)
DEVICE_AOT_WARMUP_SECONDS = histogram(
    "device_aot_warmup_seconds",
    "wall time of one ahead-of-time bucket warmup (lower+compile), by op",
)
DEVICE_MEMORY_BYTES = gauge(
    "device_memory_bytes",
    "device memory_stats() figures sampled on scrape, by device and stat",
)

# Device-execution supervisor (device_supervisor.py): the watchdog /
# split-retry / circuit-breaker layer that keeps a failing device from
# taking block import down with it.
DEVICE_BREAKER_STATE = gauge(
    "device_breaker_state",
    "per-op circuit breaker state (0=closed, 1=open, 2=half_open), by op",
)
DEVICE_BREAKER_TRANSITIONS = counter(
    "device_breaker_transitions_total",
    "circuit breaker state transitions, by op and destination state",
)
DEVICE_DISPATCH_TIMEOUTS = counter(
    "device_dispatch_timeouts_total",
    "device dispatches abandoned by the watchdog deadline, by op",
)
DEVICE_SPLIT_RETRIES = counter(
    "device_batch_split_retries_total",
    "split-batch retries after a transient device error, by op and outcome",
)

# Async device pipeline (device_pipeline.py): the persistent device-worker
# queue that coalesces signature-set groups across work types into maximal
# device batches.  ``pending_sets`` vs ``batch_fill_ratio`` answers "is the
# queue starving the device or the device starving the queue" in one scrape.
DEVICE_PIPELINE_PENDING_SETS = gauge(
    "device_pipeline_pending_sets",
    "signature sets queued in the device pipeline awaiting coalescing, by op",
)
DEVICE_PIPELINE_DEPTH = gauge(
    "device_pipeline_depth",
    "groups queued or in flight in the device pipeline, by op",
)
DEVICE_PIPELINE_BATCH_FILL_RATIO = histogram(
    "device_pipeline_batch_fill_ratio",
    "live sets dispatched / target batch size per coalesced pipeline batch, by op",
    buckets=(0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
)
DEVICE_PIPELINE_LINGER_SECONDS = histogram(
    "device_pipeline_linger_seconds",
    "oldest-submit to batch-take wait per coalesced pipeline batch, by op",
)
DEVICE_PIPELINE_WAIT_SECONDS = histogram(
    "device_pipeline_wait_seconds",
    "submit to future-resolution wait per pipeline group, by op",
)
DEVICE_PIPELINE_BATCHES = counter(
    "device_pipeline_batches_total",
    "coalesced batches executed by the device pipeline, by op",
)
DEVICE_PIPELINE_GROUPS = counter(
    "device_pipeline_groups_total",
    "signature-set groups submitted to the device pipeline, by op and work kind",
)

# Mesh-sharding subsystem (device_mesh.py): the data-parallel device mesh
# the bucketed entry points shard their batch axis over, and the per-device
# breaker layer that shrinks it around a sick chip instead of tripping the
# whole op to host.
DEVICE_MESH_SIZE = gauge(
    "device_mesh_size",
    "devices in the active data-parallel mesh (0 = mesh disabled, "
    "single-device dispatch)",
)
DEVICE_MESH_RESHARDS = counter(
    "device_mesh_reshards_total",
    "mesh topology rebuilds after a per-device breaker trip, by reason",
)
DEVICE_MESH_DEVICE_FAILURES = counter(
    "device_mesh_device_failures_total",
    "device-attributed dispatch failures recorded by the mesh layer, by device",
)
DEVICE_MESH_DEVICE_STATE = gauge(
    "device_mesh_device_breaker_state",
    "per-device mesh breaker state (0=closed, 1=open), by device",
)

# Scheduler queue depth, sampled by the manager loop (reference
# beacon_processor per-queue length gauges): read NEXT TO
# device_pipeline_pending_sets to attribute queue pressure vs batch fill.
BEACON_PROCESSOR_QUEUE_DEPTH = gauge(
    "beacon_processor_queue_depth",
    "events waiting in a priority queue, sampled by the manager, by work class",
)

# Validator-client remote signing (validator_client/web3signer.py).
WEB3SIGNER_RETRIES = counter(
    "web3signer_retries_total",
    "web3signer requests retried after a connection error, by request kind",
)

# SSE event bus (chain/events.py): per-topic delivery vs slow-consumer
# drops.  The drop counter is the SSE backpressure contract: a slow
# subscriber loses events (bounded queue, non-blocking publish) and the
# loss is visible here before a user reports missing heads.
SSE_EVENTS_SENT = counter(
    "http_sse_events_sent_total",
    "server-sent events written to a subscriber stream, by topic",
)
SSE_EVENTS_DROPPED = counter(
    "http_sse_events_dropped_total",
    "server-sent events dropped on a full subscriber queue, by topic",
)

# Checkpoint-keyed HTTP response cache (http_api/response_cache.py): per
# route-template hit/miss (hit rate per route in one PromQL expression),
# invalidations by the chain event that fired them, and occupancy.
HTTP_CACHE_HITS = counter(
    "http_response_cache_hits_total",
    "Beacon API responses served from the checkpoint-keyed cache, by route",
)
HTTP_CACHE_MISSES = counter(
    "http_response_cache_misses_total",
    "cacheable Beacon API requests that missed the cache, by route",
)
HTTP_CACHE_INVALIDATIONS = counter(
    "http_response_cache_invalidations_total",
    "cache entries invalidated by a chain event, by topic",
)
HTTP_CACHE_ENTRIES = gauge(
    "http_response_cache_entries",
    "live entries in the checkpoint-keyed response cache",
)

# In-process fault fabric (network/transport.py Hub): what the seeded
# per-link fault plans and the net.deliver injection point did to traffic.
NET_ENVELOPES_DROPPED = counter(
    "net_envelopes_dropped_total",
    "fabric envelopes not delivered, by reason (unlinked|partition|plan|fault|dead)",
)
NET_ENVELOPES_DELAYED = counter(
    "net_envelopes_delayed_total",
    "fabric envelopes queued for delayed delivery by a link plan",
)
NET_ENVELOPES_DUPLICATED = counter(
    "net_envelopes_duplicated_total",
    "fabric envelopes delivered twice by a link plan",
)
NET_ENVELOPES_REORDERED = counter(
    "net_envelopes_reordered_total",
    "fabric envelopes delivered ahead of earlier-due traffic by a link plan",
)

# Sync hardening (network/sync.py, network/backfill.py): aborted lookups and
# backfill batches retried against a different peer — the churn scenarios'
# evidence that a dead or lying peer cannot stall sync.
SYNC_LOOKUP_ABORTED = counter(
    "sync_lookup_aborted_total",
    "single-block/parent lookups aborted before import, by reason",
)
BACKFILL_BATCH_RETRIES = counter(
    "backfill_batch_retries_total",
    "backfill batches retried against a different peer, by outcome",
)

# Slasher pipeline (slasher/__init__.py drained by network/router.py): every
# slashing the local slasher produced, by kind and what happened to it —
# pooled+gossiped, or stale (its validator was already slashed / the op
# failed chain validation).  The byzantine scenarios' detection evidence.
SLASHER_SLASHINGS = counter(
    "slasher_slashings_total",
    "slashings drained from the local slasher, by kind and outcome",
)

# Additional block import stages (reference metrics.rs:40-161 has ~15).
BLOCK_DA_CHECK_SECONDS = histogram(
    "beacon_block_da_check_seconds", "blob availability check inside import"
)
BLOCK_STORE_WRITE_SECONDS = histogram(
    "beacon_block_store_write_seconds", "block+state persistence inside import"
)
HEAD_RECOMPUTE_SECONDS = histogram(
    "beacon_head_recompute_seconds", "fork-choice get_head + head swap"
)
STATE_ADVANCE_SECONDS = histogram(
    "beacon_state_advance_seconds",
    "tail-of-slot head-state pre-advance (state_advance_timer role)",
)

# Scheduler queue wait: enqueue→drain per work class (reference
# beacon_processor queue latency metrics) — fed by the same seam that
# records the per-trace queue_wait span.
QUEUE_WAIT_SECONDS = histogram(
    "beacon_processor_queue_wait_seconds",
    "enqueue-to-drain wait in the priority queues, by work class",
)

# Slot-relative delay observability (reference block_times_cache +
# metrics.rs beacon_block_delay_* / attestation delay families): every
# figure is measured against the SLOT CLOCK's start of the object's own
# slot, not wall-clock-since-receipt.
BLOCK_ARRIVAL_DELAY_SECONDS = histogram(
    "beacon_block_arrival_delay_seconds",
    "block receipt relative to its own slot start",
)
BLOCK_IMPORTED_DELAY_SECONDS = histogram(
    "beacon_block_imported_delay_seconds",
    "block import completion relative to its own slot start",
)
ATTESTATION_ARRIVAL_DELAY_SECONDS = histogram(
    "beacon_attestation_arrival_delay_seconds",
    "gossip/API attestation application relative to its slot start",
)
