"""Runtime lock sanitizer: proves the static lock graph (ISSUE 18).

The static analysis layer extracts a lock-acquisition order graph
(``scripts/analysis/lock_order_pass.py`` → generated
``lighthouse_tpu/lock_graph.py``) and an ownership registry mapping each
lock to the attributes it guards (``lighthouse_tpu/lock_ownership.py``).
Both are *claims*.  This module is the dynamic cross-check: an opt-in
instrumented-lock layer that records per-thread acquisition sequences
while tests run and turns two classes of divergence into failures:

- **order inversion** — a thread acquires ``B`` while holding ``A`` when
  the committed static graph only proves the ``B -> A`` order (and the
  pair is not listed in ``lock_ownership.SANCTIONED_ORDER_PAIRS``);
- **unguarded write** — a write to a registry-listed attribute on a
  ``guard()``-ed instance while the owning lock is not held by the
  writing thread.

Zero overhead by default: unless ``LIGHTHOUSE_TPU_LOCK_SANITIZE=1`` is
set in the environment *at construction time*, the factories return the
plain ``threading`` primitives — no wrapper, no indirection, asserted by
``tests/test_locksmith.py``.  Construction sites across the concurrent
subsystems route through these factories so flipping the variable
sanitizes the whole tree; ``TimeoutLock`` routes its inner lock here too
(label routing), so the breaker/supervisor/mesh locks participate.

Checks happen at acquire *attempt* time (before blocking), so an
inversion is reported even when it does not happen to deadlock in this
interleaving — that is the point: the sanitizer catches the schedule you
did not get.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from .lock_graph import EDGES as _STATIC_EDGES
from .lock_ownership import LOCK_OWNERSHIP, SANCTIONED_ORDER_PAIRS

ENV_VAR = "LIGHTHOUSE_TPU_LOCK_SANITIZE"

#: Forward edges the static pass proved.  An observed edge (A, B) whose
#: reverse (B, A) is the only statically-proven direction is an inversion.
_EDGE_SET = frozenset(_STATIC_EDGES)


class SanitizerViolation(AssertionError):
    """Raised by ``check()`` when the sanitizer recorded violations."""


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") == "1"


# --------------------------------------------------------------- recording

#: Guards the violation log itself (deliberately a raw primitive: the
#: sanitizer must never recurse into its own bookkeeping).
_LOG_LOCK = threading.Lock()
_VIOLATIONS: List[str] = []
_OBSERVED_EDGES: Dict[Tuple[str, str], str] = {}  # edge -> first witness

_tls = threading.local()


def _held() -> List[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _record(kind: str, detail: str) -> None:
    with _LOG_LOCK:
        _VIOLATIONS.append(f"{kind}: {detail}")


def violations() -> List[str]:
    with _LOG_LOCK:
        return list(_VIOLATIONS)


def observed_edges() -> List[Tuple[str, str]]:
    with _LOG_LOCK:
        return sorted(_OBSERVED_EDGES)


def reset() -> None:
    with _LOG_LOCK:
        _VIOLATIONS.clear()
        _OBSERVED_EDGES.clear()


def check() -> None:
    """Raise ``SanitizerViolation`` if anything was recorded — call at the
    end of a sanitized test so divergence reddens it."""
    vs = violations()
    if vs:
        raise SanitizerViolation(
            f"{len(vs)} lock-sanitizer violation(s):\n" + "\n".join(vs)
        )


def _note_attempt(label: str) -> None:
    """Order check at acquire-attempt time, against the static graph."""
    me = threading.current_thread().name
    for held in _held():
        if held == label:
            continue
        edge = (held, label)
        with _LOG_LOCK:
            _OBSERVED_EDGES.setdefault(edge, me)
        if edge in SANCTIONED_ORDER_PAIRS:
            continue
        if (label, held) in _EDGE_SET and edge not in _EDGE_SET:
            _record(
                "order-inversion",
                f"thread {me!r} acquires {label} while holding {held}, but "
                f"the static lock graph only proves {label} -> {held} "
                "(lock_graph.EDGES); sanction the pair in "
                "lock_ownership.SANCTIONED_ORDER_PAIRS or fix the order",
            )


# ------------------------------------------------------- sanitized wrappers


class _SanitizedLock:
    """``threading.Lock`` semantics + acquisition-sequence recording."""

    _reentrant = False

    def __init__(self, label: str):
        self.label = label
        self._inner = self._make_inner()
        self._owner: Optional[int] = None
        self._count = 0

    def _make_inner(self):
        return threading.Lock()

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident() and self._count > 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reacquire = self._reentrant and self.held_by_me()
        if not reacquire:
            _note_attempt(self.label)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = threading.get_ident()
            self._count += 1
            if not reacquire:
                _held().append(self.label)
        return ok

    def release(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
            held = _held()
            if self.label in held:
                held.remove(self.label)
        self._inner.release()

    def __enter__(self) -> "_SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.label!r}>"


class _SanitizedRLock(_SanitizedLock):
    _reentrant = True

    def _make_inner(self):
        return threading.RLock()

    # Condition integration: an RLock-backed Condition needs these three.
    def _release_save(self):
        count, self._count = self._count, 0
        self._owner = None
        held = _held()
        if self.label in held:
            held.remove(self.label)
        state = self._inner._release_save()
        return (count, state)

    def _acquire_restore(self, saved):
        count, state = saved
        self._inner._acquire_restore(state)
        self._owner = threading.get_ident()
        self._count = count
        _held().append(self.label)

    def _is_owned(self) -> bool:
        return self.held_by_me()


def lock(label: str) -> "threading.Lock":
    """A mutex: plain ``threading.Lock`` unless sanitizing."""
    if not enabled():
        return threading.Lock()
    return _SanitizedLock(label)


def rlock(label: str) -> "threading.RLock":
    if not enabled():
        return threading.RLock()
    return _SanitizedRLock(label)


def condition(label: str) -> "threading.Condition":
    """A ``Condition`` whose underlying lock is label-routed when
    sanitizing (``Condition.wait`` releases and re-acquires through the
    wrapper, so waits never read as order violations)."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(_SanitizedRLock(label))


# --------------------------------------------------------- write guarding

#: class name -> {guarded attr -> owning lock attr}, from the registry.
_ATTR_GUARDS: Dict[str, Dict[str, str]] = {}
for _entry in LOCK_OWNERSHIP.values():
    for _cls, _locks in _entry.get("classes", {}).items():
        _amap = _ATTR_GUARDS.setdefault(_cls, {})
        for _lock_attr, _attrs in _locks.items():
            for _a in _attrs:
                _amap[_a] = _lock_attr

_GUARDED_CACHE: Dict[type, type] = {}


def _lock_held(lk: object) -> Optional[bool]:
    """True/False when ``lk``'s hold state is knowable, None otherwise.
    Unwraps ``TimeoutLock``-style wrappers (duck-typed ``._lock``)."""
    seen = 0
    while not isinstance(lk, _SanitizedLock) and seen < 3:
        inner = getattr(lk, "_lock", None)
        if inner is None:
            return None
        lk, seen = inner, seen + 1
    if isinstance(lk, _SanitizedLock):
        return lk.held_by_me()
    return None


def guard(obj: object, attr_map: Optional[Dict[str, str]] = None) -> object:
    """Swap ``obj``'s class for a write-guarded subclass: every write to a
    registry-listed attribute asserts the owning lock is held by the
    writing thread.  No-op (returns ``obj`` unchanged) when the sanitizer
    is off or the class has no registered attributes.  Apply *after*
    ``__init__`` — construction-time writes are happens-before publish and
    exempt, matching the static race pass."""
    if not enabled():
        return obj
    base = type(obj)
    amap = attr_map if attr_map is not None else _ATTR_GUARDS.get(base.__name__)
    if not amap:
        return obj
    key = base if attr_map is None else (base, tuple(sorted(amap.items())))
    gcls = _GUARDED_CACHE.get(key)
    if gcls is None:

        def __setattr__(self, name, value, _amap=amap, _base=base):
            owner = _amap.get(name)
            if owner is not None:
                held = _lock_held(self.__dict__.get(owner))
                if held is False:
                    _record(
                        "unguarded-write",
                        f"{_base.__name__}.{name} written by thread "
                        f"{threading.current_thread().name!r} without "
                        f"holding {_base.__name__}.{owner} "
                        "(lock_ownership registry)",
                    )
            _base.__setattr__(self, name, value)

        gcls = type(f"_Guarded{base.__name__}", (base,),
                    {"__setattr__": __setattr__, "__module__": base.__module__})
        _GUARDED_CACHE[key] = gcls
    obj.__class__ = gcls
    return obj
