"""The black box: one causally-ordered incident journal + postmortem bundles.

Every prior observability layer answers "what is the system doing NOW" —
``/lighthouse/device`` snapshots, the flight-recorder ring, the trace ring,
the autotune decision log.  What none of them answer is "what happened at
3am": an unattended soak or a TPU-tunnel ``bench.py --campaign`` that trips
a breaker leaves only whatever the bounded rings haven't already evicted,
scattered across per-subsystem surfaces with no causal ordering (PR 11 had
to snapshot at trip time precisely because pre-trip records vanish).

The paper's design makes the fix cheap: every hot path funnels through a
handful of supervised seams, so ONE journal subscribed at those seams can
reconstruct any incident.  This module is that journal plus the freezer:

- :func:`emit` — the seams (breaker transitions and watchdog timeouts in
  ``device_supervisor``, mesh reshards in ``device_mesh``, batch lifecycle
  in ``device_telemetry``/``device_pipeline``, autotune decisions,
  admission sheds, fault-plan firings, scenario timeline events) append
  structured records into one bounded ring.  Each record carries a
  monotonic ``seq`` (the causal order), the logical ``slot`` from the
  ``fault_injection`` slot provider (so virtual-time soaks journal
  deterministically), the active ``trace_id`` (auto-resolved from
  ``tracing``'s contextvar), and — for device batches — the
  flight-recorder ``flight_seq``, so journal, trace trees, and flight
  records cross-reference three ways.
- :func:`capture` — on trigger (breaker OPEN, ``DispatchTimeout``,
  scenario gate failure, campaign phase crash, or a manual
  ``POST /lighthouse/postmortem``) the current journal window is frozen
  to disk together with everything it cross-references: the flight ring,
  the implicated trace trees, breaker/mesh/pipeline/autotune/admission
  snapshots, a metrics dump, the active fault plans, and the log tail.
  Bundles live under newest-K retention and are served by
  ``GET /lighthouse/postmortems``.

Import discipline: this module (like ``autotune.py``) is host-side
plumbing only — importable without jax, enforced by ``test_repo_lints``.
All subsystem snapshots are gathered via lazy imports inside
:func:`capture`, each individually guarded, so a bundle is best-effort
complete rather than all-or-nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import locksmith, metrics, telemetry_scope
from .logs import get_logger

log = get_logger("blackbox")

#: Journal ring capacity.  Sized a comfortable multiple of the flight
#: recorder's default 256 so pre-incident context outlives ring eviction.
JOURNAL_CAPACITY = int(os.environ.get("LIGHTHOUSE_TPU_BLACKBOX_JOURNAL", "4096"))

#: Newest-K postmortem bundles kept on disk (older ones are pruned before
#: each new capture, so a flapping breaker can't fill the disk).
RETAIN = int(os.environ.get("LIGHTHOUSE_TPU_BLACKBOX_RETAIN", "8"))

#: At most this many implicated trace trees ride one bundle (the newest).
MAX_BUNDLE_TRACES = 8

#: Log-ring tail length frozen into each bundle.
BUNDLE_LOG_TAIL = 200

BUNDLE_PREFIX = "postmortem_"


def _default_dir() -> str:
    return os.environ.get(
        "LIGHTHOUSE_TPU_BLACKBOX_DIR",
        os.path.join(os.environ.get("TMPDIR", "/tmp"),
                     "lighthouse_tpu_postmortems"),
    )


BLACKBOX_EVENTS = metrics.counter(
    "blackbox_events_total",
    "incident-journal records appended, by emitting seam",
)
BLACKBOX_CAPTURES = metrics.counter(
    "blackbox_captures_total",
    "postmortem bundles frozen to disk, by trigger reason",
)


# ---------------------------------------------------------------- journal


class Journal:
    """Bounded ring of structured incident records in causal order.

    ``seq`` is assigned under the ring lock, so the sequence numbers ARE
    the causal order of arrival — concurrent emitters serialize here and
    nowhere else (one uncontended lock per record; no I/O, no metrics,
    no imports under the lock).
    """

    def __init__(self, capacity: int = JOURNAL_CAPACITY):
        self.capacity = capacity
        self._buf: deque = deque(maxlen=capacity)
        self._lock = locksmith.lock("Journal._lock")
        self._seq = 0

    def append(self, record: dict) -> dict:
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            self._buf.append(record)
        return record

    def window(self, limit: Optional[int] = None,
               source: Optional[str] = None) -> List[dict]:
        """Oldest→newest records (the whole ring by default)."""
        with self._lock:
            records = list(self._buf)
        if source is not None:
            records = [r for r in records if r.get("source") == source]
        if limit is not None:
            records = records[-max(1, int(limit)):]
        return [dict(r) for r in records]

    @property
    def emitted_total(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


JOURNAL = Journal()


def emit(source: str, event: str, *, trace_id: Optional[str] = None,
         flight_seq=None, link=None, **fields) -> dict:
    """Append one record to the incident journal (the seam entry point).

    ``trace_id`` is auto-resolved from the active span when not given;
    ``slot`` comes from the ``fault_injection`` slot provider (None in
    production, the virtual clock under the scenario runner).  Returns
    the record with its assigned ``seq``.

    When a :mod:`telemetry_scope` is active the record is ALSO mirrored
    into that node's scoped journal, stamped with ``node`` and a Lamport
    ``lamport`` tick — ``merge_journals`` orders the fleet timeline on
    those.  ``link=(origin_node, origin_lamport)`` declares a cross-node
    causal edge (a gossip import linking back to the proposal): the local
    clock ticks past the origin's, so the linked record merges strictly
    after its cause.  ``flight_seq`` accepts the legacy int or the fleet
    ``(node_id, seq)`` pair.
    """
    if trace_id is None:
        from . import tracing

        sp = tracing.current_span()
        if sp is not None:
            trace_id = sp.trace.trace_id
    from . import fault_injection

    record: Dict[str, Any] = {
        "seq": 0,  # assigned under the journal lock
        "t_ms": int(time.time() * 1000),
        "slot": fault_injection.current_slot(),
        "source": source,
        "event": event,
    }
    if trace_id is not None:
        record["trace_id"] = trace_id
    if flight_seq is not None:
        if isinstance(flight_seq, (tuple, list)):
            record["flight_seq"] = [str(flight_seq[0]), int(flight_seq[1])]
        else:
            record["flight_seq"] = int(flight_seq)
    for k, v in fields.items():
        if v is not None:
            record[k] = v
    scope = telemetry_scope.current()
    if scope is not None:
        record["node"] = scope.node_id
        if link is not None:
            record["link"] = [str(link[0]), int(link[1])]
            record["lamport"] = scope.tick(at_least=int(link[1]))
            telemetry_scope.FLEET_TRACE_LINKS.inc(kind="journal-link")
        else:
            record["lamport"] = scope.tick()
        telemetry_scope.FLEET_JOURNAL_EVENTS.inc(node=scope.node_id)
        scope.tally.inc("fleet_journal_events_total", source=source)
    # process-boundary: ok(scope seam: per-node journals via telemetry_scope)
    JOURNAL.append(record)
    if scope is not None:
        # per-node mirror: the copy gets the SCOPED journal's own seq
        scope.journal.append(dict(record))
    BLACKBOX_EVENTS.inc(source=source)
    return record


# ----------------------------------------------------- fleet timeline merge

#: Fields dropped from merged fleet-timeline entries: wall-clock stamps and
#: trace ids contain run-local entropy (``os.urandom`` suffixes, real time)
#: — the merged timeline must be byte-identical across two runs at one
#: seed, so only seed-deterministic fields survive the fold.  Canonical
#: fleet time is the virtual ``slot`` (the fault-injection slot provider),
#: not ``t_ms``.
VOLATILE_FIELDS = frozenset({"t_ms", "trace_id", "remote_trace_id",
                             "flight_seq"})


def merge_key(record: dict):
    """(virtual slot, Lamport clock, node id, per-node seq) — slot-major,
    so cross-slot causality holds by construction and same-slot cross-node
    edges hold via the Lamport tick (see :func:`emit`'s ``link``)."""
    slot = record.get("slot")
    return (
        -1 if slot is None else int(slot),
        int(record.get("lamport", 0)),
        str(record.get("node", "")),
        int(record.get("seq", 0)),
    )


def merge_journals(journals: Dict[str, List[dict]]) -> List[dict]:
    """Fold N per-node journal windows (``node_id -> records``) into ONE
    causally ordered fleet timeline, keyed by :func:`merge_key` with
    :data:`VOLATILE_FIELDS` dropped.  Tolerates empty/partial journals,
    clock skew (per-node Lamport rates differ freely), and a node restart
    resetting its Lamport state (restarted records re-order only within
    their own slot, never across slots)."""
    merged: List[dict] = []
    for node_id, records in journals.items():
        for r in records or ():
            entry = {k: v for k, v in r.items() if k not in VOLATILE_FIELDS}
            entry.setdefault("node", str(node_id))
            merged.append(entry)
    merged.sort(key=merge_key)
    return merged


def fleet_summary(limit: Optional[int] = None) -> dict:
    """The ``GET /lighthouse/fleet`` payload, also frozen into every
    postmortem bundle and SOAK artifact: per-node scope snapshots plus the
    merged fleet timeline over all registered scopes."""
    scopes = telemetry_scope.all_scopes()
    timeline = merge_journals(
        {s.node_id: s.journal.window() for s in scopes})
    if limit is not None:
        timeline = timeline[-max(1, int(limit)):]
    return {
        "nodes": [s.snapshot() for s in scopes],
        "timeline": timeline,
    }


# ------------------------------------------------------- snapshot registry

#: Extra snapshot providers frozen into each bundle (name -> thunk).  The
#: HTTP server registers its admission controller here; anything process-
#: local that a 3am triage would want can join.
_SNAPSHOTTERS: Dict[str, Callable[[], Any]] = {}
# process-boundary: ok(scope seam: snapshot providers re-register per process)
_SNAPSHOTTERS_LOCK = locksmith.lock("blackbox._SNAPSHOTTERS_LOCK")


def register_snapshot(name: str, fn: Callable[[], Any]) -> None:
    with _SNAPSHOTTERS_LOCK:
        # process-boundary: ok(scope seam: per-process registry, see telemetry_scope)
        _SNAPSHOTTERS[name] = fn


def unregister_snapshot(name: str) -> None:
    with _SNAPSHOTTERS_LOCK:
        # process-boundary: ok(scope seam: per-process registry, see telemetry_scope)
        _SNAPSHOTTERS.pop(name, None)


def _safe(fn: Callable[[], Any]) -> Any:
    """A bundle is best-effort complete: a broken section records its error
    instead of aborting the capture (the capture IS the error report)."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — frozen into the bundle
        return {"error": f"{type(e).__name__}: {e}"}


# ----------------------------------------------------------------- capture

#: Serializes captures AND guards the index/dir state.  Module-level (not
#: per-object): captures are rare, seconds-scale events — serializing the
#: whole freeze keeps bundle contents internally consistent.
# process-boundary: ok(scope seam: capture state is per process by design)
_CAPTURE_LOCK = locksmith.lock("blackbox._CAPTURE_LOCK")
_CAPTURE_SEQ = 0
_INDEX: deque = deque(maxlen=64)
_DIR_OVERRIDE: Optional[str] = None
_RETAIN_OVERRIDE: Optional[int] = None


def bundle_dir() -> str:
    return _DIR_OVERRIDE or _default_dir()


def retain() -> int:
    return _RETAIN_OVERRIDE if _RETAIN_OVERRIDE is not None else RETAIN


def configure(directory: Optional[str] = None,
              retain_bundles: Optional[int] = None) -> None:
    """Override the bundle directory / retention (tests, harnesses).
    ``None`` leaves a setting unchanged; :func:`reset_for_tests` restores
    the env defaults."""
    global _DIR_OVERRIDE, _RETAIN_OVERRIDE
    if directory is not None:
        # process-boundary: ok(scope seam: per-process bundle dir override)
        _DIR_OVERRIDE = directory
    if retain_bundles is not None:
        # process-boundary: ok(scope seam: per-process retention override)
        _RETAIN_OVERRIDE = max(1, int(retain_bundles))


def _implicated_traces(journal: List[dict], flight: List[dict]) -> List[dict]:
    """Serialize the newest trace trees the journal/flight window names."""
    ids: List[str] = []
    for r in list(journal) + list(flight):
        tid = r.get("trace_id")
        if tid and tid not in ids:
            ids.append(tid)
    from . import tracing

    trees = []
    for tid in ids[-MAX_BUNDLE_TRACES:]:
        tr = tracing.TRACES.get(tid)
        if tr is not None:
            trees.append(_safe(lambda t=tr: tracing.trace_to_dict(t)))
    return trees


def _gather_snapshots() -> Dict[str, Any]:
    sections: Dict[str, Any] = {}

    def _supervisor():
        from . import device_supervisor

        return device_supervisor.summary()

    def _mesh():
        from . import device_mesh

        return device_mesh.summary()

    def _pipeline():
        from . import device_pipeline

        return device_pipeline.summary()

    def _autotune():
        from . import autotune

        return autotune.snapshot()

    def _telemetry():
        from . import device_telemetry

        return {
            "programs": device_telemetry.COMPILE_CACHE.inventory(),
            "host_fallbacks": device_telemetry.host_fallback_counts(),
            "boundary_primes": device_telemetry.boundary_prime_counts(),
            "flight_recorder": {
                "capacity": device_telemetry.FLIGHT_RECORDER.capacity,
                "stored": len(device_telemetry.FLIGHT_RECORDER),
                "recorded_total":
                    device_telemetry.FLIGHT_RECORDER.recorded_total,
            },
        }

    sections["supervisor"] = _safe(_supervisor)
    sections["mesh"] = _safe(_mesh)
    sections["pipeline"] = _safe(_pipeline)
    sections["autotune"] = _safe(_autotune)
    sections["telemetry"] = _safe(_telemetry)
    with _SNAPSHOTTERS_LOCK:
        extra = dict(_SNAPSHOTTERS)
    for name, fn in extra.items():
        sections[name] = _safe(fn)
    return sections


def _prune_locked(directory: str, keep: int) -> None:
    try:
        names = sorted(
            e for e in os.listdir(directory)
            if e.startswith(BUNDLE_PREFIX) and e.endswith(".json")
        )
    except OSError:
        return
    for stale in names[: max(0, len(names) - keep)]:
        try:
            os.remove(os.path.join(directory, stale))
        except OSError:
            pass


def capture(reason: str, extra: Optional[dict] = None) -> dict:
    """Freeze a correlated postmortem bundle to disk; returns its index
    entry (``path``, ``reason``, counts).  ``reason`` is free-form —
    conventionally ``trigger`` or ``trigger:detail`` (the metric label is
    the part before the colon, keeping cardinality bounded)."""
    global _CAPTURE_SEQ
    reason_label = reason.split(":", 1)[0]
    with _CAPTURE_LOCK:
        # process-boundary: ok(scope seam: capture seq is per process by design)
        _CAPTURE_SEQ += 1
        seq = _CAPTURE_SEQ
        journal = JOURNAL.window()

        def _flight() -> List[dict]:
            from . import device_telemetry

            rec = device_telemetry.FLIGHT_RECORDER
            return rec.recent(limit=rec.capacity)

        flight = _safe(_flight)
        if not isinstance(flight, list):
            flight = [flight]

        def _faults():
            from . import fault_injection

            return fault_injection.summary()

        def _logs():
            from .logs import RING

            return RING.tail(BUNDLE_LOG_TAIL)

        from . import fault_injection

        bundle = {
            "version": 1,
            "reason": reason,
            "capture_seq": seq,
            "t_ms": int(time.time() * 1000),
            "slot": fault_injection.current_slot(),
            "pid": os.getpid(),
            "journal": journal,
            "flight_recorder": flight,
            "traces": _safe(lambda: _implicated_traces(journal, flight)),
            "snapshots": _gather_snapshots(),
            "faults": _safe(_faults),
            "logs_tail": _safe(_logs),
            "metrics": _safe(metrics.render_prometheus),
            "fleet": _safe(fleet_summary),
        }
        if extra is not None:
            bundle["extra"] = extra
        directory = bundle_dir()
        os.makedirs(directory, exist_ok=True)
        _prune_locked(directory, max(0, retain() - 1))
        name = f"{BUNDLE_PREFIX}{bundle['t_ms']:013d}_{seq:04d}_{reason_label}.json"
        path = os.path.join(directory, name)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        index_entry = {
            "capture_seq": seq,
            "reason": reason,
            "t_ms": bundle["t_ms"],
            "slot": bundle["slot"],
            "path": path,
            "journal_records": len(journal),
            "flight_records": len(flight),
            "trace_trees": len(bundle["traces"])
            if isinstance(bundle["traces"], list) else 0,
        }
        # process-boundary: ok(scope seam: capture index is per process by design)
        _INDEX.append(index_entry)
    BLACKBOX_CAPTURES.inc(reason=reason_label)
    log.warning("postmortem bundle captured", reason=reason, path=path,
                journal_records=index_entry["journal_records"],
                flight_records=index_entry["flight_records"])
    # The capture event itself joins the journal AFTER the freeze — it
    # names this bundle in the NEXT bundle's pre-incident context, and a
    # capture can never recurse into itself.
    emit("blackbox", "capture", reason=reason, capture_seq=seq)
    return dict(index_entry)


def captures() -> List[dict]:
    """Index entries of bundles captured by THIS process (newest last)."""
    with _CAPTURE_LOCK:
        return [dict(e) for e in _INDEX]


def bundle_files() -> List[dict]:
    """Bundles currently on disk (any process), newest first."""
    directory = bundle_dir()
    try:
        names = sorted(
            (e for e in os.listdir(directory)
             if e.startswith(BUNDLE_PREFIX) and e.endswith(".json")),
            reverse=True,
        )
    except OSError:
        return []
    out = []
    for n in names:
        p = os.path.join(directory, n)
        try:
            size = os.path.getsize(p)
        except OSError:
            continue
        out.append({"file": n, "path": p, "bytes": size})
    return out


def load_bundle(name: str) -> Optional[dict]:
    """One bundle by file name (no path components accepted)."""
    if os.path.basename(name) != name or not name.startswith(BUNDLE_PREFIX):
        return None
    path = os.path.join(bundle_dir(), name)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def summary() -> dict:
    """The ``GET /lighthouse/postmortems`` payload."""
    return {
        "dir": bundle_dir(),
        "retain": retain(),
        "journal": {
            "capacity": JOURNAL.capacity,
            "stored": len(JOURNAL),
            "emitted_total": JOURNAL.emitted_total,
        },
        "captures": captures(),
        "bundles": bundle_files(),
    }


def reset_for_tests() -> None:
    """Clear journal + capture index and restore env-default dir/retention
    (disk bundles are left alone — tests own their tmp dirs)."""
    global _DIR_OVERRIDE, _RETAIN_OVERRIDE
    # process-boundary: ok(scope seam: test-only reset of per-process state)
    JOURNAL.clear()
    with _CAPTURE_LOCK:
        # process-boundary: ok(scope seam: test-only reset of per-process state)
        _INDEX.clear()
    # process-boundary: ok(scope seam: test-only reset of per-process state)
    _DIR_OVERRIDE = None
    # process-boundary: ok(scope seam: test-only reset of per-process state)
    _RETAIN_OVERRIDE = None
    telemetry_scope.reset_for_tests()
