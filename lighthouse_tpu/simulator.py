"""In-process multi-node simulator.

Equivalent of the reference's ``testing/simulator`` (``basic-sim`` /
``fallback-sim``: N in-process beacon nodes + validator clients on one
runtime, liveness checks per epoch — ``checks.rs`` asserts finalization and
sync participation).  Nodes gossip over the in-process hub fabric; each node
owns a disjoint share of the validator keys and performs its duties locally,
publishing blocks and attestations to the others.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .chain import BeaconChainHarness
from .consensus import helpers as h
from .network.node import LocalNode
from .network.transport import Hub


class SimNode:
    def __init__(self, *, index: int, hub: Optional[Hub], validator_count: int,
                 keys: List[int], genesis_time: int, spec=None,
                 endpoint=None):
        self.index = index
        self.harness = BeaconChainHarness(
            validator_count=validator_count, fake_crypto=True,
            genesis_time=genesis_time, spec=spec,
        )
        self.keys = set(keys)  # validator indices this node runs
        self.node = LocalNode(
            hub=hub, peer_id=f"sim{index}", harness=self.harness,
            endpoint=endpoint,
        )

    @property
    def chain(self):
        return self.harness.chain

    def run_duties(self, slot: int) -> Dict[str, int]:
        """One slot of duties for OUR validators: propose if ours, attest
        with our committee members (published over gossip)."""
        harness, chain = self.harness, self.chain
        spec = harness.spec
        out = {"proposed": 0, "attested": 0}
        state, parent_root = chain.state_at_slot(slot)
        proposer = h.get_beacon_proposer_index(state, spec)
        if proposer in self.keys:
            signed = harness.produce_signed_block(slot=slot)
            chain.process_block(signed)
            self.node.publish_block(signed)
            out["proposed"] = 1
        # committees are epoch-deterministic on the advanced state
        epoch = slot // spec.slots_per_epoch
        committees = h.get_committee_count_per_slot(state, epoch, spec)
        for index in range(committees):
            committee = h.get_beacon_committee(state, slot, index, spec)
            data = chain.produce_attestation_data(slot, index)
            for pos, vidx in enumerate(committee):
                if int(vidx) not in self.keys:
                    continue
                bits = [False] * len(committee)
                bits[pos] = True
                sig = harness.sign_attestation_data(state, data, int(vidx))
                att = harness.types.Attestation(
                    aggregation_bits=bits, data=data, signature=sig.to_bytes()
                )
                try:
                    chain.process_attestation(att)
                except Exception:
                    continue
                self.node.publish_attestation(att)
                out["attested"] += 1
        return out

    def shutdown(self) -> None:
        # sever the fabric links too: live peers must stop delivering into a
        # dead node's inbound queue (unbounded growth otherwise)
        endpoint = self.node.endpoint
        if hasattr(endpoint, "hub"):
            for peer in list(endpoint.connected_peers()):
                endpoint.hub.disconnect(self.node.peer_id, peer)
        self.node.shutdown()


class Simulator:
    """N nodes, validators partitioned round-robin.

    ``transport="hub"`` (default) is the in-process fabric; "tcp_secured"
    runs every node on a real TCP endpoint upgraded through the libp2p
    ladder (multistream -> noise -> yamux).  ``discovery="discv5"`` (tcp
    only) has nodes find each other through a discv5 boot node instead of
    an explicit full mesh — the reference simulator's topology built the
    reference way."""

    def __init__(self, *, node_count: int = 3, validator_count: int = 16,
                 genesis_time: int = 1_600_000_000, spec=None,
                 transport: str = "hub", discovery: Optional[str] = None):
        if transport not in ("hub", "tcp_secured"):
            raise ValueError(f"unknown transport {transport!r}")
        tcp = transport == "tcp_secured"
        self.nodes: List[SimNode] = []
        self.boot_discv5 = None
        self.hub = None if tcp else Hub()
        shares: List[List[int]] = [[] for _ in range(node_count)]
        for v in range(validator_count):
            shares[v % node_count].append(v)

        try:
            for i in range(node_count):
                endpoint = None
                if tcp:
                    from .network.tcp_transport import TcpEndpoint

                    endpoint = TcpEndpoint(f"sim{i}", secured=True)
                self.nodes.append(SimNode(
                    index=i, hub=self.hub, validator_count=validator_count,
                    keys=shares[i], genesis_time=genesis_time, spec=spec,
                    endpoint=endpoint,
                ))
            # topology wiring
            if not tcp:
                for i in range(node_count):
                    for j in range(i + 1, node_count):
                        self.hub.connect(f"sim{i}", f"sim{j}")
            elif discovery == "discv5":
                from .network.discv5 import Discv5Service, KeyPair

                self.boot_discv5 = Discv5Service(KeyPair()).start()
                for n in self.nodes:  # register everyone with the boot node
                    n.node.enable_discv5()
                    n.node.discv5.ping(self.boot_discv5.enr)
                for n in self.nodes:  # then discover + dial over the fabric
                    n.node.discover_peers_discv5([self.boot_discv5.enr],
                                                 max_new=node_count)
            else:
                for i in range(node_count):
                    for j in range(i + 1, node_count):
                        self.nodes[i].node.endpoint.dial(
                            *self.nodes[j].node.endpoint.listen_addr)
        except Exception:
            # wiring failed mid-way: the caller never gets the object, so
            # release every listener/UDP socket/thread created so far
            self.shutdown()
            raise

    def run_slot(self) -> int:
        """Advance every clock one slot and run all duties; returns the slot.
        Raises if gossip fails to converge the heads (a divergence would
        otherwise burn the whole run before the final check reports it)."""
        slot = None
        for n in self.nodes:
            slot = n.harness.advance_slot()
        for n in self.nodes:
            n.run_duties(slot)
        if not self.wait_converged():
            raise AssertionError(f"heads failed to converge at slot {slot}")
        return slot

    def run_epochs(self, epochs: int) -> None:
        spe = self.nodes[0].harness.spec.slots_per_epoch
        for _ in range(epochs * spe):
            self.run_slot()

    def wait_converged(self, timeout: float = 10.0) -> bool:
        """Wait until every node agrees on the head (gossip settled)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            heads = {n.chain.head_root for n in self.nodes}
            if len(heads) == 1:
                return True
            for n in self.nodes:
                n.node.wait_idle()
            # all idle yet diverged: don't busy-spin until the deadline
            time.sleep(0.05)
        return len({n.chain.head_root for n in self.nodes}) == 1

    # ------------------------------------------------------------- checks

    def check_finalization(self, min_epoch: int) -> None:
        """The reference's per-epoch liveness check (checks.rs)."""
        for n in self.nodes:
            f_epoch, _ = n.chain.finalized_checkpoint()
            assert f_epoch >= min_epoch, (
                f"node {n.index} finalized epoch {f_epoch} < {min_epoch}"
            )

    def check_heads_agree(self) -> None:
        heads = {n.chain.head_root for n in self.nodes}
        assert len(heads) == 1, f"heads diverged: {len(heads)} distinct"

    def shutdown(self) -> None:
        for n in self.nodes:
            n.shutdown()
        if self.boot_discv5 is not None:
            self.boot_discv5.stop()
