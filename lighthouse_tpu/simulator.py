"""In-process multi-node simulator.

Equivalent of the reference's ``testing/simulator`` (``basic-sim`` /
``fallback-sim``: N in-process beacon nodes + validator clients on one
runtime, liveness checks per epoch — ``checks.rs`` asserts finalization and
sync participation).  Nodes gossip over the in-process hub fabric; each node
owns a disjoint share of the validator keys and performs its duties locally,
publishing blocks and attestations to the others.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import blackbox, telemetry_scope, tracing
from .chain import BeaconChainHarness
from .consensus import helpers as h
from .network.node import LocalNode
from .network.transport import Hub
from .virtual_clock import WAIT_SLICE_S, ensure_clock


#: Slasher history window for simulator nodes — scenarios span a handful of
#: epochs; the production default (4096) would cost ~12 MB of dense arrays
#: per node for nothing.
SIM_SLASHER_HISTORY = 512


def _sim_slasher_kwargs(spec) -> dict:
    from .slasher import SlasherConfig

    return {
        "enable_slasher": True,
        "slasher_config": SlasherConfig(
            history_length=SIM_SLASHER_HISTORY,
            slots_per_epoch=spec.slots_per_epoch,
        ),
    }


class SimNode:
    def __init__(self, *, index: int, hub: Optional[Hub], validator_count: int,
                 keys: List[int], genesis_time: int, spec=None,
                 endpoint=None, chain=None, peer_id: Optional[str] = None,
                 enable_slasher: bool = False, clock=None):
        self.index = index
        self.clock = clock  # callable or None; threaded into peer scoring
        if chain is not None:
            # Chain-only node (checkpoint-sync join): no duty keys, no
            # harness — it follows the chain over gossip/sync.
            self.harness = None
            self._chain = chain
        else:
            self.harness = BeaconChainHarness(
                validator_count=validator_count, fake_crypto=True,
                genesis_time=genesis_time, spec=spec,
            )
            self._chain = self.harness.chain
        self.keys = set(keys)  # validator indices this node runs
        self._keys_mask: Optional[np.ndarray] = None  # bool over validators
        self.alive = True
        pid = peer_id or f"sim{index}"
        self.scope = telemetry_scope.register(telemetry_scope.TelemetryScope(pid))
        self.node = LocalNode(
            hub=hub, peer_id=pid,
            chain=self._chain, harness=self.harness, endpoint=endpoint,
            scope=self.scope, clock=clock,
            **(_sim_slasher_kwargs(self._chain.spec) if enable_slasher else {}),
        )

    @classmethod
    def resurrect(cls, old: "SimNode", *, hub: Hub) -> "SimNode":
        """A restarted node: same chain, same keys, same peer id, fresh
        network stack (the store survived the crash; the socket did not —
        and an in-memory slasher restarts empty, like the process did)."""
        fresh = cls.__new__(cls)
        fresh.index = old.index
        fresh.harness = old.harness
        fresh._chain = old.chain
        fresh.keys = old.keys
        fresh._keys_mask = None
        fresh.alive = True
        fresh.clock = old.clock
        # Fresh scope: a restarted process starts a NEW Lamport clock (and
        # an empty scoped journal) — merge_journals handles the reset via
        # the slot-major merge key.
        fresh.scope = telemetry_scope.register(
            telemetry_scope.TelemetryScope(old.peer_id))
        fresh.node = LocalNode(
            hub=hub, peer_id=old.peer_id, chain=old.chain, harness=old.harness,
            scope=fresh.scope, clock=old.clock,
            **(_sim_slasher_kwargs(old.chain.spec)
               if old.node.slasher is not None else {}),
        )
        return fresh

    @property
    def chain(self):
        return self._chain

    @property
    def peer_id(self) -> str:
        return self.node.peer_id

    def advance_slot(self) -> int:
        """Advance this node's clock one slot (harness nodes run the
        per-slot chain task too; chain-only nodes just move the clock)."""
        if self.harness is not None:
            return self.harness.advance_slot()
        clock = self.chain.slot_clock
        clock.set_slot((clock.now() or 0) + 1)
        return self.chain.current_slot()

    def run_duties(self, slot: int,
                   skip_validators: Optional[set] = None) -> Dict[str, int]:
        """One slot of duties for OUR validators: propose if ours, attest
        with our committee members (published over gossip).
        ``skip_validators``: indices whose honest duties are suppressed this
        slot — the byzantine controller's seam for replacing a validator's
        honest message with a crafted one (adversary.py).  Suppression
        covers the PROPOSAL duty too, deliberately: a suppressed validator's
        proposer slot goes empty (slightly weakening the honest baseline for
        a few slots) rather than interleaving an extra block whose packing
        races the controller's crafted traffic — determinism outranks the
        marginal baseline fidelity here."""
        out = {"proposed": 0, "attested": 0}
        if self.harness is None or not self.keys:
            return out
        # Duties run under this node's telemetry scope: journal records,
        # flight entries, and log lines emitted below land in the per-node
        # views as well as the process-global rings.
        with telemetry_scope.activate(self.scope):
            self._run_duties_scoped(slot, skip_validators or set(), out)
        return out

    def _run_duties_scoped(self, slot: int, skip: set,
                           out: Dict[str, int]) -> None:
        harness, chain = self.harness, self.chain
        spec = harness.spec
        state, parent_root = chain.state_at_slot(slot)
        proposer = h.get_beacon_proposer_index(state, spec)
        # a slashed validator is still SELECTED as proposer but its block
        # would fail process_block_header everywhere — the slot goes empty,
        # exactly as it would on mainnet
        if (proposer in self.keys and proposer not in skip
                and not state.validators[proposer].slashed):
            signed = harness.produce_signed_block(slot=slot)
            root = signed.message.hash_tree_root().hex()
            # publish_block runs INSIDE the proposal span: the outbound
            # envelope's trace context snapshots the active trace id, which
            # is what lets a remote import's resume_remote tree join back
            # to this proposal in the merged fleet artifact.
            with tracing.span("propose_block", slot=int(slot), root=root,
                              node=self.peer_id, proposer=int(proposer)):
                chain.process_block(signed)
                blackbox.emit("fleet", "block_proposed", slot=int(slot),
                              root=root, proposer=int(proposer))
                body = signed.message.body
                n_slashings = (len(body.attester_slashings)
                               + len(body.proposer_slashings))
                if n_slashings:
                    # the causal tail of the slashing pipeline: an offense
                    # on node A precedes this inclusion on node B in the
                    # merged fleet timeline (slot-major merge key)
                    blackbox.emit("fleet", "slashing_included",
                                  slot=int(slot), root=root,
                                  slashings=int(n_slashings))
                self.node.publish_block(signed)
            out["proposed"] = 1
        # committees are epoch-deterministic on the advanced state.  The
        # membership scan is vectorized: one boolean ownership mask over the
        # registry, one fancy-index per committee — the old per-member
        # Python loop was O(nodes x committees x committee_size) per slot,
        # the scale wall of ROADMAP item 5.  Attestation data is only
        # produced for committees this node actually owns members of, and
        # emission order (committee index ascending, then position
        # ascending) is IDENTICAL to the loop it replaces — the scenario
        # soak's 2-run determinism gate hangs on that.
        epoch = slot // spec.slots_per_epoch
        committees = h.get_committee_count_per_slot(state, epoch, spec)
        own = self._ownership_mask(len(state.validators), skip)
        for index in range(committees):
            committee = np.asarray(
                h.get_beacon_committee(state, slot, index, spec))
            mine = np.nonzero(own[committee])[0]
            if mine.size == 0:
                continue
            data = chain.produce_attestation_data(slot, index)
            for pos in mine:
                pos = int(pos)
                vidx = int(committee[pos])
                bits = [False] * len(committee)
                bits[pos] = True
                sig = harness.sign_attestation_data(state, data, vidx)
                att = harness.types.Attestation(
                    aggregation_bits=bits, data=data, signature=sig.to_bytes()
                )
                try:
                    chain.process_attestation(att)
                except Exception:
                    continue
                self.node.publish_attestation(att)
                out["attested"] += 1

    def _ownership_mask(self, n_validators: int,
                        skip: set) -> np.ndarray:
        """Boolean (n_validators,) mask of validators whose duties this
        node performs this slot: our keys minus the suppressed set.  The
        keys half is cached (the registry only grows); the skip overlay is
        tiny and rebuilt per call."""
        mask = self._keys_mask
        if mask is None or len(mask) < n_validators:
            mask = np.zeros(n_validators, dtype=bool)
            owned = [k for k in self.keys if k < n_validators]
            if owned:
                mask[owned] = True
            self._keys_mask = mask
        own = mask[:n_validators]
        if skip:
            own = own.copy()
            suppressed = [v for v in skip if v < n_validators]
            if suppressed:
                own[suppressed] = False
        return own

    def shutdown(self) -> None:
        # sever the fabric links too: live peers must stop delivering into a
        # dead node's inbound queue (unbounded growth otherwise)
        self.alive = False
        endpoint = self.node.endpoint
        if hasattr(endpoint, "hub"):
            for peer in list(endpoint.connected_peers()):
                endpoint.hub.disconnect(self.node.peer_id, peer)
        self.node.shutdown()
        telemetry_scope.unregister(self.node.peer_id)


class Simulator:
    """N nodes, validators partitioned round-robin.

    ``transport="hub"`` (default) is the in-process fabric; "tcp_secured"
    runs every node on a real TCP endpoint upgraded through the libp2p
    ladder (multistream -> noise -> yamux).  ``discovery="discv5"`` (tcp
    only) has nodes find each other through a discv5 boot node instead of
    an explicit full mesh — the reference simulator's topology built the
    reference way."""

    def __init__(self, *, node_count: int = 3, validator_count: int = 16,
                 genesis_time: int = 1_600_000_000, spec=None,
                 transport: str = "hub", discovery: Optional[str] = None,
                 seed: int = 0, enable_slasher: bool = False,
                 clock=None):
        if transport not in ("hub", "tcp_secured"):
            raise ValueError(f"unknown transport {transport!r}")
        # The control-path clock (virtual_clock.Clock).  None -> WallClock;
        # scenario runs pass a VirtualClock so every deadline, decay, and
        # quiescence window below runs on virtual ticks.  Legacy callables
        # (clock=time.monotonic) are shimmed by ensure_clock.
        self.clock = ensure_clock(clock)
        tcp = transport == "tcp_secured"
        self.genesis_time = genesis_time
        self.validator_count = validator_count
        self.enable_slasher = enable_slasher
        self.nodes: List[SimNode] = []
        self.boot_discv5 = None
        self.hub = None if tcp else Hub(seed=seed)
        if self.hub is not None:
            # ticks = hub ticks: every fabric tick advances the virtual
            # clock (a WallClock advance is a no-op)
            self.hub.on_tick = self.clock.advance
        shares: List[List[int]] = [[] for _ in range(node_count)]
        for v in range(validator_count):
            shares[v % node_count].append(v)

        try:
            for i in range(node_count):
                endpoint = None
                if tcp:
                    from .network.tcp_transport import TcpEndpoint

                    endpoint = TcpEndpoint(f"sim{i}", secured=True)
                self.nodes.append(SimNode(
                    index=i, hub=self.hub, validator_count=validator_count,
                    keys=shares[i], genesis_time=genesis_time, spec=spec,
                    endpoint=endpoint, enable_slasher=enable_slasher,
                    clock=self.clock.now,
                ))
            # topology wiring
            if not tcp:
                for i in range(node_count):
                    for j in range(i + 1, node_count):
                        self.hub.connect(f"sim{i}", f"sim{j}")
            elif discovery == "discv5":
                from .network.discv5 import Discv5Service, KeyPair

                self.boot_discv5 = Discv5Service(KeyPair()).start()
                for n in self.nodes:  # register everyone with the boot node
                    n.node.enable_discv5()
                    n.node.discv5.ping(self.boot_discv5.enr)
                for n in self.nodes:  # then discover + dial over the fabric
                    n.node.discover_peers_discv5([self.boot_discv5.enr],
                                                 max_new=node_count)
            else:
                for i in range(node_count):
                    for j in range(i + 1, node_count):
                        self.nodes[i].node.endpoint.dial(
                            *self.nodes[j].node.endpoint.listen_addr)
        except Exception:
            # wiring failed mid-way: the caller never gets the object, so
            # release every listener/UDP socket/thread created so far
            self.shutdown()
            raise

    @property
    def live_nodes(self) -> List[SimNode]:
        return [n for n in self.nodes if n.alive]

    def run_slot(self, require_converged: bool = True) -> int:
        """Advance every live clock one slot and run all duties; returns the
        slot.  With ``require_converged`` (the happy-path default) raises if
        gossip fails to converge the heads — a divergence would otherwise
        burn the whole run before the final check reports it.  Scenario
        runs pass ``False`` while a fault window is open (partitioned or
        lossy fabrics diverge by design; the convergence GATE runs after
        recovery)."""
        slot = None
        for n in self.live_nodes:
            slot = n.advance_slot()
        for n in self.live_nodes:
            n.run_duties(slot)
            # settle between nodes: whether the NEXT node's attesters see
            # this node's freshly-published block must be a property of
            # the topology, never of thread scheduling (the scenario
            # soak's determinism gate hangs on this)
            self.settle()
        if self.hub is not None:
            # one fabric tick per slot: link-plan latency is slot-granular
            self.hub.advance_tick()
            self.settle()
        # the fabric is quiescent: worker-deferred fleet events are final
        # for this slot — drain them on this (runner) thread
        self.drain_fleet_events()
        if require_converged and not self.wait_converged():
            raise AssertionError(f"heads failed to converge at slot {slot}")
        return slot

    def run_epochs(self, epochs: int, require_converged: bool = True) -> None:
        spe = self.spec.slots_per_epoch
        for _ in range(epochs * spe):
            self.run_slot(require_converged=require_converged)

    @property
    def spec(self):
        for n in self.nodes:
            if n.harness is not None:
                return n.harness.spec
        return self.nodes[0].chain.spec

    def settle(self, timeout: float = 10.0, rounds: int = 2) -> bool:
        """Block until the fabric is quiescent: every live node's inbound
        queue empty, its network loop between envelopes, and its processor
        idle — for ``rounds`` consecutive checks (work can cascade: a
        drained envelope may forward gossip into another node's inbound).

        This, not head equality, is what makes a slot deterministic: the
        next proposer's op pool must hold every attestation the wire
        delivered, or block content races thread scheduling.

        Runs entirely on the injected clock: deadlines are virtual-time
        budgets.  A busy processor is granted a fixed REAL wait slice per
        round (workers need wall time to finish), and the clock is charged
        the equivalent virtual ticks so the budget tracks the waiting
        actually performed — host load can stretch a round's wall time
        without moving the virtual point at which the deadline fires."""
        clock = self.clock
        deadline = clock.now() + timeout
        consecutive = 0
        while consecutive < rounds:
            quiet = True
            for n in self.live_nodes:
                node = n.node
                # unfinished_tasks, not .empty(): the count covers an
                # envelope from the producer's put() until the service
                # loop's task_done() — including the instant it is popped
                # but not yet flagged _processing (the ~1/1000-slot
                # long-horizon determinism race)
                if node.endpoint.inbound.unfinished_tasks or \
                        getattr(node.service, "_processing", False):
                    quiet = False
                if node.sync.busy():  # background lookups still importing
                    quiet = False
                if not node.processor.wait_idle(WAIT_SLICE_S):
                    clock.charge(WAIT_SLICE_S)
                    quiet = False
            if quiet:
                consecutive += 1
            else:
                consecutive = 0
                if clock.now() > deadline:
                    return False
            clock.lull(0.002)
        return True

    def wait_converged(self, timeout: float = 10.0,
                       nodes: Optional[List[SimNode]] = None) -> bool:
        """Wait until every (live) node agrees on the head (gossip settled).
        Pumps the fabric's delayed queue while waiting so plan latency
        cannot deadlock convergence."""
        clock = self.clock
        group = [n for n in (nodes if nodes is not None else self.nodes)
                 if n.alive]
        if not group:
            return True
        deadline = clock.now() + timeout
        while clock.now() < deadline:
            heads = {n.chain.head_root for n in group}
            if len(heads) == 1:
                return True
            for n in group:
                n.node.wait_idle()
            if self.hub is not None and self.hub.pending_delayed():
                self.hub.advance_tick()
            # all idle yet diverged: don't busy-spin until the deadline
            clock.lull(0.05)
        return len({n.chain.head_root for n in group}) == 1

    def drain_fleet_events(self) -> None:
        """Drain worker-deferred fleet journal events into each node's
        scoped journal — on THIS (runner) thread, in stable node order, with
        each scope's stable-sorted batch — so per-node ``seq`` and Lamport
        assignment never depends on worker-thread interleaving (the 2-run
        fleet-timeline determinism gate hangs on this)."""
        for n in sorted(self.live_nodes, key=lambda n: n.peer_id):
            scope = getattr(n, "scope", None)
            if scope is None:
                continue
            events = scope.drain_pending()
            if not events:
                continue
            with telemetry_scope.activate(scope):
                for ev in events:
                    blackbox.emit(ev["source"], ev["event"],
                                  link=ev.get("link"), **ev["fields"])

    # ----------------------------------------------------------- churn

    def kill_node(self, index: int) -> SimNode:
        """Take a node offline (fallback-sim's killed BN): links severed,
        processor down, peer id freed for a later restart."""
        node = self.nodes[index]
        node.shutdown()
        if self.hub is not None:
            self.hub.unregister(node.peer_id)
        return node

    def restart_node(self, index: int) -> SimNode:
        """Bring a killed node back on its own persisted chain: clock
        fast-forwarded to the fleet's slot, fresh network stack, links
        re-dialed — the status handshake then range-syncs it to the head."""
        assert self.hub is not None, "restart is a hub-fabric operation"
        old = self.nodes[index]
        assert not old.alive, f"node {index} is not dead"
        current = max(n.chain.current_slot() for n in self.live_nodes)
        while old.chain.current_slot() < current:
            if old.harness is not None:
                old.harness.advance_slot()
            else:
                old.chain.slot_clock.set_slot(old.chain.current_slot() + 1)
        fresh = SimNode.resurrect(old, hub=self.hub)
        self.nodes[index] = fresh
        for other in self.live_nodes:
            if other is not fresh:
                self.hub.connect(fresh.peer_id, other.peer_id)
        return fresh

    def add_checkpoint_node(self, *, anchor_from: int = 0,
                            peer_id: Optional[str] = None) -> SimNode:
        """A new node joins from a checkpoint anchor (weak subjectivity):
        it boots from ``anchor_from``'s finalized (state, block) pair — no
        genesis replay — and is wired to every live peer; forward sync
        starts on the status handshake, backfill is the caller's second
        step (``BackfillSync``)."""
        assert self.hub is not None, "checkpoint join is a hub-fabric operation"
        from .chain.beacon_chain import BeaconChain
        from .chain.slot_clock import ManualSlotClock

        donor = self.nodes[anchor_from]
        assert donor.harness is not None, "anchor donor must be a full node"
        f_epoch, f_root = donor.chain.finalized_checkpoint()
        assert f_epoch >= 1, "checkpoint join needs a finalized anchor"
        anchor_block = donor.chain.get_block(f_root)
        anchor_state = donor.chain.get_state(f_root).copy()
        clock = ManualSlotClock(self.genesis_time, donor.chain.spec.seconds_per_slot)
        clock.set_slot(donor.chain.current_slot())
        chain = BeaconChain(
            genesis_state=anchor_state, types=donor.harness.types,
            spec=donor.harness.spec, slot_clock=clock,
            anchor_block=anchor_block,
        )
        index = len(self.nodes)
        joined = SimNode(
            index=index, hub=self.hub, validator_count=self.validator_count,
            keys=[], genesis_time=self.genesis_time, chain=chain,
            peer_id=peer_id or f"sim{index}",
            enable_slasher=self.enable_slasher, clock=self.clock.now,
        )
        self.nodes.append(joined)
        for other in self.live_nodes:
            if other is not joined:
                self.hub.connect(joined.peer_id, other.peer_id)
        return joined

    # ------------------------------------------------------------- checks

    def check_finalization(self, min_epoch: int) -> None:
        """The reference's per-epoch liveness check (checks.rs)."""
        for n in self.live_nodes:
            f_epoch, _ = n.chain.finalized_checkpoint()
            assert f_epoch >= min_epoch, (
                f"node {n.index} finalized epoch {f_epoch} < {min_epoch}"
            )

    def check_heads_agree(self) -> None:
        heads = {n.chain.head_root for n in self.live_nodes}
        assert len(heads) == 1, f"heads diverged: {len(heads)} distinct"

    def shutdown(self) -> None:
        for n in self.nodes:
            if n.alive:
                n.shutdown()
        if self.boot_discv5 is not None:
            self.boot_discv5.stop()
