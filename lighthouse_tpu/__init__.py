"""lighthouse-tpu: a TPU-native Ethereum consensus-layer framework."""

__version__ = "0.2.0"
