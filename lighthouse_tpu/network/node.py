"""In-process beacon node: chain + scheduler + network service + router +
sync, wired on the hub fabric.

The building block of the N-node simulator (reference:
``testing/node_test_rig`` ``LocalBeaconNode`` + ``testing/simulator``).
"""

from __future__ import annotations

from typing import Optional

from ..chain import BeaconChain, BeaconChainHarness
from ..scheduler import BeaconProcessor
from . import topics as topics_mod
from .peer_manager import PeerAction
from .router import Router
from .service import NetworkService
from .sync import SyncManager
from .transport import Hub


class LocalNode:
    def __init__(
        self,
        *,
        hub: Optional[Hub] = None,
        peer_id: str,
        harness: Optional[BeaconChainHarness] = None,
        chain: Optional[BeaconChain] = None,
        max_workers: int = 2,
        bls_backend: Optional[str] = None,
        enable_slasher: bool = False,
        slasher_config=None,
        endpoint=None,
        subscribe_all_subnets: bool = True,
        scope=None,
        clock=None,
    ):
        if harness is not None:
            chain = harness.chain
        assert chain is not None
        if bls_backend is not None:
            # Node assembly selects the execution backend ("jax" = the batched
            # device multi-pairing program); tests pass None to keep whatever
            # the harness configured (fake/host).
            from ..crypto.bls.backends import set_backend

            set_backend(bls_backend)
        self.harness = harness
        self.chain = chain
        self.peer_id = peer_id
        # transport seam: in-process hub (simulators) or a provided endpoint
        # (e.g. TcpEndpoint — two OS processes over sockets)
        if endpoint is not None:
            self.endpoint = endpoint
        else:
            assert hub is not None, "pass hub= or endpoint="
            self.endpoint = hub.register(peer_id)
        # Node telemetry scope: stamps outbound envelopes with this node's
        # trace context and receives deferred fleet-journal events.
        self.scope = scope
        self.endpoint.scope = scope
        # clock: optional callable threaded into peer scoring so decay and
        # ban lifts run on the simulator's virtual clock during scenarios
        self.service = NetworkService(self.endpoint, clock=clock)
        self.processor = BeaconProcessor(max_workers=max_workers)
        self.slasher = None
        if enable_slasher:
            from ..slasher import Slasher, SlasherConfig

            self.slasher = Slasher(
                chain.types,
                slasher_config
                or SlasherConfig(slots_per_epoch=chain.spec.slots_per_epoch),
            )
        self.router = Router(
            chain=chain, service=self.service, processor=self.processor,
            slasher=self.slasher, scope=scope,
        )
        self.sync = SyncManager(chain=chain, service=self.service, router=self.router)
        digest = self.router.fork_digest
        fork = type(chain.genesis_state).fork_name
        for topic in topics_mod.core_topics(digest, fork, chain.spec):
            self.service.subscribe(str(topic))
        # Attestation/sync subnets go through the subnet service (reference
        # subnet_service/): backbone rotation + VC duty subscriptions.
        # subscribe_all (the --subscribe-all-subnets flag) is the right
        # default for small in-process networks, where 2 backbone subnets
        # per node would partition subnet traffic.
        import hashlib as _hashlib

        from .subnet_service import SubnetService

        self.subnets = SubnetService(
            service=self.service, digest=digest, spec=chain.spec,
            node_id=int.from_bytes(
                _hashlib.sha256(peer_id.encode()).digest(), "big"),
            subscribe_all=subscribe_all_subnets,
        )
        if not subscribe_all_subnets:
            self.subnets.update_epoch(0)

    # ----------------------------------------------------------- discovery

    def enable_discv5(self, keypair=None):
        """Attach a discv5-over-UDP discovery service whose ENR advertises
        BOTH our discovery (udp) port and the TCP fabric listen port — the
        reference node's discovery/transport split (discv5 finds peers,
        libp2p dials them)."""
        from .discv5 import Discv5Service, KeyPair
        from .discv5.enr import ENR

        self.discv5 = Discv5Service(keypair or KeyPair())
        host, tcp_port = self.endpoint.listen_addr
        # Advertise the FABRIC's host for the tcp entry (falling back to
        # the discovery socket's when the fabric binds a wildcard) — peers
        # dial what the ENR says.
        ip = self.discv5.ip if host in ("0.0.0.0", "") else host
        from .subnet_service import attnets_bitfield

        # The spec keys compute_subscribed_subnets to the DISCOVERY node id
        # so peers can predict our backbone subnets from the ENR — re-seed
        # the subnet service BEFORE minting the ENR, or the record would
        # advertise the stale (peer-id-derived) backbone.
        self.subnets.node_id = int.from_bytes(self.discv5.node_id, "big")
        if not self.subnets.subscribe_all:
            self.subnets.update_epoch(
                self.chain.current_slot() // self.chain.spec.slots_per_epoch)
        active = self.subnets.active_attestation_subnets()
        sync_active = self.subnets.active_sync_subnets()
        self._enr_ip, self._enr_tcp = ip, tcp_port
        self._advertised_subnets = (set(active), set(sync_active))
        self.discv5.enr = ENR.build(
            self.discv5.keypair, seq=1, ip=ip,
            udp=self.discv5.port, tcp=tcp_port,
            extra={b"attnets": attnets_bitfield(active),
                   b"syncnets": attnets_bitfield(
                       sync_active, self.chain.spec.sync_committee_subnet_count)},
        )
        # the SAME bits in req/resp metadata — one encoder, so the two
        # advertisements cannot drift
        self.router.metadata.attnets = int.from_bytes(
            attnets_bitfield(active), "little")
        self.router.metadata.syncnets = int.from_bytes(
            attnets_bitfield(sync_active,
                             self.chain.spec.sync_committee_subnet_count),
            "little")
        self.router.metadata.seq_number += 1
        # Seed the routing table from the persisted DHT (persisted_dht.rs:
        # a restarted node re-joins without fresh bootstrap rounds).
        from .persisted_dht import load_dht

        for enr in load_dht(self.chain.store):
            try:
                self.discv5.add_enr(enr)
            except Exception:
                continue  # one stale record must not stop discovery
        self.discv5.start()
        return self.discv5

    def refresh_subnet_advertisement(self) -> bool:
        """Re-mint the ENR (seq+1) and bump MetaData.seq_number when the
        active subnet set changed (backbone rotation / duty expiry) — a
        stale record makes peers dial us for subnets we left.  Called from
        the per-slot tick; returns True when a refresh happened."""
        if getattr(self, "discv5", None) is None:
            return False
        from .discv5.enr import ENR
        from .subnet_service import attnets_bitfield

        active = set(self.subnets.active_attestation_subnets())
        sync_active = set(self.subnets.active_sync_subnets())
        if (active, sync_active) == self._advertised_subnets:
            return False
        self._advertised_subnets = (active, sync_active)
        self.discv5.enr = ENR.build(
            self.discv5.keypair, seq=self.discv5.enr.seq + 1,
            ip=self._enr_ip, udp=self.discv5.port, tcp=self._enr_tcp,
            extra={b"attnets": attnets_bitfield(active),
                   b"syncnets": attnets_bitfield(
                       sync_active, self.chain.spec.sync_committee_subnet_count)},
        )
        self.router.metadata.attnets = int.from_bytes(
            attnets_bitfield(active), "little")
        self.router.metadata.syncnets = int.from_bytes(
            attnets_bitfield(sync_active,
                             self.chain.spec.sync_committee_subnet_count),
            "little")
        self.router.metadata.seq_number += 1
        return True

    def _dial_new_addrs(self, addrs, max_new: int) -> int:
        """Dial every address not already known, up to ``max_new`` — the
        shared tail of both discovery flavors."""
        endpoint = self.endpoint
        known = set(endpoint.known_peer_addrs().values())
        known.add(tuple(endpoint.listen_addr))
        dialed = 0
        for addr in addrs:
            if addr in known:
                continue
            try:
                endpoint.dial(*addr, timeout=3.0)
                known.add(addr)
                dialed += 1
            except Exception:
                continue  # stale address: skip
            if dialed >= max_new:
                break
        return dialed

    def discover_peers_discv5(self, boot_enrs, max_new: int = 8,
                              prefer_subnets=None) -> int:
        """One discv5 discovery round: bootstrap FINDNODE sweeps against the
        boot ENRs, then dial discovered records that advertise a TCP port —
        records advertising any of ``prefer_subnets`` in their attnets
        field first (reference discovery/subnet_predicate.rs; defaults to
        our own active subnets when running a real backbone).  Returns
        #dialed."""
        from .discv5 import rlp as discv5_rlp
        from .subnet_service import subnet_predicate

        if getattr(self, "discv5", None) is None:
            return 0
        if prefer_subnets is None and not self.subnets.subscribe_all:
            prefer_subnets = self.subnets.active_attestation_subnets()
        for boot in boot_enrs:
            try:
                self.discv5.bootstrap(boot)
            except Exception:
                continue
        preferred, rest = [], []
        for enr in list(self.discv5.table.values()):
            tcp_raw = enr.pairs.get(b"tcp")
            ip = enr.ip()
            if not tcp_raw or ip is None:
                continue
            try:
                addr = (ip, discv5_rlp.decode_uint(tcp_raw))
            except Exception:
                continue  # one malformed record must not veto the round
            (preferred if subnet_predicate(enr, prefer_subnets or ())
             else rest).append(addr)
        return self._dial_new_addrs(preferred + rest, max_new)

    def discover_peers(self, max_new: int = 8) -> int:
        """One discovery round (the FINDNODE sweep a discv5 node runs):
        ask every connected peer — boot nodes included — for the listen
        addresses they know, dial the unknown ones.  Returns #dialed.
        Requires a socket-backed endpoint (TcpEndpoint)."""
        from . import rpc as rpc_mod

        endpoint = self.endpoint
        if not hasattr(endpoint, "dial"):
            return 0  # in-process hub: topology is explicit
        dialed = 0
        for peer in list(endpoint.connected_peers()):
            if dialed >= max_new:
                break  # stop issuing RPCs once the round's budget is met
            try:
                chunks = self.service.request(
                    peer, rpc_mod.PEER_EXCHANGE,
                    rpc_mod.PeerExchangeRequest(max_peers=64),
                )
            except rpc_mod.RpcError:
                continue
            addrs = []
            for result, payload, _ctx in chunks:
                if result != rpc_mod.SUCCESS:
                    continue
                try:
                    entries = rpc_mod.decode_peer_entries(payload)
                except Exception:
                    # one malformed answer must not veto the whole round
                    self.service.peer_manager.report(
                        peer, PeerAction.LOW_TOLERANCE, "bad peer-exchange payload"
                    )
                    continue
                addrs.extend(
                    (e.host, e.port) for e in entries
                    if e.peer_id != self.peer_id
                )
            dialed += self._dial_new_addrs(addrs, max_new - dialed)
        return dialed

    # ------------------------------------------------------------ publish

    def publish_block(self, signed_block) -> int:
        topic = topics_mod.GossipTopic(self.router.fork_digest, topics_mod.BEACON_BLOCK)
        n = self.service.publish(str(topic), signed_block.as_ssz_bytes())
        # A locally-produced block may have queued LC updates at import —
        # publish them now rather than waiting for the next gossip block.
        self.router._publish_light_client_updates()
        return n

    def publish_blob_sidecar(self, sidecar) -> int:
        subnet = int(sidecar.index) % self.chain.spec.max_blobs_per_block
        topic = topics_mod.GossipTopic(
            self.router.fork_digest, f"{topics_mod.BLOB_SIDECAR_PREFIX}{subnet}"
        )
        return self.service.publish(str(topic), sidecar.as_ssz_bytes())

    def publish_operation(self, kind: str, op) -> int:
        """Gossip a pool operation on its global topic (voluntary_exit /
        proposer_slashing / attester_slashing / bls_to_execution_change)."""
        topic = topics_mod.GossipTopic(self.router.fork_digest, kind)
        return self.service.publish(str(topic), op.as_ssz_bytes())

    def publish_attestation(self, attestation) -> int:
        subnet = topics_mod.compute_subnet_for_attestation(
            self.chain.head_state,
            int(attestation.data.slot),
            int(attestation.data.index),
            self.chain.spec,
        )
        topic = topics_mod.attestation_subnet_topic(self.router.fork_digest, subnet)
        return self.service.publish(str(topic), attestation.as_ssz_bytes())

    def wait_idle(self, timeout: float = 10.0) -> bool:
        return self.processor.wait_idle(timeout)

    def shutdown(self) -> None:
        # Goodbye(1 = client shutdown) to every peer BEFORE tearing the
        # stack down (reference: lighthouse sends Goodbye on shutdown so
        # peers drop the connection cleanly instead of scoring a timeout).
        from . import rpc as rpc_mod
        from .transport import Envelope

        goodbye = rpc_mod.Goodbye(reason=1)
        for peer in list(self.endpoint.connected_peers()):
            try:
                self.endpoint.send(peer, Envelope(
                    kind="rpc_request", sender=self.peer_id,
                    protocol=rpc_mod.GOODBYE, request_id=0,
                    data=rpc_mod.encode_request(rpc_mod.GOODBYE, goodbye),
                ))
            except Exception:
                continue  # best-effort PER PEER; one failure must not
                # silence the goodbye to everyone else
        self.service.shutdown()
        self.router.reprocess.shutdown()
        # unhook from the chain: a restarted node (SimNode.resurrect, same
        # chain object) must not leave imports feeding a dead queue
        try:
            self.chain.block_imported_hooks.remove(
                self.router.reprocess.block_imported)
        except ValueError:
            pass
        self.processor.shutdown()
        if getattr(self, "discv5", None) is not None:
            # persist the routing table for the next start (persisted_dht.rs)
            try:
                from .persisted_dht import persist_dht

                persist_dht(self.chain.store, list(self.discv5.table.values()))
            except Exception:
                pass  # persistence is best-effort; shutdown must proceed
            self.discv5.stop()
        if hasattr(self.endpoint, "close"):
            self.endpoint.close()  # socket-backed endpoints own OS resources
