"""Persist the discv5 routing table across restarts.

Equivalent of the reference's ``beacon_node/network/src/persisted_dht.rs``:
on shutdown the node writes every ENR it knows to the store's DHT column;
on startup discovery seeds its table from them, so a restarted node
re-joins the network without waiting for fresh bootstrap rounds.

Wire format: concatenated ``u16-be length || ENR rlp`` records under the
all-zero key (the reference uses Hash256::zero() in its own column).
"""

from __future__ import annotations

import struct
from typing import List

from ..store.kv import DBColumn

DHT_DB_KEY = b"\x00" * 32


def persist_dht(store, enrs: List) -> int:
    """Write ``enrs`` to the DHT column; returns the count written."""
    out = bytearray()
    n = 0
    for enr in enrs:
        rlp = enr.to_rlp()
        if len(rlp) > 0xFFFF:
            continue  # spec caps ENRs at 300 bytes; refuse anything absurd
        out += struct.pack(">H", len(rlp)) + rlp
        n += 1
    store.put(DBColumn.DHT, DHT_DB_KEY, bytes(out))
    return n


def load_dht(store) -> List:
    """Read the persisted ENRs (empty list when absent or corrupt — a bad
    record must never stop node startup)."""
    from .discv5.enr import ENR

    raw = store.get(DBColumn.DHT, DHT_DB_KEY)
    if not raw:
        return []
    enrs = []
    pos = 0
    try:
        while pos + 2 <= len(raw):
            (n,) = struct.unpack_from(">H", raw, pos)
            pos += 2
            if pos + n > len(raw):
                break
            enrs.append(ENR.from_rlp(raw[pos:pos + n]))
            pos += n
    except Exception:
        return enrs  # keep whatever decoded cleanly
    return enrs


def clear_dht(store) -> None:
    store.delete(DBColumn.DHT, DHT_DB_KEY)
