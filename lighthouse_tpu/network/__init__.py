"""Networking: gossip pub/sub, req/resp RPC, peer management, sync
(reference: ``beacon_node/lighthouse_network`` + ``beacon_node/network``)."""

from . import rpc, snappy_codec, topics
from .node import LocalNode
from .peer_manager import PeerAction, PeerManager
from .router import Router
from .service import NetworkService, message_id
from .sync import SyncManager, SyncState
from .transport import Envelope, Hub

__all__ = [
    "Envelope",
    "Hub",
    "LocalNode",
    "NetworkService",
    "PeerAction",
    "PeerManager",
    "Router",
    "SyncManager",
    "SyncState",
    "message_id",
    "rpc",
    "snappy_codec",
    "topics",
]
