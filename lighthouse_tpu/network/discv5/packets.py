"""discv5.1 wire packets: masked headers, three flags, AES-GCM messages.

Packet layout (discv5-wire.md):

    packet        = masking-iv || masked-header || message
    masked-header = aesctr_encrypt(masking-key, masking-iv, header)
    masking-key   = dest-node-id[:16]
    header        = static-header || authdata
    static-header = "discv5" || version(0x0001) || flag || nonce(12) || authdata-size(2)

Flags: 0 ordinary (authdata = src-node-id), 1 WHOAREYOU (authdata =
id-nonce(16) || enr-seq(8), no message), 2 handshake (authdata =
src-node-id || sig-size || eph-key-size || id-signature || eph-pubkey ||
[ENR]).  Messages are AES-GCM with the session key, the header nonce, and
``masking-iv || header`` as associated data."""

from __future__ import annotations

import os
import secrets
from dataclasses import dataclass, field
from typing import Optional, Tuple

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

PROTOCOL_ID = b"discv5"
VERSION = 0x0001

FLAG_ORDINARY = 0
FLAG_WHOAREYOU = 1
FLAG_HANDSHAKE = 2

STATIC_HEADER_LEN = 6 + 2 + 1 + 12 + 2


class PacketError(Exception):
    pass


def _aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


@dataclass
class Header:
    flag: int
    nonce: bytes  # 12 bytes
    authdata: bytes

    def encode(self) -> bytes:
        return (
            PROTOCOL_ID
            + VERSION.to_bytes(2, "big")
            + bytes([self.flag])
            + self.nonce
            + len(self.authdata).to_bytes(2, "big")
            + self.authdata
        )


@dataclass
class Packet:
    masking_iv: bytes
    header: Header
    message_ct: bytes  # empty for WHOAREYOU

    @property
    def challenge_data(self) -> bytes:
        """masking-iv || static-header || authdata — the handshake binds its
        id-signature and session keys to this exact WHOAREYOU bytes."""
        return self.masking_iv + self.header.encode()


def encode_packet(dest_node_id: bytes, header: Header, message_ct: bytes = b"",
                  masking_iv: Optional[bytes] = None) -> bytes:
    if masking_iv is None:
        masking_iv = os.urandom(16)
    masked = _aes_ctr(dest_node_id[:16], masking_iv, header.encode())
    return masking_iv + masked + message_ct


def decode_packet(local_node_id: bytes, datagram: bytes) -> Packet:
    if len(datagram) < 16 + STATIC_HEADER_LEN:
        raise PacketError("datagram too short")
    masking_iv = datagram[:16]
    cipher = Cipher(algorithms.AES(local_node_id[:16]), modes.CTR(masking_iv))
    dec = cipher.decryptor()
    static = dec.update(datagram[16:16 + STATIC_HEADER_LEN])
    if static[:6] != PROTOCOL_ID:
        raise PacketError("bad protocol id")
    if int.from_bytes(static[6:8], "big") != VERSION:
        raise PacketError("unsupported version")
    flag = static[8]
    nonce = static[9:21]
    authdata_size = int.from_bytes(static[21:23], "big")
    start = 16 + STATIC_HEADER_LEN
    if len(datagram) < start + authdata_size:
        raise PacketError("truncated authdata")
    authdata = dec.update(datagram[start:start + authdata_size])
    message_ct = datagram[start + authdata_size:]
    return Packet(masking_iv, Header(flag, nonce, authdata), message_ct)


# ------------------------------------------------------------- authdata


def ordinary_authdata(src_node_id: bytes) -> bytes:
    return src_node_id


def whoareyou_authdata(id_nonce: bytes, enr_seq: int) -> bytes:
    return id_nonce + enr_seq.to_bytes(8, "big")


def parse_whoareyou(authdata: bytes) -> Tuple[bytes, int]:
    if len(authdata) != 24:
        raise PacketError("bad whoareyou authdata")
    return authdata[:16], int.from_bytes(authdata[16:], "big")


def handshake_authdata(src_node_id: bytes, id_signature: bytes,
                       eph_pubkey: bytes, enr_rlp: bytes = b"") -> bytes:
    return (
        src_node_id
        + bytes([len(id_signature), len(eph_pubkey)])
        + id_signature
        + eph_pubkey
        + enr_rlp
    )


def parse_handshake(authdata: bytes) -> Tuple[bytes, bytes, bytes, bytes]:
    """(src_node_id, id_signature, eph_pubkey, enr_rlp)."""
    if len(authdata) < 34:
        raise PacketError("handshake authdata too short")
    src = authdata[:32]
    sig_size, key_size = authdata[32], authdata[33]
    pos = 34
    sig = authdata[pos:pos + sig_size]
    pos += sig_size
    eph = authdata[pos:pos + key_size]
    pos += key_size
    if len(sig) != sig_size or len(eph) != key_size:
        raise PacketError("truncated handshake authdata")
    return src, sig, eph, authdata[pos:]


# -------------------------------------------------------------- messages


def encrypt_message(key: bytes, nonce: bytes, plaintext: bytes, ad: bytes) -> bytes:
    return AESGCM(key).encrypt(nonce, plaintext, ad)


def decrypt_message(key: bytes, nonce: bytes, ciphertext: bytes, ad: bytes) -> bytes:
    return AESGCM(key).decrypt(nonce, ciphertext, ad)


def random_nonce() -> bytes:
    return secrets.token_bytes(12)


def random_id_nonce() -> bytes:
    return secrets.token_bytes(16)
