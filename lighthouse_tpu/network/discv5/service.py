"""The discv5 UDP node: sessions, handshake state machine, routing table.

Mirrors the role of the reference's ``discv5`` crate as driven by
``beacon_node/lighthouse_network/src/discovery/mod.rs``: nodes hold signed
ENRs, talk over masked UDP packets, establish AES-GCM sessions via the
WHOAREYOU handshake, answer PING/FINDNODE, and discover peers by querying
FINDNODE at descending log2-distances.

Threading model: one receive thread per service; requests are synchronous
with per-request events (discovery is control-plane traffic — latency, not
throughput)."""

from __future__ import annotations

import secrets
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ...logs import get_logger
from . import packets, rlp, secp256k1, session as session_mod
from .enr import ENR, EnrError, KeyPair

log = get_logger("discv5")

MSG_PING = 0x01
MSG_PONG = 0x02
MSG_FINDNODE = 0x03
MSG_NODES = 0x04

MAX_NODES_PER_PACKET = 3  # ENRs per NODES response (wire budget, spec ~4)
REQUEST_TIMEOUT = 3.0


class Discv5Error(Exception):
    pass


@dataclass
class Session:
    send_key: bytes
    recv_key: bytes


@dataclass
class _PendingRequest:
    message: bytes                    # full plaintext (type || rlp)
    request_id: bytes
    event: threading.Event = field(default_factory=threading.Event)
    responses: List = field(default_factory=list)
    total_expected: int = 1
    created: float = field(default_factory=time.monotonic)

PENDING_TTL = 10.0     # s: un-answered handshake elicitations
CHALLENGE_TTL = 30.0   # s: WHOAREYOU challenges we issued
MAX_ADDRS = 4096       # spoofed src-id flood bound


def _enr_to_item(enr: ENR):
    return rlp.decode(enr.to_rlp())


def _enr_from_item(item) -> ENR:
    return ENR.from_rlp(rlp.encode(item))


def log2_distance(a: bytes, b: bytes) -> int:
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


class Discv5Service:
    def __init__(self, keypair: Optional[KeyPair] = None, *,
                 ip: str = "127.0.0.1", port: int = 0):
        self.keypair = keypair or KeyPair()
        self.node_id = self.keypair.node_id
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((ip, port))
        self._sock.settimeout(0.2)
        self.ip, self.port = self._sock.getsockname()
        self.enr = ENR.build(self.keypair, seq=1, ip=self.ip, udp=self.port)
        # sessions + handshake state
        self._sessions: Dict[bytes, Session] = {}          # node-id -> keys
        self._pending: Dict[bytes, _PendingRequest] = {}   # nonce -> request
        self._requests: Dict[bytes, _PendingRequest] = {}  # request-id -> req
        self._challenges: Dict[bytes, Tuple[packets.Packet, float]] = {}  # node-id -> (WHOAREYOU, ts)
        self._addrs: Dict[bytes, Tuple[str, int]] = {}     # node-id -> addr
        # routing table: node-id -> ENR (flat; bucketized on query)
        self.table: Dict[bytes, ENR] = {}
        self._lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Discv5Service":
        self._running = True
        self._thread = threading.Thread(
            target=self._rx_loop, daemon=True,
            name=f"discv5-{self.node_id.hex()[:8]}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._sock.close()

    # ------------------------------------------------------------- messages

    @staticmethod
    def _encode_message(msg_type: int, items) -> bytes:
        return bytes([msg_type]) + rlp.encode(items)

    def _new_request_id(self) -> bytes:
        return secrets.token_bytes(8)

    def _ping_message(self, request_id: bytes) -> bytes:
        return self._encode_message(
            MSG_PING, [request_id, rlp.encode_uint(self.enr.seq)]
        )

    # ------------------------------------------------------------ transport

    def _send_with_session(self, dest: ENR, plaintext: bytes,
                           req: Optional[_PendingRequest]) -> None:
        dest_id = dest.node_id
        addr = (dest.ip(), dest.udp_port())
        with self._lock:
            self._addrs[dest_id] = addr
            sess = self._sessions.get(dest_id)
        nonce = packets.random_nonce()
        if sess is None:
            # No session: send a random-content ordinary packet to elicit
            # WHOAREYOU (spec: the initiator may send junk; the real message
            # is replayed inside the handshake packet).
            header = packets.Header(packets.FLAG_ORDINARY, nonce,
                                    packets.ordinary_authdata(self.node_id))
            filler = secrets.token_bytes(16)
            datagram = packets.encode_packet(dest_id, header, filler)
            if req is not None:
                with self._lock:
                    self._pending[nonce] = req
            self._sock.sendto(datagram, addr)
            return
        header = packets.Header(packets.FLAG_ORDINARY, nonce,
                                packets.ordinary_authdata(self.node_id))
        masking_iv = secrets.token_bytes(16)
        ad = masking_iv + header.encode()
        ct = packets.encrypt_message(sess.send_key, nonce, plaintext, ad)
        datagram = packets.encode_packet(dest_id, header, ct, masking_iv=masking_iv)
        if req is not None:
            # Register even sessioned sends: if the peer LOST its session
            # (restart), it answers WHOAREYOU with this nonce and we must be
            # able to replay the request through a fresh handshake.
            with self._lock:
                self._pending[nonce] = req
        self._sock.sendto(datagram, addr)

    def _request(self, dest: ENR, plaintext: bytes, request_id: bytes,
                 timeout: float = REQUEST_TIMEOUT) -> List:
        # The handshake resolves the peer through the table: every request
        # target must be there (a hidden add_enr precondition otherwise).
        self.add_enr(dest)
        req = _PendingRequest(message=plaintext, request_id=request_id)
        with self._lock:
            self._requests[request_id] = req
        try:
            self._send_with_session(dest, plaintext, req)
            if not req.event.wait(timeout):
                raise Discv5Error("request timed out")
            return req.responses
        finally:
            with self._lock:
                self._requests.pop(request_id, None)
                for nonce in [n for n, r in self._pending.items() if r is req]:
                    del self._pending[nonce]

    # -------------------------------------------------------------- public

    def ping(self, dest: ENR) -> int:
        """PING -> PONG; returns the peer's advertised enr-seq."""
        rid = self._new_request_id()
        resp = self._request(dest, self._ping_message(rid), rid)
        return resp[0]

    def find_node(self, dest: ENR, distances: List[int]) -> List[ENR]:
        rid = self._new_request_id()
        msg = self._encode_message(
            MSG_FINDNODE,
            [rid, [rlp.encode_uint(d) for d in distances]],
        )
        resp = self._request(dest, msg, rid)
        out: List[ENR] = []
        for batch in resp:
            out.extend(batch)
        return out

    def bootstrap(self, boot: ENR, rounds: int = 4, batch: int = 8) -> int:
        """Ping a boot node then FINDNODE batches of descending distances
        from 256 (xor-metric distances concentrate just below 256, so the
        first batches cover almost the whole table — the reference's
        discovery queries walk the same space).  Returns the table size."""
        self.add_enr(boot)
        try:
            self.ping(boot)
        except Discv5Error:
            return len(self.table)
        asked = 0
        for i in range(rounds):
            hi = 256 - batch * i
            distances = list(range(hi, max(hi - batch, 0), -1))
            if not distances:
                break
            try:
                found = self.find_node(boot, distances)
            except Discv5Error:
                continue
            asked += 1
            for enr in found:
                self.add_enr(enr)
        log.info("discv5 bootstrap complete", table=len(self.table),
                 queries=asked)
        return len(self.table)

    def add_enr(self, enr: ENR) -> None:
        if not enr.verify():
            raise EnrError("refusing unverified ENR")
        nid = enr.node_id
        if nid == self.node_id:
            return
        with self._lock:
            known = self.table.get(nid)
            if known is None or enr.seq > known.seq:
                self.table[nid] = enr

    def nodes_at_distance(self, distances: List[int]) -> List[ENR]:
        out = []
        with self._lock:
            entries = list(self.table.values())
        for enr in entries:
            if log2_distance(self.node_id, enr.node_id) in distances:
                out.append(enr)
        if 0 in distances:
            out.append(self.enr)
        return out

    # ------------------------------------------------------------- receive

    def _gc(self) -> None:
        """Expire stale handshake state: timed-out pendings, old
        challenges, and the addr map's size bound — per-packet state must
        not accumulate under churn or a spoofed-src flood."""
        now = time.monotonic()
        with self._lock:
            for nonce in [n for n, r in self._pending.items()
                          if now - r.created > PENDING_TTL]:
                del self._pending[nonce]
            for nid in [n for n, (_, ts) in self._challenges.items()
                        if now - ts > CHALLENGE_TTL]:
                del self._challenges[nid]
            while len(self._addrs) > MAX_ADDRS:
                self._addrs.pop(next(iter(self._addrs)))

    def _rx_loop(self) -> None:
        last_gc = time.monotonic()
        while self._running:
            if time.monotonic() - last_gc > 5.0:
                self._gc()
                last_gc = time.monotonic()
            try:
                datagram, addr = self._sock.recvfrom(2048)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._handle_datagram(datagram, addr)
            except Exception as e:  # a bad packet must not kill the loop
                log.debug("discv5 packet dropped", error=str(e)[:80],
                          addr=f"{addr[0]}:{addr[1]}")

    def _handle_datagram(self, datagram: bytes, addr) -> None:
        pkt = packets.decode_packet(self.node_id, datagram)
        if pkt.header.flag == packets.FLAG_WHOAREYOU:
            self._on_whoareyou(pkt, addr)
        elif pkt.header.flag == packets.FLAG_HANDSHAKE:
            self._on_handshake(pkt, addr)
        else:
            self._on_ordinary(pkt, addr)

    # WHOAREYOU: we are the handshake initiator.
    def _on_whoareyou(self, pkt: packets.Packet, addr) -> None:
        with self._lock:
            req = self._pending.pop(pkt.header.nonce, None)
        if req is None:
            return  # unsolicited
        # Which peer is this? The one we addressed at `addr`.
        dest = None
        with self._lock:
            for nid, known in self._addrs.items():
                if known == addr:
                    enr = self.table.get(nid)
                    if enr is not None:
                        dest = enr
                        break
        if dest is None:
            return
        dest_id = dest.node_id
        # Any session we held with this peer is stale (it sent WHOAREYOU
        # because it cannot decrypt us — e.g. it restarted): drop it so the
        # fresh handshake keys take over.
        with self._lock:
            self._sessions.pop(dest_id, None)
        challenge_data = pkt.challenge_data
        eph = KeyPair()
        init_key, recp_key = session_mod.derive_keys(
            eph.priv, dest.public_key, self.node_id, dest_id, challenge_data
        )
        id_sig = session_mod.id_sign(
            self.keypair.priv, challenge_data, eph.compressed_pub, dest_id
        )
        _, enr_seq = packets.parse_whoareyou(pkt.header.authdata)
        enr_rlp = self.enr.to_rlp() if enr_seq < self.enr.seq else b""
        authdata = packets.handshake_authdata(
            self.node_id, id_sig, eph.compressed_pub, enr_rlp
        )
        nonce = packets.random_nonce()
        header = packets.Header(packets.FLAG_HANDSHAKE, nonce, authdata)
        masking_iv = secrets.token_bytes(16)
        ad = masking_iv + header.encode()
        ct = packets.encrypt_message(init_key, nonce, req.message, ad)
        datagram = packets.encode_packet(dest_id, header, ct, masking_iv=masking_iv)
        with self._lock:
            self._sessions[dest_id] = Session(send_key=init_key, recv_key=recp_key)
        self._sock.sendto(datagram, addr)

    # Handshake packet: we sent the WHOAREYOU; peer is the initiator.
    def _on_handshake(self, pkt: packets.Packet, addr) -> None:
        src_id, id_sig, eph_pub_bytes, enr_rlp = packets.parse_handshake(
            pkt.header.authdata
        )
        with self._lock:
            entry = self._challenges.pop(src_id, None)
        if entry is None:
            return
        challenge, _ts = entry
        challenge_data = challenge.challenge_data
        if enr_rlp:
            enr = ENR.from_rlp(enr_rlp)
            if enr.node_id != src_id:
                return
            self.add_enr(enr)
        with self._lock:
            enr = self.table.get(src_id)
        if enr is None:
            return
        if not session_mod.id_verify(
            enr.public_key, id_sig, challenge_data, eph_pub_bytes, self.node_id
        ):
            log.warning("discv5 handshake id-signature invalid",
                        peer=src_id.hex()[:12])
            return
        eph_pub = secp256k1.decompress(eph_pub_bytes)
        init_key, recp_key = session_mod.derive_keys_from_pubkey(
            self.keypair.priv, eph_pub, src_id, self.node_id, challenge_data
        )
        sess = Session(send_key=recp_key, recv_key=init_key)
        with self._lock:
            self._sessions[src_id] = sess
            self._addrs[src_id] = addr
        ad = pkt.masking_iv + pkt.header.encode()
        try:
            plaintext = packets.decrypt_message(
                sess.recv_key, pkt.header.nonce, pkt.message_ct, ad
            )
        except Exception:
            return
        self._dispatch(src_id, plaintext, addr)

    def _on_ordinary(self, pkt: packets.Packet, addr) -> None:
        src_id = pkt.header.authdata[:32]
        with self._lock:
            sess = self._sessions.get(src_id)
            known_seq = self.table[src_id].seq if src_id in self.table else 0
        plaintext = None
        if sess is not None:
            ad = pkt.masking_iv + pkt.header.encode()
            try:
                plaintext = packets.decrypt_message(
                    sess.recv_key, pkt.header.nonce, pkt.message_ct, ad
                )
            except Exception:
                plaintext = None  # stale keys: re-challenge below
        if plaintext is None:
            # No (working) session: WHOAREYOU, echoing the packet's nonce.
            authdata = packets.whoareyou_authdata(
                packets.random_id_nonce(), known_seq
            )
            header = packets.Header(packets.FLAG_WHOAREYOU,
                                    pkt.header.nonce, authdata)
            masking_iv = secrets.token_bytes(16)
            challenge = packets.Packet(masking_iv, header, b"")
            with self._lock:
                self._challenges[src_id] = (challenge, time.monotonic())
                self._addrs[src_id] = addr
            self._sock.sendto(
                packets.encode_packet(src_id, header, b"", masking_iv=masking_iv),
                addr,
            )
            return
        with self._lock:
            self._addrs[src_id] = addr
        self._dispatch(src_id, plaintext, addr)

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, src_id: bytes, plaintext: bytes, addr) -> None:
        msg_type = plaintext[0]
        body = rlp.decode(plaintext[1:])
        if msg_type == MSG_PING:
            rid, seq_raw = body
            pong = self._encode_message(MSG_PONG, [
                rid, rlp.encode_uint(self.enr.seq),
                bytes(int(x) for x in addr[0].split(".")),
                rlp.encode_uint(addr[1]),
            ])
            self._respond(src_id, pong, addr)
        elif msg_type == MSG_PONG:
            rid = body[0]
            self._complete(rid, rlp.decode_uint(body[1]))
        elif msg_type == MSG_FINDNODE:
            rid, dist_items = body
            distances = [rlp.decode_uint(d) for d in dist_items]
            found = self.nodes_at_distance(distances)
            batches = [found[i:i + MAX_NODES_PER_PACKET]
                       for i in range(0, len(found), MAX_NODES_PER_PACKET)] or [[]]
            total = len(batches)
            for batch in batches:
                nodes = self._encode_message(MSG_NODES, [
                    rid, rlp.encode_uint(total),
                    [_enr_to_item(e) for e in batch],
                ])
                self._respond(src_id, nodes, addr)
        elif msg_type == MSG_NODES:
            rid, total_raw, enr_items = body
            enrs = []
            for item in enr_items:
                try:
                    enrs.append(_enr_from_item(item))
                except EnrError:
                    continue  # a bad record poisons only itself
            self._complete(rid, enrs, total=rlp.decode_uint(total_raw) or 1)

    def _respond(self, dest_id: bytes, plaintext: bytes, addr) -> None:
        with self._lock:
            sess = self._sessions.get(dest_id)
        if sess is None:
            return
        nonce = packets.random_nonce()
        header = packets.Header(packets.FLAG_ORDINARY, nonce,
                                packets.ordinary_authdata(self.node_id))
        masking_iv = secrets.token_bytes(16)
        ad = masking_iv + header.encode()
        ct = packets.encrypt_message(sess.send_key, nonce, plaintext, ad)
        self._sock.sendto(
            packets.encode_packet(dest_id, header, ct, masking_iv=masking_iv),
            addr,
        )

    def _complete(self, request_id: bytes, response, total: int = 1) -> None:
        with self._lock:
            req = self._requests.get(bytes(request_id))
        if req is None:
            return
        req.responses.append(response)
        req.total_expected = total
        if len(req.responses) >= total:
            req.event.set()
