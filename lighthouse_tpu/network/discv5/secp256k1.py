"""Minimal secp256k1 for discv5: point arithmetic, deterministic ECDSA
(RFC 6979), and ECDH — ENR identity scheme v4 and the handshake's key
agreement.  Discovery-scale only (a handful of ops per handshake); the
BLS hot path lives in ``ops/``, not here."""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
G = (Gx, Gy)

Point = Optional[Tuple[int, int]]  # None = infinity


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def add(p1: Point, p2: Point) -> Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def mul(p: Point, k: int) -> Point:
    k %= N
    result: Point = None
    addend = p
    while k:
        if k & 1:
            result = add(result, addend)
        addend = add(addend, addend)
        k >>= 1
    return result


def pubkey(priv: int) -> Tuple[int, int]:
    pt = mul(G, priv)
    assert pt is not None
    return pt


def compress(pt: Tuple[int, int]) -> bytes:
    x, y = pt
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def decompress(data: bytes) -> Tuple[int, int]:
    if len(data) == 65 and data[0] == 4:
        return (int.from_bytes(data[1:33], "big"), int.from_bytes(data[33:], "big"))
    if len(data) != 33 or data[0] not in (2, 3):
        raise ValueError("bad secp256k1 point encoding")
    x = int.from_bytes(data[1:], "big")
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("x not on curve")
    if (y & 1) != (data[0] & 1):
        y = P - y
    return (x, y)


def uncompressed_xy(pt: Tuple[int, int]) -> bytes:
    """x || y, 64 bytes — keccak of this is the discv5 node id."""
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


# ------------------------------------------------------------------- ECDSA


def _rfc6979_k(priv: int, h: bytes) -> int:
    """Deterministic nonce (RFC 6979, HMAC-SHA256)."""
    x = priv.to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(priv: int, msg_hash: bytes) -> bytes:
    """64-byte r||s signature (low-s), over a 32-byte message hash."""
    z = int.from_bytes(msg_hash, "big") % N
    while True:
        k = _rfc6979_k(priv, msg_hash)
        pt = mul(G, k)
        r = pt[0] % N
        if r == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        s = _inv(k, N) * (z + r * priv) % N
        if s == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        if s > N // 2:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(pub: Tuple[int, int], msg_hash: bytes, sig: bytes) -> bool:
    if len(sig) != 64:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return False
    z = int.from_bytes(msg_hash, "big") % N
    w = _inv(s, N)
    u1 = z * w % N
    u2 = r * w % N
    pt = add(mul(G, u1), mul(pub, u2))
    if pt is None:
        return False
    return pt[0] % N == r


def ecdh(priv: int, pub: Tuple[int, int]) -> bytes:
    """discv5 ecdh(): the COMPRESSED shared point (33 bytes)."""
    shared = mul(pub, priv)
    assert shared is not None
    return compress(shared)
