"""EIP-778 Ethereum Node Records with the v4 identity scheme.

A record is ``[signature, seq, k1, v1, k2, v2, ...]`` (keys sorted,
RLP-encoded, <= 300 bytes); the v4 scheme signs ``keccak256(rlp([seq,
k1, v1, ...]))`` with secp256k1 and derives the node id as
``keccak256(uncompressed_pubkey_xy)``.  Text form: ``enr:`` +
unpadded base64url of the RLP."""

from __future__ import annotations

import base64
import secrets
from typing import Dict, Optional

from . import rlp, secp256k1
from .keccak import keccak256

MAX_RECORD_BYTES = 300


class EnrError(Exception):
    pass


class KeyPair:
    def __init__(self, priv: Optional[int] = None):
        if priv is None:
            priv = (secrets.randbits(255) % (secp256k1.N - 1)) + 1
        self.priv = priv
        self.pub = secp256k1.pubkey(priv)

    @classmethod
    def from_bytes(cls, data: bytes) -> "KeyPair":
        return cls(int.from_bytes(data, "big"))

    @property
    def node_id(self) -> bytes:
        return keccak256(secp256k1.uncompressed_xy(self.pub))

    @property
    def compressed_pub(self) -> bytes:
        return secp256k1.compress(self.pub)


class ENR:
    def __init__(self, seq: int, pairs: Dict[bytes, bytes], signature: bytes):
        self.seq = seq
        self.pairs = dict(pairs)
        self.signature = signature

    # -------------------------------------------------------------- create

    @classmethod
    def build(cls, keypair: KeyPair, seq: int = 1, *,
              ip: Optional[str] = None, udp: Optional[int] = None,
              tcp: Optional[int] = None,
              extra: Optional[Dict[bytes, bytes]] = None) -> "ENR":
        pairs: Dict[bytes, bytes] = {
            b"id": b"v4",
            b"secp256k1": keypair.compressed_pub,
        }
        if ip is not None:
            octets = ip.split(".")
            if len(octets) != 4 or not all(
                    o.isdigit() and 0 <= int(o) <= 255 for o in octets):
                raise EnrError(f"not an IPv4 address: {ip!r} (EIP-778 ip "
                               "must be exactly 4 bytes)")
            pairs[b"ip"] = bytes(int(x) for x in octets)
        for name, port in ((b"udp", udp), (b"tcp", tcp)):
            if port is None:
                continue
            if not 1 <= port <= 65535:
                raise EnrError(f"{name.decode()} port {port} outside "
                               "1..65535 (EIP-778 fields are 16-bit)")
            pairs[name] = rlp.encode_uint(port)
        if extra:
            pairs.update(extra)
        content = cls._content_rlp(seq, pairs)
        sig = secp256k1.sign(keypair.priv, keccak256(content))
        record = cls(seq, pairs, sig)
        if len(record.to_rlp()) > MAX_RECORD_BYTES:
            raise EnrError("ENR exceeds 300 bytes")
        return record

    @staticmethod
    def _content_rlp(seq: int, pairs: Dict[bytes, bytes]) -> bytes:
        items = [rlp.encode_uint(seq)]
        for k in sorted(pairs):
            items.append(k)
            items.append(pairs[k])
        return rlp.encode(items)

    # -------------------------------------------------------------- codecs

    def to_rlp(self) -> bytes:
        items = [self.signature, rlp.encode_uint(self.seq)]
        for k in sorted(self.pairs):
            items.append(k)
            items.append(self.pairs[k])
        return rlp.encode(items)

    @classmethod
    def from_rlp(cls, data: bytes) -> "ENR":
        if len(data) > MAX_RECORD_BYTES:
            raise EnrError("ENR exceeds 300 bytes")
        items = rlp.decode(data)
        if not isinstance(items, list) or len(items) < 2 or len(items) % 2:
            raise EnrError("malformed ENR structure")
        signature, seq_raw = items[0], items[1]
        pairs: Dict[bytes, bytes] = {}
        prev = None
        for i in range(2, len(items), 2):
            k, v = items[i], items[i + 1]
            if not isinstance(k, bytes) or not isinstance(v, bytes):
                raise EnrError("ENR keys/values must be byte strings")
            if prev is not None and k <= prev:
                raise EnrError("ENR keys out of order")
            prev = k
            pairs[k] = v
        record = cls(rlp.decode_uint(seq_raw), pairs, signature)
        if not record.verify():
            raise EnrError("invalid ENR signature")
        return record

    def to_text(self) -> str:
        return "enr:" + base64.urlsafe_b64encode(self.to_rlp()).rstrip(b"=").decode()

    @classmethod
    def from_text(cls, text: str) -> "ENR":
        if not text.startswith("enr:"):
            raise EnrError("missing enr: prefix")
        b64 = text[4:]
        b64 += "=" * (-len(b64) % 4)
        return cls.from_rlp(base64.urlsafe_b64decode(b64))

    # ------------------------------------------------------------- queries

    def verify(self) -> bool:
        if self.pairs.get(b"id") != b"v4":
            return False
        pub_bytes = self.pairs.get(b"secp256k1")
        if pub_bytes is None:
            return False
        try:
            pub = secp256k1.decompress(pub_bytes)
        except ValueError:
            return False
        content = self._content_rlp(self.seq, self.pairs)
        return secp256k1.verify(pub, keccak256(content), self.signature)

    @property
    def node_id(self) -> bytes:
        pub = secp256k1.decompress(self.pairs[b"secp256k1"])
        return keccak256(secp256k1.uncompressed_xy(pub))

    @property
    def public_key(self):
        return secp256k1.decompress(self.pairs[b"secp256k1"])

    def ip(self) -> Optional[str]:
        raw = self.pairs.get(b"ip")
        return ".".join(str(b) for b in raw) if raw else None

    def udp_port(self) -> Optional[int]:
        raw = self.pairs.get(b"udp")
        return rlp.decode_uint(raw) if raw else None

    def __repr__(self) -> str:
        return f"ENR(seq={self.seq}, id={self.node_id.hex()[:12]})"
