"""Recursive Length Prefix codec (Ethereum RLP) — ENR records and discv5
messages are RLP-structured.  Items are ``bytes`` or (nested) lists."""

from __future__ import annotations

from typing import List, Tuple, Union

Item = Union[bytes, List["Item"]]


class RlpError(Exception):
    pass


def _encode_length(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    ll = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(ll)]) + ll


def encode(item: Item) -> bytes:
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _encode_length(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(x) for x in item)
        return _encode_length(len(payload), 0xC0) + payload
    raise RlpError(f"cannot RLP-encode {type(item).__name__}")


def encode_uint(n: int) -> bytes:
    """Canonical integer form: big-endian, no leading zeros, 0 == empty."""
    if n == 0:
        return b""
    return n.to_bytes((n.bit_length() + 7) // 8, "big")


def decode_uint(b: bytes) -> int:
    if b.startswith(b"\x00"):
        raise RlpError("non-canonical integer (leading zero)")
    return int.from_bytes(b, "big")


def _decode_at(data: bytes, pos: int) -> Tuple[Item, int]:
    if pos >= len(data):
        raise RlpError("truncated")
    prefix = data[pos]
    if prefix < 0x80:
        return bytes([prefix]), pos + 1
    if prefix < 0xB8:
        length = prefix - 0x80
        end = pos + 1 + length
        out = data[pos + 1:end]
        if len(out) != length:
            raise RlpError("truncated string")
        if length == 1 and out[0] < 0x80:
            raise RlpError("non-canonical single byte")
        return out, end
    if prefix < 0xC0:
        ll = prefix - 0xB7
        length = int.from_bytes(data[pos + 1:pos + 1 + ll], "big")
        if length < 56:
            raise RlpError("non-canonical long string")
        start = pos + 1 + ll
        end = start + length
        if end > len(data):
            raise RlpError("truncated long string")
        return data[start:end], end
    if prefix < 0xF8:
        length = prefix - 0xC0
        end = pos + 1 + length
        if end > len(data):
            raise RlpError("truncated list")
        items, p = [], pos + 1
        while p < end:
            item, p = _decode_at(data, p)
            items.append(item)
        if p != end:
            raise RlpError("list payload overrun")
        return items, end
    ll = prefix - 0xF7
    length = int.from_bytes(data[pos + 1:pos + 1 + ll], "big")
    if length < 56:
        raise RlpError("non-canonical long list")
    start = pos + 1 + ll
    end = start + length
    if end > len(data):
        raise RlpError("truncated long list")
    items, p = [], start
    while p < end:
        item, p = _decode_at(data, p)
        items.append(item)
    if p != end:
        raise RlpError("list payload overrun")
    return items, end


def decode(data: bytes) -> Item:
    item, end = _decode_at(bytes(data), 0)
    if end != len(data):
        raise RlpError("trailing bytes after RLP item")
    return item
