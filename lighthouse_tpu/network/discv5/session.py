"""Handshake cryptography: HKDF session keys + the id-signature.

discv5-theory.md:

    ecdh-secret    = ecdh(eph-privkey, dest-static-pubkey)   (compressed, 33B)
    kdf-info       = "discovery v5 key agreement" || node-id-A || node-id-B
    keydata        = HKDF-SHA256(salt=challenge-data, ikm=ecdh-secret,
                                 info=kdf-info, len=32)
    initiator-key  = keydata[:16];  recipient-key = keydata[16:]

    id-signature   = sign(sha256("discovery v5 identity proof"
                          || challenge-data || eph-pubkey || node-id-B))

A is always the handshake INITIATOR (the side that got WHOAREYOU)."""

from __future__ import annotations

import hashlib
import hmac
from typing import Tuple

from . import secp256k1

ID_SIGNATURE_TEXT = b"discovery v5 identity proof"
KDF_INFO_TEXT = b"discovery v5 key agreement"


def _hkdf_sha256(salt: bytes, ikm: bytes, info: bytes, length: int) -> bytes:
    prk = hmac.new(salt, ikm, hashlib.sha256).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]


def derive_keys(eph_priv: int, dest_pub, node_id_a: bytes, node_id_b: bytes,
                challenge_data: bytes) -> Tuple[bytes, bytes]:
    """(initiator_key, recipient_key) from OUR ephemeral private key."""
    secret = secp256k1.ecdh(eph_priv, dest_pub)
    info = KDF_INFO_TEXT + node_id_a + node_id_b
    keydata = _hkdf_sha256(challenge_data, secret, info, 32)
    return keydata[:16], keydata[16:]


def derive_keys_from_pubkey(static_priv: int, eph_pub, node_id_a: bytes,
                            node_id_b: bytes, challenge_data: bytes
                            ) -> Tuple[bytes, bytes]:
    """Recipient side: same secret via ecdh(static-priv, eph-pubkey)."""
    secret = secp256k1.ecdh(static_priv, eph_pub)
    info = KDF_INFO_TEXT + node_id_a + node_id_b
    keydata = _hkdf_sha256(challenge_data, secret, info, 32)
    return keydata[:16], keydata[16:]


def id_sign(static_priv: int, challenge_data: bytes, eph_pubkey: bytes,
            dest_node_id: bytes) -> bytes:
    h = hashlib.sha256(
        ID_SIGNATURE_TEXT + challenge_data + eph_pubkey + dest_node_id
    ).digest()
    return secp256k1.sign(static_priv, h)


def id_verify(static_pub, signature: bytes, challenge_data: bytes,
              eph_pubkey: bytes, dest_node_id: bytes) -> bool:
    h = hashlib.sha256(
        ID_SIGNATURE_TEXT + challenge_data + eph_pubkey + dest_node_id
    ).digest()
    return secp256k1.verify(static_pub, h, signature)
