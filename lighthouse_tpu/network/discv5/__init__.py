"""Discovery v5 (discv5) over UDP — the real wire format.

Equivalent of the reference's discovery layer
(``beacon_node/lighthouse_network/src/discovery/mod.rs`` + the ``discv5``
crate, ``Cargo.toml:115``): ENR records (EIP-778, v4 identity scheme),
masked packet headers, the WHOAREYOU handshake with ECDH-derived AES-GCM
session keys, and the PING/PONG/FINDNODE/NODES message set over UDP.

Modules:
- ``keccak``    — keccak-256 (pre-NIST padding; NOT hashlib's sha3_256)
- ``secp256k1`` — the secp256k1 group, deterministic ECDSA, ECDH
- ``rlp``       — recursive length prefix codec
- ``enr``       — EIP-778 records (sign/verify/encode + ``enr:`` text form)
- ``packets``   — discv5.1 masked header codec (ordinary/whoareyou/handshake)
- ``session``   — HKDF session-key derivation + id-signature
- ``service``   — the UDP node: handshake state machine, routing table,
                  FINDNODE-driven peer discovery
"""

from .enr import ENR, KeyPair
from .service import Discv5Service

__all__ = ["ENR", "KeyPair", "Discv5Service"]
