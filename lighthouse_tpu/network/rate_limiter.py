"""Token-bucket RPC rate limiter.

Equivalent of the reference's ``rpc/rate_limiter.rs`` (1–495): one quota per
protocol, enforced per peer.  A quota of ``(max_tokens, period)`` replenishes
continuously at ``max_tokens / period`` tokens per second up to the cap;
requests carry a cost (1 for fixed-size requests, the block/root/blob count
for range-style requests, exactly like the reference's
``RPCRequest::expected_responses``).  A request whose cost exceeds the
bucket's CAP can never be served and is a protocol violation; one that only
exceeds the current fill is throttled.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from . import rpc as rpc_mod


@dataclass(frozen=True)
class Quota:
    max_tokens: float
    period_secs: float


# Mirrors the reference's default RPC quotas (rate_limiter.rs defaults /
# lighthouse_network config): generous enough for honest sync, tight enough
# that a single peer cannot monopolize the worker pool.
DEFAULT_QUOTAS: Dict[str, Quota] = {
    rpc_mod.STATUS: Quota(5, 15.0),
    rpc_mod.GOODBYE: Quota(1, 10.0),
    rpc_mod.PING: Quota(2, 10.0),
    rpc_mod.METADATA: Quota(2, 5.0),
    rpc_mod.BLOCKS_BY_RANGE: Quota(1024, 10.0),  # tokens are BLOCKS
    rpc_mod.BLOCKS_BY_ROOT: Quota(128, 10.0),  # tokens are ROOTS
    rpc_mod.BLOBS_BY_RANGE: Quota(768, 10.0),
    rpc_mod.BLOBS_BY_ROOT: Quota(128, 10.0),
    # light-client serving does per-request state reads (bootstrap walks
    # Merkle branches) — quota it like the reference does
    rpc_mod.LIGHT_CLIENT_BOOTSTRAP: Quota(1, 10.0),
    rpc_mod.LIGHT_CLIENT_OPTIMISTIC_UPDATE: Quota(1, 10.0),
    rpc_mod.LIGHT_CLIENT_FINALITY_UPDATE: Quota(1, 10.0),
}


def request_cost(protocol: str, request) -> float:
    """Token cost of one request (the reference's expected_responses)."""
    if protocol == rpc_mod.BLOCKS_BY_RANGE or protocol == rpc_mod.BLOBS_BY_RANGE:
        return max(1, int(getattr(request, "count", 1)))
    if protocol == rpc_mod.BLOCKS_BY_ROOT:
        return max(1, len(getattr(request, "roots", ()) or ()))
    if protocol == rpc_mod.BLOBS_BY_ROOT:
        return max(1, len(getattr(request, "ids", ()) or ()))
    return 1.0


class RateLimitExceeded(Exception):
    def __init__(self, fatal: bool):
        self.fatal = fatal  # cost can NEVER fit (protocol violation)
        super().__init__("rate limit exceeded" + (" (oversize request)" if fatal else ""))


class RPCRateLimiter:
    def __init__(self, quotas: Optional[Dict[str, Quota]] = None,
                 clock=time.monotonic):
        self.quotas = dict(DEFAULT_QUOTAS if quotas is None else quotas)
        self._clock = clock
        self._lock = threading.Lock()
        # (peer, protocol) -> (tokens, last_refill_time)
        self._buckets: Dict[Tuple[str, str], Tuple[float, float]] = {}

    def allow(self, peer: str, protocol: str, cost: float = 1.0) -> None:
        """Consume ``cost`` tokens or raise ``RateLimitExceeded``.

        Unknown protocols are unlimited (the router rejects them anyway)."""
        quota = self.quotas.get(protocol)
        if quota is None:
            return
        if cost > quota.max_tokens:
            raise RateLimitExceeded(fatal=True)
        now = self._clock()
        with self._lock:
            tokens, last = self._buckets.get((peer, protocol),
                                             (quota.max_tokens, now))
            tokens = min(
                quota.max_tokens,
                tokens + (now - last) * quota.max_tokens / quota.period_secs,
            )
            if tokens < cost:
                self._buckets[(peer, protocol)] = (tokens, now)
                raise RateLimitExceeded(fatal=False)
            self._buckets[(peer, protocol)] = (tokens - cost, now)

    def prune(self, older_than_secs: float = 120.0) -> None:
        """Drop idle buckets (bounded memory under peer churn)."""
        cutoff = self._clock() - older_than_secs
        with self._lock:
            self._buckets = {
                k: v for k, v in self._buckets.items() if v[1] >= cutoff
            }
