"""Router: gossip/RPC demux into chain work.

Equivalent of the reference's ``network/src/router.rs`` +
``network_beacon_processor/`` (gossip_methods.rs / rpc_methods.rs): decodes
typed messages, pushes them through the ``BeaconProcessor`` priority queues
as WorkEvents whose handlers call into the ``BeaconChain``, gates gossip
forwarding on validation outcome, serves BlocksByRange/BlocksByRoot from the
store, and reports misbehaving peers.
"""

from __future__ import annotations

from typing import List, Optional

from .. import metrics, telemetry_scope, tracing
from ..chain.beacon_chain import AttestationError, BlockError, ChainError
from ..consensus import helpers as h
from ..scheduler import BeaconProcessor, ReprocessQueue, W, WorkEvent
from . import rpc as rpc_mod
from . import topics as topics_mod
from .peer_manager import PeerAction
from .service import NetworkService


class Router:
    def __init__(
        self,
        *,
        chain,
        service: NetworkService,
        processor: Optional[BeaconProcessor] = None,
        sync_manager=None,
        slasher=None,
        scope=None,
    ):
        self.chain = chain
        self.service = service
        # Node telemetry scope (telemetry_scope.TelemetryScope) — held as a
        # plain attribute because gossip handlers run on processor worker
        # threads, where the runner's contextvar activation is invisible.
        self.scope = scope
        self.processor = processor if processor is not None else BeaconProcessor(max_workers=2)
        self.sync = sync_manager
        self.slasher = slasher
        # Attestations referencing a not-yet-imported block are parked here
        # and re-queued the moment the chain imports that root (reference
        # work_reprocessing_queue.rs) — dropping them instead loses real
        # fork-choice weight after every partition heal, and makes block
        # content race the lookup that imports the missing fork.
        self.reprocess = ReprocessQueue(self.processor)
        chain.block_imported_hooks.append(self.reprocess.block_imported)
        # drop_during_sync enforcement: while range sync is running, stale
        # gossip (attestations/aggregates/contributions/LC updates) is
        # discarded at enqueue (reference beacon_processor lib.rs).  The
        # lambda reads self.sync dynamically — SyncManager attaches itself
        # to the router after construction.
        if self.processor.is_syncing is None:
            from .sync import SyncState

            self.processor.is_syncing = (
                lambda: self.sync is not None and self.sync.state == SyncState.SYNCING
            )
        service.on_gossip = self.on_gossip
        # Same handler, ctx-aware arity: the service prefers this hook and
        # hands us the envelope's propagated trace context as the 5th arg.
        service.on_gossip_ctx = self.on_gossip
        service.on_rpc_request = self.on_rpc_request
        service.on_peer_connected = self.on_peer_connected
        service.on_peer_disconnected = self.on_peer_disconnected
        state = chain.genesis_state
        self.fork_digest = topics_mod.fork_digest(state, b"")
        self.metadata = rpc_mod.MetaData(seq_number=0, attnets=0, syncnets=0)

    # ------------------------------------------------------------ status

    def local_status(self) -> rpc_mod.Status:
        f_epoch, f_root = self.chain.finalized_checkpoint()
        head_root = self.chain.head_root
        return rpc_mod.Status(
            fork_digest=self.fork_digest,
            finalized_root=f_root,
            finalized_epoch=f_epoch,
            head_root=head_root,
            head_slot=self.chain._blocks_slot(head_root),
        )

    def on_peer_connected(self, peer: str) -> None:
        """Dial Status at connect (reference: ``status_peer``) — from a
        worker, not the network loop (the request blocks on the reply)."""

        def do_status(_):
            try:
                chunks = self.service.request(peer, rpc_mod.STATUS, self.local_status())
            except rpc_mod.RpcError:
                return
            if chunks and chunks[0][0] == rpc_mod.SUCCESS:
                status = rpc_mod.Status.from_bytes(chunks[0][1])
                self._handle_peer_status(peer, status)

        self.processor.send(WorkEvent(work_type=W.STATUS, process=do_status))

    def on_peer_disconnected(self, peer: str) -> None:
        pass

    def _handle_peer_status(self, peer: str, status: rpc_mod.Status) -> None:
        if status.fork_digest != self.fork_digest:
            self.service.peer_manager.report(peer, PeerAction.LOW_TOLERANCE, "wrong fork")
            self.service.endpoint.disconnect(peer)
            return
        self.service.peer_manager._peer(peer).status = status
        if self.sync is not None:
            self.sync.on_peer_status(peer, status)

    # ------------------------------------------------------------ gossip

    def on_gossip(self, topic: str, uncompressed: bytes, compressed: bytes,
                  sender: str, trace_ctx: Optional[dict] = None) -> None:
        try:
            kind = topics_mod.GossipTopic.parse(topic).kind
        except ValueError:
            self.service.reject_gossip(sender, topic, "bad_topic")
            return
        if kind == topics_mod.BEACON_BLOCK:
            self.processor.send(
                WorkEvent(
                    work_type=W.GOSSIP_BLOCK,
                    process=lambda _: self._process_gossip_block(
                        topic, uncompressed, compressed, sender,
                        trace_ctx=trace_ctx,
                    ),
                )
            )
        elif kind.startswith(topics_mod.BLOB_SIDECAR_PREFIX):
            self.processor.send(
                WorkEvent(
                    work_type=W.GOSSIP_BLOB_SIDECAR,
                    process=lambda _: self._process_gossip_blob(
                        topic, uncompressed, compressed, sender
                    ),
                )
            )
        elif kind.startswith(topics_mod.BEACON_ATTESTATION_PREFIX) or kind == topics_mod.BEACON_AGGREGATE_AND_PROOF:
            wt = (
                W.GOSSIP_AGGREGATE
                if kind == topics_mod.BEACON_AGGREGATE_AND_PROOF
                else W.GOSSIP_ATTESTATION
            )
            item = (topic, uncompressed, compressed, sender)
            self.processor.send(
                WorkEvent(
                    work_type=wt,
                    process=lambda it: self._process_gossip_attestations([it]),
                    process_batch=self._process_gossip_attestations,
                    item=item,
                    drop_during_sync=True,
                )
            )
        elif kind in self._OP_WORK_TYPES:
            item = (kind, topic, uncompressed, compressed, sender)
            self.processor.send(
                WorkEvent(
                    work_type=self._OP_WORK_TYPES[kind],
                    process=lambda _=None, it=item: self._process_gossip_operation(*it),
                    # current-slot-scoped work is worthless mid-sync; pool ops
                    # (exits/slashings/changes) stay valid and are kept
                    drop_during_sync=(
                        kind == topics_mod.SYNC_COMMITTEE_CONTRIBUTION_AND_PROOF
                    ),
                )
            )
        elif kind in (topics_mod.LIGHT_CLIENT_FINALITY_UPDATE,
                      topics_mod.LIGHT_CLIENT_OPTIMISTIC_UPDATE):
            wt = (W.GOSSIP_LIGHT_CLIENT_FINALITY_UPDATE
                  if kind == topics_mod.LIGHT_CLIENT_FINALITY_UPDATE
                  else W.GOSSIP_LIGHT_CLIENT_OPTIMISTIC_UPDATE)
            item = (kind, topic, uncompressed, compressed, sender)
            self.processor.send(
                WorkEvent(
                    work_type=wt,
                    process=lambda _=None, it=item: self._process_gossip_lc_update(*it),
                    drop_during_sync=True,
                )
            )

    _OP_WORK_TYPES = {
        topics_mod.VOLUNTARY_EXIT: W.GOSSIP_VOLUNTARY_EXIT,
        topics_mod.PROPOSER_SLASHING: W.GOSSIP_PROPOSER_SLASHING,
        topics_mod.ATTESTER_SLASHING: W.GOSSIP_ATTESTER_SLASHING,
        topics_mod.BLS_TO_EXECUTION_CHANGE: W.GOSSIP_BLS_TO_EXECUTION_CHANGE,
        topics_mod.SYNC_COMMITTEE_CONTRIBUTION_AND_PROOF: W.GOSSIP_SYNC_CONTRIBUTION,
    }

    def _process_gossip_operation(self, kind: str, topic: str,
                                  uncompressed: bytes, compressed: bytes,
                                  sender: str) -> None:
        """Pool-operation gossip (reference gossip_methods.rs
        process_gossip_{voluntary_exit,proposer_slashing,attester_slashing,
        bls_to_execution_change} + process_gossip_sync_contribution):
        decode, verify via the chain (dedup -> drop; invalid -> penalize),
        pool, and forward only what validated fresh."""
        chain = self.chain
        try:
            if kind == topics_mod.VOLUNTARY_EXIT:
                op = chain.types.SignedVoluntaryExit.from_ssz_bytes(uncompressed)
                fresh = chain.on_gossip_voluntary_exit(op)
            elif kind == topics_mod.PROPOSER_SLASHING:
                op = chain.types.ProposerSlashing.from_ssz_bytes(uncompressed)
                fresh = chain.on_gossip_proposer_slashing(op)
            elif kind == topics_mod.ATTESTER_SLASHING:
                # electra slashings carry the EIP-7549 committee-spanning
                # container; the TOPIC's digest names the fork (wallclock
                # would misdecode cross-fork messages at the transition)
                digest = topics_mod.GossipTopic.parse(topic).fork_digest
                fork = topics_mod.fork_name_for_digest(
                    digest, bytes(chain.genesis_state.genesis_validators_root),
                    chain.spec,
                ) or chain.spec.fork_name_at_slot(chain.current_slot())
                cls = (chain.types.AttesterSlashingElectra
                       if fork == "electra" else chain.types.AttesterSlashing)
                op = cls.from_ssz_bytes(uncompressed)
                fresh = chain.on_gossip_attester_slashing(op)
            elif kind == topics_mod.BLS_TO_EXECUTION_CHANGE:
                op = chain.types.SignedBLSToExecutionChange.from_ssz_bytes(
                    uncompressed)
                fresh = chain.on_gossip_bls_change(op)
            else:  # sync contribution-and-proof
                signed = chain.types.SignedContributionAndProof.from_ssz_bytes(
                    uncompressed)
                (err,) = chain.process_signed_contributions([signed])
                if err is not None:
                    # IGNORE vs REJECT (p2p spec): a contribution outside
                    # the slot window is normal propagation lag, not peer
                    # misbehavior — penalizing it would bleed honest peers
                    if "outside the current-slot window" not in err:
                        self.service.reject_gossip(
                            sender, topic, "invalid_op", detail=err)
                    return
                fresh = True
        except ChainError as e:
            self.service.reject_gossip(
                sender, topic, "invalid_op", detail=str(e))
            return
        except Exception:
            self.service.reject_gossip(sender, topic, "undecodable")
            return
        if fresh:
            self.service.forward(topic, compressed, exclude=sender,
                                 uncompressed=uncompressed)

    def _process_gossip_block(
        self, topic: str, uncompressed: bytes, compressed: bytes, sender: str,
        trace_ctx: Optional[dict] = None,
    ) -> None:
        from .sync import decode_signed_block

        chain = self.chain
        try:
            signed = decode_signed_block(chain, uncompressed)
        except Exception:
            self.service.reject_gossip(sender, topic, "undecodable")
            return
        # Proposer dedup/equivocation gate before any state work; the cache
        # is only POPULATED after successful import (observe-after-verify),
        # so an attacker's junk block cannot brand the honest proposer an
        # equivocator (observed_block_producers.rs).
        block_root = signed.message.hash_tree_root()
        seen = chain.observed.block_producers.status(
            int(signed.message.slot), int(signed.message.proposer_index), block_root
        )
        if seen == "duplicate":
            return
        if seen == "equivocation":
            # the slasher wants exactly these (double proposal evidence)
            if self.slasher is not None:
                self.slasher.on_block(signed)
                self._drain_slasher()
            self.service.reject_gossip(sender, topic, "proposer_equivocation")
            return
        # Resume the publisher's trace context (if the envelope carried one)
        # as a fresh local root: the import tree joins the remote proposal
        # tree on remote_trace_id in the fleet artifact.
        with tracing.resume_remote(
                trace_ctx, "gossip_block_import",
                slot=int(signed.message.slot), root=block_root.hex(),
                sender=sender,
                node=self.scope.node_id if self.scope is not None else None):
            try:
                chain.process_block(signed)
            except BlockError as e:
                if "pending availability" in str(e):
                    # Blobs haven't arrived yet — the chain stashed the block
                    # in the DA checker; the blob handler completes the
                    # import.
                    return
                if "unknown parent" in str(e) and self.sync is not None:
                    # Don't penalize: we may simply be behind. But do NOT
                    # forward either — an unknown-parent block has passed no
                    # validation, so propagating it would relay junk (the
                    # reference queues it for reprocessing and only
                    # propagates validated blocks).
                    self.sync.on_unknown_parent(signed, sender)
                    return
                self.service.reject_gossip(
                    sender, topic, "invalid_block", detail=str(e))
                return
            chain.observed.block_producers.observe(
                int(signed.message.slot), int(signed.message.proposer_index),
                block_root
            )
            if self.slasher is not None:
                self.slasher.on_block(signed)
                self._drain_slasher()
            # Forward with the ORIGIN's trace context, not a fresh local
            # stamp — downstream nodes see the publisher's causal frame.
            self.service.forward(topic, compressed, exclude=sender,
                                 uncompressed=uncompressed,
                                 trace_ctx=trace_ctx)
            self._publish_light_client_updates()
        # Imported: journal the cross-node causal link.  Worker threads must
        # not append to the scope journal directly (ordering would depend on
        # thread interleaving) — defer, drained on the runner thread at the
        # next settle boundary.
        if self.scope is not None:
            link = None
            origin = trace_ctx.get("node") if trace_ctx else None
            if trace_ctx and trace_ctx.get("trace_id"):
                link = (trace_ctx.get("node"), int(trace_ctx.get("lamport") or 0))
                telemetry_scope.FLEET_TRACE_LINKS.inc(kind="remote-import")
            self.scope.defer(
                "fleet", "block_imported",
                {"slot": int(signed.message.slot), "root": block_root.hex(),
                 "origin": origin},
                link=link,
            )

    def _publish_light_client_updates(self) -> None:
        """Gossip newly-produced LC finality/optimistic updates (reference:
        the LC server publishes on the two light_client topics)."""
        fin, opt = self.chain.lc_cache.take_new_updates()
        if fin is not None:
            t = topics_mod.GossipTopic(
                self.fork_digest, topics_mod.LIGHT_CLIENT_FINALITY_UPDATE
            )
            self.service.publish(str(t), fin.as_ssz_bytes())
        if opt is not None:
            t = topics_mod.GossipTopic(
                self.fork_digest, topics_mod.LIGHT_CLIENT_OPTIMISTIC_UPDATE
            )
            self.service.publish(str(t), opt.as_ssz_bytes())

    def _process_gossip_lc_update(self, kind: str, topic: str,
                                  uncompressed: bytes, compressed: bytes,
                                  sender: str) -> None:
        """Light-client update gossip (reference
        light_client_{finality,optimistic}_update_verification.rs / p2p
        spec): a received update is valid iff it EQUALS the one this node's
        LC server computed from its own view — forward on match, IGNORE
        (no penalty: views can lag) otherwise."""
        cache = self.chain.lc_cache
        ours = (cache.latest_finality_update
                if kind == topics_mod.LIGHT_CLIENT_FINALITY_UPDATE
                else cache.latest_optimistic_update)
        if ours is None:
            return  # no local view to validate against: IGNORE
        if ours.as_ssz_bytes() == uncompressed:
            self.service.forward(topic, compressed, exclude=sender,
                                 uncompressed=uncompressed)

    def _process_gossip_blob(
        self, topic: str, uncompressed: bytes, compressed: bytes, sender: str
    ) -> None:
        """Gossip blob sidecar: verify (inclusion proof + KZG) into the DA
        checker; if this completes a block waiting on availability, import it
        (blob_verification.rs + data_availability_checker.rs)."""
        from ..chain.da import BlobError

        chain = self.chain
        try:
            sidecar = chain.types.BlobSidecar.from_ssz_bytes(uncompressed)
        except Exception:
            self.service.reject_gossip(sender, topic, "undecodable")
            return
        try:
            block_root = chain.da_checker.put_blob(sidecar)
        except BlobError as e:
            self.service.reject_gossip(
                sender, topic, "invalid_blob",
                action=PeerAction.MID_TOLERANCE, detail=str(e))
            return
        self.service.forward(topic, compressed, exclude=sender,
                             uncompressed=uncompressed)
        ready = chain.da_checker.take_ready_block(block_root)
        if ready is not None:
            try:
                chain.process_block(ready)
            except BlockError:
                pass  # unrelated import failure; peers already penalized upstream

    def _process_gossip_attestations(self, items: List[tuple]) -> None:
        """Batch-coalesced attestation verification (reference
        ``process_gossip_attestation_batch`` /
        ``attestation_verification/batch.rs:31-224``): every item in the
        drained batch is spec-checked and dedup'd individually, then ALL
        signature sets verify in ONE backend call — one padded device program
        per drained queue batch.  On batch failure, fall back to per-item
        verification so only the actually-bad items are penalized (the
        fidelity fallback, batch.rs:205)."""
        from ..crypto.bls import api as bls

        chain = self.chain
        candidates = []  # (candidate, topic, compressed, sender)
        for topic, uncompressed, compressed, sender in items:
            try:
                kind = topics_mod.GossipTopic.parse(topic).kind
                is_aggregate = kind == topics_mod.BEACON_AGGREGATE_AND_PROOF
                if is_aggregate:
                    agg = chain.types.SignedAggregateAndProof.from_ssz_bytes(uncompressed)
                    attestation = agg.message.aggregate
                else:
                    attestation = chain.types.Attestation.from_ssz_bytes(uncompressed)
            except Exception:
                self.service.reject_gossip(sender, topic, "undecodable")
                continue
            # Observed-cache dedup BEFORE any signature work (the gossip
            # replay/DoS defense; observed_attesters.rs semantics).
            target_epoch = int(attestation.data.target.epoch)
            if is_aggregate:
                att_root = attestation.hash_tree_root()
                if chain.observed.aggregates.is_known(int(attestation.data.slot), att_root):
                    continue  # exact duplicate aggregate
                if chain.observed.aggregators.is_known(
                    target_epoch, int(agg.message.aggregator_index)
                ):
                    continue  # aggregator already aggregated this epoch
            try:
                if is_aggregate:
                    # Full aggregate gossip verification: aggregator committee
                    # membership + is_aggregator + 3 signature sets (selection
                    # proof, outer sig, indexed att) — never just the inner
                    # aggregate (round-2 advisor high finding).
                    cand = chain.preverify_aggregate(agg)
                    sig_sets = cand.signature_sets
                    inner = cand.inner
                else:
                    cand = chain.preverify_attestation(attestation)
                    sig_sets = [cand.signature_set]
                    inner = cand
            except AttestationError as e:
                if "unknown head block" in str(e):
                    # Pre-finalization roots can never become the head: reject
                    # and penalize (reference attestation_verification.rs ->
                    # is_pre_finalization_block).  Genuinely-unknown roots are
                    # left to sync's single-block lookup, unpenalized.
                    root = bytes(attestation.data.beacon_block_root)
                    if chain.is_pre_finalization_block(root):
                        self.service.reject_gossip(
                            sender, topic, "pre_finalization_attestation")
                    elif self.sync is not None:
                        # genuinely unknown: park the raw item until the
                        # root imports (park BEFORE the lookup spawns, or
                        # the import could land between the two and strand
                        # the attestation), then chase the block off-thread
                        # The re-queued event must carry the SAME batch
                        # shape as fresh gossip (item + process_batch): a
                        # released park coalesces with live attestation
                        # events in the processor's drain batch, and a
                        # shapeless event there feeds item=None into the
                        # batch handler — the unpack TypeError then kills
                        # the WHOLE drained batch in the worker-panic
                        # handler (silent attestation loss the 128-epoch
                        # soak caught as nondeterministic block content).
                        item = (topic, uncompressed, compressed, sender)
                        self.reprocess.await_block(root, WorkEvent(
                            work_type=W.GOSSIP_ATTESTATION,
                            process=lambda it:
                                self._process_gossip_attestations([it]),
                            process_batch=self._process_gossip_attestations,
                            item=item,
                        ))
                        if chain.fork_choice.contains_block(root):
                            # ANOTHER import path (range sync, a parent
                            # chase) landed the root between preverify and
                            # the park — its hook has already fired, so
                            # release the fresh park ourselves
                            self.reprocess.block_imported(root)
                        else:
                            self.sync.lookup_block_async(root, sender)
                    continue
                self.service.reject_gossip(
                    sender, topic, "invalid_attestation",
                    action=PeerAction.MID_TOLERANCE, detail=str(e))
                continue
            slasher_only = False
            if not is_aggregate:
                vidx = (
                    int(inner.indexed.attesting_indices[0])
                    if len(inner.indexed.attesting_indices) == 1
                    else None
                )
                if vidx is not None and chain.observed.attesters.is_known(
                    target_epoch, vidx
                ):
                    # Validator already attested this epoch: IGNORE for fork
                    # choice/forwarding — but a second message for the same
                    # epoch is exactly what a double/surround voter emits, so
                    # the slasher still gets it once the signature verifies
                    # (reference handle_attestation_verification_failure:
                    # PriorAttestationKnown still feeds the slasher).
                    if self.slasher is None:
                        continue
                    slasher_only = True
            candidates.append((cand, sig_sets, is_aggregate, topic, compressed,
                               sender, slasher_only))
        if not candidates:
            return

        # ONE verification group for the whole drained batch (aggregates
        # contribute 3 sets each — batch.rs:31-135 semantics).  Through the
        # async device pipeline this group coalesces with whatever block
        # import / sync-committee / other gossip workers submitted
        # concurrently — the worker waits on a future, not on the device.
        from .. import device_pipeline

        kind = ("gossip_aggregate" if any(c[2] for c in candidates)
                else "gossip_attestation")
        with device_pipeline.work_context(kind):
            batch_ok = bls.verify_signature_sets(
                [s for c in candidates for s in c[1]]
            )
        for (cand, sig_sets, is_aggregate, topic, compressed, sender,
             slasher_only) in candidates:
            ok = batch_ok or bls.verify_signature_sets(sig_sets)
            if not ok:
                self.service.reject_gossip(
                    sender, topic, "bad_signature",
                    action=PeerAction.MID_TOLERANCE)
                continue
            indexed = cand.inner.indexed if is_aggregate else cand.indexed
            # The slasher eats on SIGNATURE verification, before the
            # fork-choice apply (reference: slashing evidence needs a valid
            # signature, not a successful import) — an equivocating vote
            # whose apply fails (e.g. its target was pruned from our view)
            # is still evidence.
            if self.slasher is not None:
                self.slasher.on_attestation(indexed)
                self._drain_slasher()
            if slasher_only:
                # verified duplicate: slashing evidence only — no fork-choice
                # weight, no forward (the epoch's first message already won)
                continue
            try:
                if is_aggregate:
                    chain.apply_verified_aggregate(cand)
                else:
                    chain.apply_attestation(cand)
            except Exception as e:
                # One bad item (e.g. fork choice's validate_on_attestation
                # rejecting a crafted target) must never kill the rest of
                # the drained batch — the byzantine soak caught exactly
                # this: a half-bad batch silently dropped every later
                # candidate, slasher evidence included.  IGNORE, don't
                # penalize: a candidate that preverified and then fails
                # apply is usually a view-lag race (our fork choice pruned
                # the target between the two), and scoring honest relayers
                # for it bleeds the mesh.
                self.service.reject_gossip(
                    sender, topic, "apply_failed", detail=str(e),
                    penalize=False)
                continue
            self.service.forward(topic, compressed, exclude=sender)

    def _drain_slasher(self) -> None:
        """Slashings found by the slasher enter the op pool for our next
        proposal AND gossip out on the slashing topics (reference
        slasher_service: slashings are broadcast so ANY proposer can include
        them, not just us).  Both ride the chain's gossip-op path — dedup,
        signature verification, trial application, fork-choice equivocation
        mask — so a stale finding (validator already slashed) dies here
        instead of poisoning blocks."""
        attester, proposer = self.slasher.drain_slashings()
        for kind, ops, verify in (
            (topics_mod.ATTESTER_SLASHING, attester,
             self.chain.on_gossip_attester_slashing),
            (topics_mod.PROPOSER_SLASHING, proposer,
             self.chain.on_gossip_proposer_slashing),
        ):
            for s in ops:
                try:
                    fresh = verify(s)
                except ChainError:
                    metrics.SLASHER_SLASHINGS.inc(kind=kind, outcome="stale")
                    continue
                if not fresh:
                    metrics.SLASHER_SLASHINGS.inc(kind=kind, outcome="known")
                    continue
                metrics.SLASHER_SLASHINGS.inc(kind=kind, outcome="pooled")
                topic = topics_mod.GossipTopic(self.fork_digest, kind)
                self.service.publish(str(topic), s.as_ssz_bytes())

    # --------------------------------------------------------------- rpc

    def on_rpc_request(self, protocol: str, request, sender: str) -> List[bytes]:
        if protocol == rpc_mod.STATUS:
            self._handle_peer_status(sender, request)
            return [rpc_mod.encode_response_chunk(rpc_mod.SUCCESS, self.local_status().to_bytes())]
        if protocol == rpc_mod.PING:
            pong = rpc_mod.Ping(self.metadata.seq_number)
            return [rpc_mod.encode_response_chunk(rpc_mod.SUCCESS, pong.to_bytes())]
        if protocol == rpc_mod.METADATA:
            return [rpc_mod.encode_response_chunk(rpc_mod.SUCCESS, self.metadata.to_bytes())]
        if protocol == rpc_mod.GOODBYE:
            self.service.endpoint.disconnect(sender)
            return []
        if protocol == rpc_mod.BLOCKS_BY_RANGE:
            return self._serve_blocks_by_range(request, sender)
        if protocol == rpc_mod.BLOCKS_BY_ROOT:
            return self._serve_blocks_by_root(request, sender)
        if protocol == rpc_mod.BLOBS_BY_RANGE:
            return self._serve_blobs_by_range(request, sender)
        if protocol == rpc_mod.BLOBS_BY_ROOT:
            return self._serve_blobs_by_root(request, sender)
        if protocol == rpc_mod.LIGHT_CLIENT_BOOTSTRAP:
            bootstrap = self.chain.produce_light_client_bootstrap(
                bytes(request.root))
            if bootstrap is None:
                return [rpc_mod.encode_response_chunk(
                    rpc_mod.RESOURCE_UNAVAILABLE, b"")]
            return [self._lc_chunk(bootstrap, int(bootstrap.header.beacon.slot))]
        if protocol in (rpc_mod.LIGHT_CLIENT_OPTIMISTIC_UPDATE,
                        rpc_mod.LIGHT_CLIENT_FINALITY_UPDATE):
            update = (
                self.chain.lc_cache.latest_optimistic_update
                if protocol == rpc_mod.LIGHT_CLIENT_OPTIMISTIC_UPDATE
                else self.chain.lc_cache.latest_finality_update
            )
            if update is None:
                return [rpc_mod.encode_response_chunk(
                    rpc_mod.RESOURCE_UNAVAILABLE, b"")]
            return [self._lc_chunk(
                update, int(update.attested_header.beacon.slot))]
        if protocol == rpc_mod.PEER_EXCHANGE:
            return self._serve_peer_exchange(request, sender)
        return [rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"unknown protocol")]

    def _blob_chunk(self, sidecar) -> bytes:
        slot = int(sidecar.signed_block_header.message.slot)
        epoch = slot // self.chain.spec.slots_per_epoch
        version = self.chain.spec.fork_version_for(self.chain.spec.fork_name_at_epoch(epoch))
        context = h.compute_fork_digest(
            version, bytes(self.chain.genesis_state.genesis_validators_root)
        )
        return rpc_mod.encode_response_chunk(
            rpc_mod.SUCCESS, sidecar.as_ssz_bytes(), context_bytes=context
        )

    def _serve_blobs_by_range(self, req, sender: str) -> List[bytes]:
        """Reference ``rpc_methods.rs`` handle_blobs_by_range_request:
        per-slot sidecars in ascending (slot, index) order."""
        if req.count > rpc_mod.MAX_REQUEST_BLOCKS:
            self.service.peer_manager.report(sender, PeerAction.LOW_TOLERANCE, "oversize range")
            return [rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"count too large")]
        chain = self.chain
        chunks: List[bytes] = []
        prev_root = None
        for slot in range(req.start_slot, req.start_slot + req.count):
            root = chain.block_root_at_slot(slot) or chain.db.cold_block_root_at_slot(slot)
            if root is None or root == prev_root:
                continue
            prev_root = root
            for sidecar in sorted(chain.get_blobs(root), key=lambda s: int(s.index)):
                # a skip slot resolves to an EARLIER block; its sidecars are
                # outside the requested range and must not be served
                if int(sidecar.signed_block_header.message.slot) != slot:
                    continue
                chunks.append(self._blob_chunk(sidecar))
        return chunks

    def _serve_blobs_by_root(self, req, sender: str) -> List[bytes]:
        if len(req.ids) > rpc_mod.MAX_REQUEST_BLOCKS:
            return [rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"too many ids")]
        chunks = []
        for root, index in req.ids:
            for sidecar in self.chain.get_blobs(root):
                if int(sidecar.index) == index:
                    chunks.append(self._blob_chunk(sidecar))
        return chunks

    def _serve_peer_exchange(self, req, sender: str) -> List[bytes]:
        """Share known listen addresses of our other peers (the discovery
        analog of a discv5 FINDNODE answer)."""
        return [rpc_mod.serve_peer_exchange(
            self.service.endpoint, sender, req.max_peers
        )]

    def _context_for_slot(self, slot: int) -> bytes:
        """Fork digest of the era ``slot`` belongs to — the context bytes
        every forked-payload chunk carries (container schemas differ per
        era; the startup digest would mislead post-transition clients)."""
        spec = self.chain.spec
        version = spec.fork_version_for(
            spec.fork_name_at_epoch(slot // spec.slots_per_epoch))
        return h.compute_fork_digest(
            version, bytes(self.chain.genesis_state.genesis_validators_root))

    def _lc_chunk(self, payload, slot: int) -> bytes:
        return rpc_mod.encode_response_chunk(
            rpc_mod.SUCCESS, payload.as_ssz_bytes(),
            context_bytes=self._context_for_slot(slot))

    def _block_chunk(self, signed_block) -> bytes:
        return rpc_mod.encode_response_chunk(
            rpc_mod.SUCCESS, signed_block.as_ssz_bytes(),
            context_bytes=self._context_for_slot(int(signed_block.message.slot)),
        )

    def _serve_blocks_by_range(self, req: rpc_mod.BlocksByRangeRequest, sender: str) -> List[bytes]:
        if req.count > rpc_mod.MAX_REQUEST_BLOCKS:
            self.service.peer_manager.report(sender, PeerAction.LOW_TOLERANCE, "oversize range")
            return [rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"count too large")]
        chain = self.chain
        roots: List[bytes] = []
        slots: List[int] = []
        prev_root = None
        for slot in range(req.start_slot, req.start_slot + req.count):
            root = chain.block_root_at_slot(slot)
            if root is None or root == prev_root:
                root_cold = chain.db.cold_block_root_at_slot(slot)
                if root_cold is None or root_cold == prev_root:
                    continue
                root = root_cold
            prev_root = root
            roots.append(root)
            slots.append(slot)
        # Batched: blinded store hits cost one EL round trip total.
        chunks: List[bytes] = []
        for slot, block in zip(slots, chain.get_blocks(roots)):
            if block is not None and int(block.message.slot) == slot:
                chunks.append(self._block_chunk(block))
        return chunks

    def _serve_blocks_by_root(self, req: rpc_mod.BlocksByRootRequest, sender: str) -> List[bytes]:
        if len(req.roots) > rpc_mod.MAX_REQUEST_BLOCKS:
            return [rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"too many roots")]
        chunks = []
        for block in self.chain.get_blocks(list(req.roots)):
            if block is not None:
                chunks.append(self._block_chunk(block))
        return chunks
