"""TCP transport: the socket-backed ``Endpoint`` implementation.

The second implementation of the transport seam (VERDICT r1 item 10): the
in-process ``Hub`` serves simulators; this one carries the same ``Envelope``
frames over real TCP sockets, so two OS processes can gossip and sync over
localhost (or a LAN) with the whole stack above the seam (gossip dedup, RPC,
peer scoring, range sync) unchanged.  Reference analog:
``lighthouse_network``'s libp2p TCP transport under the behaviour
composition (multistream-select's protocol negotiation maps to the envelope
header's topic/protocol strings).

Wire format per frame (all integers big-endian), VERDICT r2 item 4 — the
payload bytes on the wire ARE the spec ssz_snappy encodings (gossip data =
snappy-compressed SSZ exactly as the pubsub topic defines; rpc data = the
``rpc.py`` ssz_snappy request/response chunk bytes), with a fixed binary
header instead of the old JSON+base64 framing:

    u32 frame_len ||
    u8 kind (0 hello | 1 gossip | 2 rpc_request | 3 rpc_response)
    u8  sender_len  || sender utf8          (libp2p peer-id analog)
    u16 topic_len   || topic utf8           (gossip: /eth2/<digest>/<kind>/ssz_snappy)
    u16 proto_len   || protocol utf8        (rpc: /eth2/beacon_chain/req/<m>/<v>/ssz_snappy)
    u64 request_id
    u32 data_len    || data bytes (ssz_snappy)

A connection opens with a ``hello`` frame carrying the dialer's peer id; the
acceptor answers with its own.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Set, Tuple

from .transport import Envelope

MAX_FRAME = 64 * 1024 * 1024

_KIND_TO_WIRE = {"hello": 0, "gossip": 1, "rpc_request": 2, "rpc_response": 3,
                 "ihave": 4, "iwant": 5, "subscribe": 6, "unsubscribe": 7,
                 "graft": 8, "prune": 9}
_WIRE_TO_KIND = {v: k for k, v in _KIND_TO_WIRE.items()}

# Per-stream protocols negotiated with multistream-select over yamux
# (secured mode).  Gossip-class traffic speaks the REAL gossipsub v1.1
# protobuf wire format (reference: vendored gossipsub protocol.rs
# PROTOCOL: "/meshsub/1.1.0" + varint-delimited rpc.proto frames); the
# envelope stream carries hello + the ssz_snappy req/resp chunks.
ENVELOPE_PROTOCOL = "/lighthouse-tpu/envelope/1.0.0"
MESHSUB_PROTOCOL = "/meshsub/1.1.0"
MESHSUB_KINDS = frozenset(
    {"gossip", "ihave", "iwant", "graft", "prune", "subscribe", "unsubscribe"})


class TcpTransportError(Exception):
    pass


def _encode(env: Envelope) -> bytes:
    sender = env.sender.encode()
    topic = (env.topic or "").encode()
    proto = (env.protocol or "").encode()
    if len(sender) > 0xFF or len(topic) > 0xFFFF or len(proto) > 0xFFFF:
        raise TcpTransportError("oversized envelope header field")
    payload = b"".join(
        (
            struct.pack(">BB", _KIND_TO_WIRE[env.kind], len(sender)),
            sender,
            struct.pack(">H", len(topic)),
            topic,
            struct.pack(">H", len(proto)),
            proto,
            struct.pack(">QI", env.request_id or 0, len(env.data)),
            env.data,
        )
    )
    return struct.pack(">I", len(payload)) + payload


def _decode(payload: bytes) -> Envelope:
    try:
        kind_b, sender_len = struct.unpack_from(">BB", payload, 0)
        pos = 2
        sender = payload[pos : pos + sender_len].decode()
        pos += sender_len
        (topic_len,) = struct.unpack_from(">H", payload, pos)
        pos += 2
        topic = payload[pos : pos + topic_len].decode() or None
        pos += topic_len
        (proto_len,) = struct.unpack_from(">H", payload, pos)
        pos += 2
        proto = payload[pos : pos + proto_len].decode() or None
        pos += proto_len
        request_id, data_len = struct.unpack_from(">QI", payload, pos)
        pos += 12
        data = payload[pos : pos + data_len]
        if len(data) != data_len or pos + data_len != len(payload):
            raise TcpTransportError("envelope length mismatch")
        kind = _WIRE_TO_KIND.get(kind_b)
        if kind is None:
            raise TcpTransportError(f"unknown envelope kind {kind_b}")
    except (struct.error, UnicodeDecodeError) as e:
        raise TcpTransportError(f"malformed envelope: {e}") from e
    return Envelope(
        kind=kind, sender=sender, topic=topic, protocol=proto,
        request_id=request_id, data=data,
    )


def _env_to_rpc(env: Envelope):
    """Gossip-class Envelope -> one gossipsub protobuf RPC."""
    from . import pb
    from .transport import decode_prune_data

    if env.kind == "gossip":
        return pb.RPC(publish=[pb.Message(data=env.data, topic=env.topic or "")])
    if env.kind == "subscribe":
        return pb.RPC(subscriptions=[pb.SubOpts(True, env.topic or "")])
    if env.kind == "unsubscribe":
        return pb.RPC(subscriptions=[pb.SubOpts(False, env.topic or "")])
    ctrl = pb.ControlMessage()
    if env.kind == "ihave":
        ctrl.ihave.append(pb.ControlIHave(env.topic or "", [env.data]))
    elif env.kind == "iwant":
        ctrl.iwant.append(pb.ControlIWant([env.data]))
    elif env.kind == "graft":
        ctrl.graft.append(pb.ControlGraft(env.topic or ""))
    elif env.kind == "prune":
        backoff, px = decode_prune_data(env.data)
        peers = []
        for rec in px:
            pid = rec.rsplit("|", 1)[1] if "|" in rec else ""
            peers.append(pb.PeerInfo(peer_id=pid.encode(),
                                     signed_peer_record=rec.encode()))
        ctrl.prune.append(pb.ControlPrune(env.topic or "", peers, backoff))
    else:
        raise TcpTransportError(f"not a meshsub kind: {env.kind}")
    return pb.RPC(control=ctrl)


def _rpc_to_envs(peer: str, rpc) -> list:
    """One inbound gossipsub RPC -> Envelopes for the service loop.  The
    sender is the connection's proven peer, never a wire field (Eth2
    StrictNoSign: gossipsub's anonymous mode)."""
    from .transport import encode_prune_data

    envs = []
    for sub in rpc.subscriptions:
        envs.append(Envelope(
            kind="subscribe" if sub.subscribe else "unsubscribe",
            sender=peer, topic=sub.topic_id))
    for msg in rpc.publish:
        envs.append(Envelope(kind="gossip", sender=peer, topic=msg.topic,
                             data=msg.data))
    ctrl = rpc.control
    if ctrl is not None:
        for ih in ctrl.ihave:
            for mid in ih.message_ids:
                envs.append(Envelope(kind="ihave", sender=peer,
                                     topic=ih.topic_id, data=mid))
        for iw in ctrl.iwant:
            for mid in iw.message_ids:
                envs.append(Envelope(kind="iwant", sender=peer, data=mid))
        for g in ctrl.graft:
            envs.append(Envelope(kind="graft", sender=peer, topic=g.topic_id))
        for pr in ctrl.prune:
            px = [p.signed_peer_record.decode("utf-8", "replace")
                  for p in pr.peers if p.signed_peer_record]
            envs.append(Envelope(
                kind="prune", sender=peer, topic=pr.topic_id,
                data=encode_prune_data(
                    pr.backoff if pr.backoff is not None else 60, px)))
    return envs


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock: socket.socket) -> Optional[bytes]:
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise TcpTransportError(f"frame of {length} bytes exceeds limit")
    return _read_exact(sock, length)


class _SecuredChannel:
    """Socket-shaped adapter over one yamux stream of a noise session, so
    every existing envelope path (sendall/recv/shutdown/close) works
    unchanged on a secured connection."""

    def __init__(self, session, stream, sock) -> None:
        self._session = session
        self._stream = stream
        self._sock = sock
        self._timeout = None
        self.remote_identity = session.conn.remote_identity

    def sendall(self, data: bytes) -> None:
        self._stream.send(data)

    def recv(self, n: int) -> bytes:
        try:
            return self._stream.recv(n, timeout=self._timeout)
        except Exception:
            return b""

    def settimeout(self, t) -> None:
        # A SOFT timeout on stream reads only — the raw socket must stay
        # timeout-free (the yamux rx thread owns it; a socket timeout
        # would tear down an idle healthy session).
        self._timeout = t

    def getpeername(self):
        return self._sock.getpeername()

    def shutdown(self, _how) -> None:
        self._session.close()

    def close(self) -> None:
        self._session.close()


class TcpEndpoint:
    """Drop-in for ``transport.Endpoint``: same attributes and methods, but
    peers live in other processes.

    ``secured=True`` upgrades every connection through the libp2p ladder
    (multistream-select -> Noise XX with a secp256k1 identity proof ->
    yamux) and runs the envelope protocol over one yamux stream — the
    reference's transport stack shape end to end."""

    def __init__(self, peer_id: str, host: str = "127.0.0.1", port: int = 0,
                 *, secured: bool = False, identity_priv: int = None):
        self.secured = secured
        if secured and identity_priv is None:
            from .discv5.enr import KeyPair

            identity_priv = KeyPair().priv
        self.identity_priv = identity_priv
        self.peer_id = peer_id
        self.inbound: "queue.Queue[Envelope]" = queue.Queue()
        self.on_connect: Optional[Callable[[str], None]] = None
        self.on_disconnect: Optional[Callable[[str], None]] = None
        self._conns: Dict[str, socket.socket] = {}
        # peer id -> Noise-proven secp256k1 identity (secured mode): while a
        # connection is LIVE, a second connection claiming its peer id with
        # a different key is refused (no eviction-by-impersonation).  The
        # binding lifts when the connection drops — peer ids here are
        # self-declared (the reference derives them from the key itself),
        # so pinning beyond the connection's life would lock out an
        # honestly-restarted peer with a fresh auto-generated key.
        self._peer_identities: Dict[str, bytes] = {}
        # peer id -> (host, listen_port) for re-dialing / peer exchange
        self.peer_listen_addrs: Dict[str, Tuple[str, int]] = {}
        # insertion-ordered ids whose address came from an UNAUTHENTICATED
        # PRUNE peer-exchange hint (bounded; only hints evict hints)
        self._px_hinted: Dict[str, None] = {}
        # peer -> live inbound meshsub reader count (DoS cap)
        self._meshsub_readers: Dict[str, int] = {}
        # per-connection write mutex: sendall from multiple threads must not
        # interleave partial frames on the stream
        self._write_locks: Dict[str, threading.Lock] = {}
        # peer -> (meshsub outbound yamux stream, its write lock): the
        # negotiated /meshsub/1.1.0 substream gossip-class envelopes ride
        # as protobuf RPC frames (secured mode only)
        self._meshsub_out: Dict[str, Tuple[object, threading.Lock]] = {}
        self._lock = threading.Lock()
        self._shutdown = False

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"tcp-accept-{peer_id}", daemon=True
        )
        self._accept_thread.start()

    # ------------------------------------------------------------- address

    @property
    def listen_addr(self) -> Tuple[str, int]:
        return self._listener.getsockname()[:2]

    # ------------------------------------------------------------- dialing

    def _hello(self) -> "Envelope":
        # data carries OUR listen port (u16 be): the ephemeral socket port a
        # peer sees is useless for dialing us back or for peer exchange
        return Envelope(kind="hello", sender=self.peer_id,
                        data=struct.pack(">H", self.listen_addr[1]))

    def _record_peer_addr(self, peer: str, sock: socket.socket,
                          hello: "Envelope") -> None:
        if len(hello.data) >= 2:
            (listen_port,) = struct.unpack(">H", hello.data[:2])
            try:
                host = sock.getpeername()[0]
            except OSError:
                return  # connection already torn down — nothing to record
            self._store_peer_addr(peer, (host, listen_port))

    MAX_KNOWN_ADDRS = 1024  # bound the address book under peer churn

    def known_peer_addrs(self) -> Dict[str, Tuple[str, int]]:
        """Snapshot of known peer listen addresses (safe to iterate —
        handshake threads mutate the underlying dict under the lock)."""
        with self._lock:
            return dict(self.peer_listen_addrs)

    MAX_PX_HINTS = 256  # unauthenticated PX may only fill this many slots

    def px_hint(self, peer: str, addr: Tuple[str, int]) -> None:
        """PRUNE peer-exchange hint: record a dialable address only for
        peers we know NOTHING about — PX comes from an arbitrary peer and
        must never override OR DISPLACE an address learned from an
        established connection (address-book poisoning).  Hints live in a
        bounded sub-budget and only ever evict other hints; check and
        store are one critical section so a concurrent authoritative
        store wins."""
        with self._lock:
            if peer in self.peer_listen_addrs or peer == self.peer_id:
                return
            while len(self._px_hinted) >= self.MAX_PX_HINTS:
                victim = next(iter(self._px_hinted))
                self._px_hinted.pop(victim, None)
                self.peer_listen_addrs.pop(victim, None)
            if len(self.peer_listen_addrs) >= self.MAX_KNOWN_ADDRS:
                return  # book full of authoritative entries: drop the hint
            self._px_hinted[peer] = None
            self.peer_listen_addrs[peer] = addr

    def _store_peer_addr(self, peer: str, addr: Tuple[str, int]) -> None:
        with self._lock:
            # an authoritative store upgrades any PX hint for this peer
            self._px_hinted.pop(peer, None)
            self.peer_listen_addrs.pop(peer, None)
            self.peer_listen_addrs[peer] = addr
            while len(self.peer_listen_addrs) > self.MAX_KNOWN_ADDRS:
                victim = next(iter(self.peer_listen_addrs))
                self.peer_listen_addrs.pop(victim)
                self._px_hinted.pop(victim, None)

    def _upgrade_outbound(self, sock: socket.socket):
        """Shared ladder (noise.upgrade_outbound) + the envelope stream,
        negotiated per-stream with multistream-select like every libp2p
        substream.  The raw socket's timeout stays in force through the
        whole upgrade (a stalling peer fails the handshake instead of
        pinning it)."""
        from .noise import upgrade_outbound
        from .noise.multistream import negotiate_outbound

        session = upgrade_outbound(sock, self.identity_priv)
        stream = session.open_stream()
        negotiate_outbound(stream, [ENVELOPE_PROTOCOL])
        return _SecuredChannel(session, stream, sock)

    def _upgrade_inbound(self, sock: socket.socket):
        from .noise import upgrade_inbound
        from .noise.multistream import negotiate_inbound

        session = upgrade_inbound(sock, self.identity_priv)
        stream = session.accept_stream(timeout=10.0)
        negotiate_inbound(stream, [ENVELOPE_PROTOCOL])
        return _SecuredChannel(session, stream, sock)

    def dial(self, host: str, port: int, timeout: float = 5.0) -> str:
        """Connect to a remote endpoint; returns its peer id."""
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(timeout)
        try:
            if self.secured:
                sock = self._upgrade_outbound(sock)
                sock.settimeout(timeout)  # soft bound on the hello reads
            sock.sendall(_encode(self._hello()))
            payload = _read_frame(sock)
            if payload is None:
                raise TcpTransportError("peer closed during handshake")
            hello = _decode(payload)
            if hello.kind != "hello":
                raise TcpTransportError(
                    f"bad handshake frame kind {hello.kind!r}")
        except Exception:
            # no leaked fd (or yamux rx thread) on a failed handshake
            try:
                sock.close()
            except OSError:
                pass
            raise
        sock.settimeout(None)
        if not self._register_conn(hello.sender, sock):
            raise TcpTransportError(
                f"peer {hello.sender!r} refused: identity mismatch with a "
                "live connection")
        # the address we DIALED is authoritative for this peer (recorded
        # only for ESTABLISHED connections)
        self._store_peer_addr(hello.sender, (host, port))
        return hello.sender

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handshake_inbound, args=(sock,), daemon=True
            ).start()

    def _handshake_inbound(self, sock: socket.socket) -> None:
        try:
            sock.settimeout(5.0)
            if self.secured:
                sock = self._upgrade_inbound(sock)
                sock.settimeout(5.0)  # soft bound on the hello reads
            payload = _read_frame(sock)
            if payload is None:
                sock.close()
                return
            hello = _decode(payload)
            if hello.kind != "hello":
                sock.close()
                return
            sock.sendall(_encode(self._hello()))
            sock.settimeout(None)
        except Exception:
            sock.close()
            return
        if self._register_conn(hello.sender, sock):
            # address recorded only for ESTABLISHED connections — a refused
            # impersonator must not poison the address book either
            self._record_peer_addr(hello.sender, sock, hello)

    def _register_conn(self, peer: str, sock: socket.socket) -> bool:
        """Returns False when the connection was REFUSED (identity
        mismatch against a live binding) — callers must not report it as
        established.  Check and install are ONE critical section: two
        concurrent handshakes for the same peer id must never leave the
        binding describing a key other than the surviving connection's."""
        identity = getattr(sock, "remote_identity", None)
        old = None
        with self._lock:
            bound = self._peer_identities.get(peer)
            if (identity is not None and bound is not None
                    and bound != identity and peer in self._conns):
                refused = True  # live conn + proven-key mismatch
            else:
                refused = False
                if identity is not None:
                    self._peer_identities[peer] = identity
                old = self._conns.pop(peer, None)
                self._conns[peer] = sock
                self._write_locks[peer] = threading.Lock()
                # the superseded connection's meshsub stream dies with its
                # session — a send through it would tear down THIS conn
                self._meshsub_out.pop(peer, None)
        if refused:
            try:
                sock.close()
            except OSError:
                pass
            return False
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        threading.Thread(
            target=self._read_loop, args=(peer, sock),
            name=f"tcp-read-{self.peer_id}-{peer}", daemon=True,
        ).start()
        session = getattr(sock, "_session", None)
        if session is not None:
            # Secured connection: accept the peer's substreams (its
            # outbound meshsub) BEFORE opening ours — two nodes opening
            # simultaneously must not deadlock on each other's accept.
            threading.Thread(
                target=self._stream_demux, args=(peer, sock, session),
                name=f"meshsub-demux-{self.peer_id}-{peer}", daemon=True,
            ).start()
            try:
                self._open_meshsub(peer, sock, session)
            except Exception:
                # gossip falls back to the envelope stream — same bytes
                # at the service layer, just not the protobuf framing
                pass
        if self.on_connect:
            self.on_connect(peer)
        return True

    # ------------------------------------------------------------ meshsub

    def _open_meshsub(self, peer: str, channel, session) -> None:
        """Open + negotiate OUR /meshsub/1.1.0 send stream (libp2p
        gossipsub keeps one unidirectional outbound stream per peer)."""
        from .noise.multistream import negotiate_outbound

        stream = session.open_stream()
        negotiate_outbound(stream, [MESHSUB_PROTOCOL])
        with self._lock:
            if self._conns.get(peer) is not channel:
                stream.close()  # superseded while negotiating
                return
            self._meshsub_out[peer] = (stream, threading.Lock())

    def _stream_demux(self, peer: str, channel, session) -> None:
        """Accept inbound substreams for the connection's lifetime and
        dispatch by negotiated protocol (the libp2p behaviour's inbound
        stream handler)."""
        from .noise.multistream import MultistreamError, negotiate_inbound
        from .noise.yamux import YamuxError

        while not self._shutdown and session._running:
            with self._lock:
                if self._conns.get(peer) is not channel:
                    return  # superseded
            try:
                stream = session.accept_stream(timeout=5.0)
            except YamuxError:
                continue
            except Exception:
                return
            try:
                proto = negotiate_inbound(stream, [MESHSUB_PROTOCOL])
            except (MultistreamError, YamuxError, OSError):
                try:
                    stream.close()
                except Exception:
                    pass
                continue
            if proto == MESHSUB_PROTOCOL:
                # libp2p gossipsub keeps ONE inbound stream per peer (a
                # replacement during re-negotiation makes two briefly);
                # anything beyond that is a thread-exhaustion attack.
                with self._lock:
                    live = self._meshsub_readers.get(peer, 0)
                    if live >= 2:
                        over = True
                    else:
                        over = False
                        self._meshsub_readers[peer] = live + 1
                if over:
                    try:
                        stream.close()
                    except Exception:
                        pass
                    continue
                threading.Thread(
                    target=self._meshsub_read_loop,
                    args=(peer, channel, stream),
                    name=f"meshsub-read-{self.peer_id}-{peer}", daemon=True,
                ).start()

    def _meshsub_read_loop(self, peer: str, channel, stream) -> None:
        """Decode varint-delimited protobuf RPC frames into Envelopes.
        A protocol violation (StrictNoSign field, bad framing) drops the
        CONNECTION — the reference's gossipsub handler does the same for
        invalid RPCs."""
        from . import pb

        violated = False
        try:
            while not self._shutdown:
                rpc = pb.read_frame(lambda n: stream.recv_exact(n, timeout=None))
                for env in _rpc_to_envs(peer, rpc):
                    self.inbound.put(env)
        except pb.PbError:
            violated = True
        except Exception:
            pass
        finally:
            with self._lock:
                live = self._meshsub_readers.get(peer, 0) - 1
                if live > 0:
                    self._meshsub_readers[peer] = live
                else:
                    self._meshsub_readers.pop(peer, None)
        if violated:
            with self._lock:
                current = self._conns.get(peer) is channel
            if current:
                self._drop_conn(peer, channel)

    # ---------------------------------------------------------------- io

    def _read_loop(self, peer: str, sock: socket.socket) -> None:
        try:
            while not self._shutdown:
                payload = _read_frame(sock)
                if payload is None:
                    break
                try:
                    env = _decode(payload)
                except (TcpTransportError, KeyError, ValueError):
                    break  # protocol violation: drop the connection
                self.inbound.put(env)
        except (OSError, TcpTransportError):
            pass
        self._drop_conn(peer, sock)

    def _drop_conn(self, peer: str, sock: socket.socket) -> None:
        with self._lock:
            if self._conns.get(peer) is sock:
                del self._conns[peer]
                self._write_locks.pop(peer, None)
                self._meshsub_out.pop(peer, None)
                # the identity binding lives as long as the connection
                self._peer_identities.pop(peer, None)
            else:
                return  # superseded by a reconnect
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        if self.on_disconnect and not self._shutdown:
            self.on_disconnect(peer)

    # -------------------------------------------------- Endpoint interface

    def connected_peers(self) -> Set[str]:
        with self._lock:
            return set(self._conns)

    def send(self, to: str, env: Envelope) -> bool:
        with self._lock:
            sock = self._conns.get(to)
            wlock = self._write_locks.get(to)
            meshsub = (self._meshsub_out.get(to)
                       if env.kind in MESHSUB_KINDS else None)
        if sock is None or wlock is None:
            return False
        try:
            if meshsub is not None:
                from . import pb

                stream, mlock = meshsub
                frame = pb.encode_frame(_env_to_rpc(env))
                with mlock:
                    stream.send(frame)
                return True
            with wlock:
                sock.sendall(_encode(env))
            return True
        except Exception as e:
            # secured channels raise YamuxError/NoiseError, raw sockets
            # OSError — the Endpoint contract is bool either way, and a
            # dead connection must be dropped (on_disconnect must fire)
            from .noise.protocol import NoiseError
            from .noise.yamux import YamuxError

            if not isinstance(e, (OSError, YamuxError, NoiseError)):
                raise
            self._drop_conn(to, sock)
            return False

    def disconnect(self, peer: str) -> None:
        with self._lock:
            sock = self._conns.get(peer)
        if sock is not None:
            self._drop_conn(peer, sock)

    def close(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.items())
            self._conns.clear()
        for _, sock in conns:
            try:
                # shutdown() wakes the peer AND our own blocked reader thread
                # (close() alone doesn't interrupt an in-flight recv)
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
