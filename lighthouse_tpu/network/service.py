"""Per-node network service: gossip pub/sub + RPC streams over a transport
endpoint.

The role of the reference's ``lighthouse_network`` service composition
(`service/mod.rs`): owns the transport endpoint, the peer manager, topic
subscriptions, the seen-message cache, and RPC request/response correlation.

Gossip is real gossipsub v1.1 behaviour: inbound messages dedup by the
eth2 message-id (SHA256(domain + uncompressed payload)[:20]), route to the
router for validation, and forward only after acceptance — into a mesh
maintained by SubOpts subscription exchange and heartbeat GRAFT/PRUNE
between D_low/D_high with v1.1 prune backoff + peer exchange, plus
IHAVE/IWANT lazy pull and score-threshold gates.  On secured TCP
connections these envelopes ride the wire as ``/meshsub/1.1.0`` protobuf
RPC frames (``tcp_transport`` + ``pb``); on the in-process hub they stay
envelopes — same behaviour either way.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from .. import metrics
from . import rpc as rpc_mod
from .peer_manager import PeerManager
from .transport import Endpoint, Envelope

#: Every gossip validation REJECT, by topic kind and reason — the router's
#: rejection paths all funnel through ``NetworkService.reject_gossip`` so a
#: lying peer's junk is simultaneously counted here and scored into the
#: graylist/ban ladder (reference: gossipsub REJECT -> peer penalty).
GOSSIP_REJECTED = metrics.counter(
    "gossip_rejected_total",
    "gossip messages rejected at validation, by topic kind and reason",
)

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
SEEN_CACHE_SIZE = 16384

# Gossipsub-shaped dissemination (reference vendored gossipsub: behaviour.rs
# mesh maintenance + IHAVE/IWANT lazy gossip).  Eager push goes to at most
# MESH_DEGREE peers per topic; up to LAZY_DEGREE others get an IHAVE with the
# message id and pull what they miss with IWANT.  With few peers everything
# degenerates to the old flood — same delivery, bounded amplification at
# scale.
MESH_DEGREE = 8  # gossipsub D
MESH_DEGREE_LOW = 4  # D_low: heartbeat grafts below this
MESH_DEGREE_HIGH = 12  # D_high: heartbeat prunes above this
LAZY_DEGREE = 6  # gossip_lazy
MCACHE_SIZE = 512  # message cache entries servable via IWANT
IWANT_RETRY_SECS = 5.0  # re-pull window when an advertiser never delivers
HEARTBEAT_SECS = 1.0  # gossipsub heartbeat_interval
PRUNE_BACKOFF_SECS = 60  # v1.1 prune_backoff: no re-graft window
MAX_PROMISES_PER_PEER = 32  # outstanding IWANTs we owe any one advertiser
PX_PEERS = 16  # v1.1 prune_peers: peer-exchange records per PRUNE

# Gossipsub v1.1 peer-score thresholds (reference PeerScoreThresholds /
# lighthouse_network's gossipsub config), mapped onto THIS peer manager's
# score scale (disconnect at -20, ban at -50 — peer_manager.py):
#  - below GOSSIP: the peer gets no eager push and no IHAVE from us
#  - below PUBLISH: our own publications skip it too
#  - below GRAYLIST: every incoming gossip/control message is ignored
GOSSIP_THRESHOLD = -5.0
PUBLISH_THRESHOLD = -10.0
GRAYLIST_THRESHOLD = -16.0


def message_id(uncompressed: bytes) -> bytes:
    """Spec gossip message-id for snappy-decodable messages."""
    return hashlib.sha256(MESSAGE_DOMAIN_VALID_SNAPPY + uncompressed).digest()[:20]


class NetworkService:
    def __init__(self, endpoint: Endpoint, peer_manager: Optional[PeerManager] = None,
                 rate_limiter=None, clock=None):
        from .rate_limiter import RPCRateLimiter

        self.endpoint = endpoint
        self.peer_id = endpoint.peer_id
        if peer_manager is not None:
            self.peer_manager = peer_manager
        else:
            # clock: optional callable for score decay / ban lifts — the
            # simulator threads its virtual clock here so peer scoring is
            # deterministic under host load (ISSUE 20)
            self.peer_manager = (PeerManager(clock=clock) if clock is not None
                                 else PeerManager())
        self.rate_limiter = rate_limiter if rate_limiter is not None else RPCRateLimiter()
        # outbound throttle (self_limiter.rs): same quotas as we enforce
        # on peers — never send what we ourselves would reject
        self.self_limiter = RPCRateLimiter()
        self.subscriptions: set = set()
        # gossipsub mesh state (reference vendored gossipsub behaviour.rs):
        # peer_topics — which topics each connected peer announced via
        # SubOpts; mesh — full-message peers per topic (both grafted-by-us
        # and grafted-us); _graft_backoff — (peer, topic) -> monotonic
        # deadline before which re-GRAFT is refused (v1.1 prune backoff)
        self.peer_topics: Dict[str, set] = {}
        self.mesh: Dict[str, set] = {}
        self._graft_backoff: Dict[Tuple[str, str], float] = {}
        self._mesh_lock = threading.Lock()
        self._last_heartbeat = 0.0
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        # mid -> (topic, compressed, origin trace_ctx): the cached ctx rides
        # IWANT re-serves, so a pulled message still carries its ORIGINAL
        # publisher's trace context, not the re-server's.
        self._mcache: "OrderedDict[bytes, Tuple[str, bytes, Optional[dict]]]" = OrderedDict()
        # mid -> (sent_at, advertiser, topic): a peer whose IHAVE we
        # pulled owes us the message (gossip_promises.rs); broken promises
        # take the mild behaviour penalty, NEVER a violation-grade strike
        # (an honest peer's mcache eviction between IHAVE and IWANT is
        # normal churn)
        self._iwant_pending: "OrderedDict[bytes, Tuple[float, str, str]]" = OrderedDict()
        self._seen_lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._next_request_id = 1
        self._pending: Dict[int, dict] = {}
        # router hooks, set by Router.attach
        self.on_gossip: Optional[Callable] = None  # (topic, data, sender) -> bool accept
        # trace-aware variant: (topic, uncompressed, compressed, sender,
        # trace_ctx).  Preferred over on_gossip when set; the 4-arg hook
        # stays for callers (tests, harnesses) that don't care about ctx.
        self.on_gossip_ctx: Optional[Callable] = None
        self.on_rpc_request: Optional[Callable] = None  # (protocol, req, sender) -> chunks
        self.on_peer_connected: Optional[Callable] = None
        self.on_peer_disconnected: Optional[Callable] = None

        endpoint.on_connect = self._handle_connect
        endpoint.on_disconnect = self._handle_disconnect
        self._processing = False  # see _run: Simulator.settle quiescence
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._run, name=f"net-{self.peer_id}", daemon=True
        )
        self._thread.start()

    # ----------------------------------------------------------- lifecycle

    def _handle_connect(self, peer: str) -> None:
        if not self.peer_manager.on_connect(peer):
            self.endpoint.disconnect(peer)  # banned
            return
        # announce our topic interest (gossipsub: SubOpts on stream open)
        for topic in sorted(self.subscriptions):
            self.endpoint.send(
                peer, Envelope(kind="subscribe", sender=self.peer_id, topic=topic)
            )
        if self.on_peer_connected:
            self.on_peer_connected(peer)

    def _handle_disconnect(self, peer: str) -> None:
        with self._mesh_lock:
            self.peer_topics.pop(peer, None)
            for members in self.mesh.values():
                members.discard(peer)
        self.peer_manager.on_disconnect(peer)
        if self.on_peer_disconnected:
            self.on_peer_disconnected(peer)

    def shutdown(self) -> None:
        self._shutdown = True
        self.endpoint.inbound.put(None)  # wake the loop
        self._thread.join(timeout=2.0)

    # ------------------------------------------------------------- gossip

    def subscribe(self, topic: str) -> None:
        topic = str(topic)
        if topic in self.subscriptions:
            return
        self.subscriptions.add(topic)
        env = Envelope(kind="subscribe", sender=self.peer_id, topic=topic)
        for peer in self.endpoint.connected_peers():
            self.endpoint.send(peer, env)
        # mesh formation happens on the next heartbeat (gossipsub JOIN)

    def unsubscribe(self, topic: str) -> None:
        topic = str(topic)
        if topic not in self.subscriptions:
            return
        self.subscriptions.discard(topic)
        # promises for the topic we are LEAVING are void, not broken —
        # the delivery would be dropped at the subscription gate
        with self._seen_lock:
            for mid in [m for m, (_t, _a, t_) in self._iwant_pending.items()
                        if t_ == topic]:
                del self._iwant_pending[mid]
        # gossipsub LEAVE: PRUNE every mesh member, then announce
        with self._mesh_lock:
            members = self.mesh.pop(topic, set())
        for peer in members:
            self._send_prune(peer, topic)
        env = Envelope(kind="unsubscribe", sender=self.peer_id, topic=topic)
        for peer in self.endpoint.connected_peers():
            self.endpoint.send(peer, env)

    def _mark_seen(self, mid: bytes) -> bool:
        """True if newly seen."""
        with self._seen_lock:
            if mid in self._seen:
                return False
            self._seen[mid] = None
            while len(self._seen) > SEEN_CACHE_SIZE:
                self._seen.popitem(last=False)
            return True

    def _cache_message(self, mid: bytes, topic: str, compressed: bytes,
                       trace_ctx: Optional[dict] = None) -> None:
        with self._seen_lock:
            self._mcache[mid] = (topic, compressed, trace_ctx)
            while len(self._mcache) > MCACHE_SIZE:
                self._mcache.popitem(last=False)

    def _rank_key(self, topic: str):
        """Stable per-(node, topic) peer ranking.  OUR peer id is mixed
        into the order — a global order would make every node pick the same
        top peers and starve the tail; per-node orders give the
        random-graph connectivity gossipsub meshes rely on."""
        me = self.peer_id.encode()

        def key(p: str) -> bytes:
            return hashlib.sha256(me + p.encode() + topic.encode()).digest()

        return key

    def eager_lazy_split(self, topic: str, candidates, grafted) -> Tuple[list, list]:
        """The dissemination split: the grafted mesh topped up by ranked
        candidates to the target degree gets the full message; the next
        LAZY_DEGREE ranked peers get IHAVE."""
        grafted = set(grafted)
        ranked = sorted((p for p in candidates if p not in grafted),
                        key=self._rank_key(topic))
        eager = list(grafted) + ranked[:max(0, MESH_DEGREE - len(grafted))]
        lazy = [p for p in ranked if p not in eager][:LAZY_DEGREE]
        return eager, lazy

    def _topic_candidates(self, topic: str, exclude: Optional[str], floor: float):
        """Connected peers eligible for ``topic`` traffic: above the score
        floor and — when they have announced a subscription set — actually
        subscribed (gossipsub never pushes to peers outside the topic).  A
        peer with NO announcement yet is included: its SubOpts may still be
        in flight."""
        pm = self.peer_manager
        with self._mesh_lock:
            # membership-only reads under the lock — no per-message deep
            # copy of every peer's whole topic set
            excluded = {p for p, ts in self.peer_topics.items()
                        if topic not in ts}
        out = []
        for p in pm.connected_peers():
            if p == exclude or p in excluded or pm.score(p) < floor:
                continue
            out.append(p)
        return out

    def _disseminate(self, topic: str, mid: bytes, compressed: bytes,
                     exclude: Optional[str], publishing: bool = False,
                     trace_ctx: Optional[dict] = None) -> int:
        self._cache_message(mid, topic, compressed, trace_ctx=trace_ctx)
        # v1.1 score gates: low-scored peers fall out of gossip entirely,
        # and our OWN publications demand the stricter publish threshold.
        floor = PUBLISH_THRESHOLD if publishing else GOSSIP_THRESHOLD
        candidates = self._topic_candidates(topic, exclude, floor)
        with self._mesh_lock:
            grafted = set(self.mesh.get(topic, ())) & set(candidates)
        # Eager push: the grafted mesh, topped up by ranked candidates until
        # the target degree — a just-subscribed node has full delivery
        # before its first heartbeat forms the mesh.
        eager, lazy = self.eager_lazy_split(topic, candidates, grafted)
        env = Envelope(kind="gossip", sender=self.peer_id, topic=topic,
                       data=compressed, trace_ctx=trace_ctx)
        n = 0
        for peer in eager:
            if self.endpoint.send(peer, env):
                n += 1
        if lazy:
            ihave = Envelope(kind="ihave", sender=self.peer_id, topic=topic, data=mid)
            for peer in lazy:
                self.endpoint.send(peer, ihave)
        return n

    def publish(self, topic: str, uncompressed: bytes) -> int:
        """Publish locally-originated data; returns #peers eagerly reached.

        The publisher's trace context is resolved HERE (not lazily at
        ``Endpoint.send``) so the mcache entry carries it too — an IWANT
        re-serve must present the origin's context, deterministically,
        whichever node serves the pull."""
        from . import snappy_codec

        ctx = None
        if self.endpoint.scope is not None:
            from .. import telemetry_scope

            ctx = telemetry_scope.envelope_trace_ctx(self.endpoint.scope)
        mid = message_id(uncompressed)
        self._mark_seen(mid)
        return self._disseminate(
            str(topic), mid, snappy_codec.compress(uncompressed), exclude=None,
            publishing=True, trace_ctx=ctx,
        )

    def forward(self, topic: str, compressed: bytes, exclude: str,
                uncompressed: Optional[bytes] = None,
                trace_ctx: Optional[dict] = None) -> int:
        """Forward validated gossip.  Callers that hold the uncompressed
        bytes (the router always does) pass them to avoid re-decompressing
        multi-MB payloads on the propagation hot path.  ``trace_ctx``
        preserves the ORIGIN's envelope trace context across hops (the
        router passes through what it received)."""
        from . import snappy_codec

        if uncompressed is None:
            try:
                uncompressed = snappy_codec.decompress(compressed)
            except snappy_codec.SnappyError:
                return 0
        return self._disseminate(
            str(topic), message_id(uncompressed), compressed, exclude=exclude,
            trace_ctx=trace_ctx,
        )

    # ---------------------------------------------------------------- rpc

    def request(
        self, peer: str, protocol: str, request, timeout: float = 5.0
    ) -> List[Tuple[int, bytes, Optional[bytes]]]:
        """Blocking request; returns the response chunk list
        ``[(result, payload, context_bytes)]``.

        Outbound requests pass a SELF rate limiter first (reference
        ``rpc/self_limiter.rs``): we never send faster than peers are
        allowed to receive, so our own sync bursts cannot get us penalized
        or disconnected.  In this synchronous stack "queueing" = waiting
        for tokens, bounded by the request's own timeout."""
        from .rate_limiter import RateLimitExceeded, request_cost

        deadline = time.monotonic() + timeout
        cost = request_cost(protocol, request)
        throttled = False
        while True:
            try:
                self.self_limiter.allow(peer, protocol, cost)
                break
            except RateLimitExceeded as e:
                if e.fatal:
                    raise rpc_mod.RpcSelfLimited(
                        f"request to {peer} exceeds the {protocol} quota")
                if time.monotonic() >= deadline:
                    raise rpc_mod.RpcSelfLimited(
                        f"self-rate-limited to {peer} ({protocol})")
                throttled = True
                time.sleep(0.05)
        if throttled and deadline - time.monotonic() < 0.25:
            # the throttle consumed (almost) the whole budget: the network
            # wait below would time out instantly and be misread as the
            # PEER timing out — keep the attribution on our own limiter.
            # Only when the limiter actually waited: a small CALLER timeout
            # alone is not our throttle's fault.
            raise rpc_mod.RpcSelfLimited(
                f"self-rate-limited to {peer} ({protocol}): no budget left")
        with self._req_lock:
            rid = self._next_request_id
            self._next_request_id += 1
            entry = {"chunks": [], "done": threading.Event(), "protocol": protocol, "peer": peer}
            self._pending[rid] = entry
        env = Envelope(
            kind="rpc_request",
            sender=self.peer_id,
            protocol=protocol,
            request_id=rid,
            data=rpc_mod.encode_request(protocol, request),
        )
        if not self.endpoint.send(peer, env):
            with self._req_lock:
                self._pending.pop(rid, None)
            raise rpc_mod.RpcError(f"peer {peer} unreachable")
        # ONE budget covers throttle wait + network wait: time spent in the
        # self-limiter above comes out of the same deadline, so the caller
        # never blocks past its own timeout.
        if not entry["done"].wait(max(0.0, deadline - time.monotonic())):
            with self._req_lock:
                self._pending.pop(rid, None)
            raise rpc_mod.RpcError(f"request to {peer} timed out ({protocol})")
        return entry["chunks"]

    # ------------------------------------------------------------ inbound

    def _run(self) -> None:
        import queue as queue_mod

        while not self._shutdown:
            got_item = False
            try:
                env = self.endpoint.inbound.get(timeout=0.5)
                got_item = True
                # quiescence beacon for Simulator.settle().  NOTE: between
                # the get() above and this assignment the envelope is in
                # hand but invisible to both the queue and the flag — a
                # settle that read only .empty() + _processing could slip
                # into that gap.  Settle therefore keys on the queue's
                # task accounting (unfinished_tasks, decremented only in
                # the finally below), which has no such window; the flag
                # stays as a redundant beacon.
                self._processing = True
            except queue_mod.Empty:
                env = None
            # Drain score-triggered disconnects (reference: the peer
            # manager's heartbeat closes connections below the threshold).
            for peer in self.peer_manager.heartbeat():
                self.endpoint.disconnect(peer)
            now = time.monotonic()
            if now - self._last_heartbeat >= HEARTBEAT_SECS:
                self._last_heartbeat = now
                self._mesh_heartbeat(now)
                self._expire_gossip_promises(now)
            if env is None:
                if got_item:  # the stop() wake sentinel
                    self._processing = False
                    self.endpoint.inbound.task_done()
                continue
            # _processing stays True until the envelope's work is handed
            # off (router validation enqueues to the processor BEFORE the
            # finally clears it, so a settle check that sees False + empty
            # inbound + idle processor has seen every consequence)
            try:
                if env.kind == "gossip":
                    self._on_gossip(env)
                elif env.kind == "ihave":
                    self._on_ihave(env)
                elif env.kind == "iwant":
                    self._on_iwant(env)
                elif env.kind == "subscribe":
                    self._on_subscribe(env)
                elif env.kind == "unsubscribe":
                    self._on_unsubscribe(env)
                elif env.kind == "graft":
                    self._on_graft(env)
                elif env.kind == "prune":
                    self._on_prune(env)
                elif env.kind == "rpc_request":
                    self._on_rpc_request(env)
                elif env.kind == "rpc_response":
                    self._on_rpc_response(env)
            except Exception:
                # network loop must survive malformed input (reference:
                # codec errors → peer penalty, not a crash)
                from .peer_manager import PeerAction

                self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "codec error")
            finally:
                self._processing = False
                self.endpoint.inbound.task_done()

    # -------------------------------------------------- mesh maintenance

    def _send_prune(self, peer: str, topic: str) -> None:
        """PRUNE with v1.1 backoff + peer exchange.  Recording the backoff
        locally serves both directions: we won't re-graft the peer during
        the window, and a GRAFT from it inside the window is a violation."""
        from .transport import encode_prune_data

        px: list = []
        book_fn = getattr(self.endpoint, "known_peer_addrs", None)
        if book_fn is not None:
            with self._mesh_lock:
                excluded = {p for p, ts in self.peer_topics.items()
                            if topic not in ts}
            for p, (host, port) in book_fn().items():
                if p in (peer, self.peer_id) or p in excluded:
                    continue
                px.append(f"{host}:{port}|{p}")
                if len(px) >= PX_PEERS:
                    break
        with self._mesh_lock:
            self._graft_backoff[(peer, topic)] = (
                time.monotonic() + PRUNE_BACKOFF_SECS
            )
            self._trim_backoff_locked()
        self.endpoint.send(
            peer,
            Envelope(kind="prune", sender=self.peer_id, topic=topic,
                     data=encode_prune_data(PRUNE_BACKOFF_SECS, px)),
        )

    def _trim_backoff_locked(self) -> None:
        while len(self._graft_backoff) > 4096:
            self._graft_backoff.pop(next(iter(self._graft_backoff)))

    MAX_PEER_TOPICS = 1024  # a real node needs ~100 (64 subnets + core)

    def _on_subscribe(self, env: Envelope) -> None:
        if not env.topic:
            return
        # a queued announcement from an already-disconnected peer must not
        # resurrect its peer_topics entry (disconnect cleanup ran first)
        if env.sender not in self.endpoint.connected_peers():
            return
        from .peer_manager import PeerAction

        with self._mesh_lock:
            topics = self.peer_topics.setdefault(env.sender, set())
            if len(topics) >= self.MAX_PEER_TOPICS:
                overflow = env.topic not in topics
            else:
                topics.add(env.topic)
                overflow = False
        if overflow:
            self.peer_manager.report(
                env.sender, PeerAction.LOW_TOLERANCE, "subscription flood")

    def _on_unsubscribe(self, env: Envelope) -> None:
        if not env.topic:
            return
        with self._mesh_lock:
            self.peer_topics.get(env.sender, set()).discard(env.topic)
            self.mesh.get(env.topic, set()).discard(env.sender)

    def _on_graft(self, env: Envelope) -> None:
        """gossipsub handle_graft: accept into the mesh, or PRUNE back —
        and penalize backoff violations (v1.1 behaviour.rs)."""
        from .peer_manager import PeerAction

        topic, peer = env.topic, env.sender
        if not topic:
            return
        if peer not in self.endpoint.connected_peers():
            return  # stale GRAFT from a peer that already disconnected
        if topic not in self.subscriptions or self.peer_manager.score(peer) < 0:
            self._send_prune(peer, topic)
            return
        with self._mesh_lock:
            deadline = self._graft_backoff.get((peer, topic), 0.0)
        if time.monotonic() < deadline:
            self.peer_manager.report(
                peer, PeerAction.LOW_TOLERANCE, "graft inside prune backoff")
            self._send_prune(peer, topic)
            return
        with self._mesh_lock:
            self.mesh.setdefault(topic, set()).add(peer)
            # grafting implies the peer treats itself as subscribed
            self.peer_topics.setdefault(peer, set()).add(topic)

    def _on_prune(self, env: Envelope) -> None:
        from .transport import decode_prune_data

        topic, peer = env.topic, env.sender
        if not topic:
            return
        backoff, px = decode_prune_data(env.data)
        with self._mesh_lock:
            self.mesh.get(topic, set()).discard(peer)
            self._graft_backoff[(peer, topic)] = (
                time.monotonic() + min(int(backoff), 3600)
            )
            self._trim_backoff_locked()
        # v1.1 peer exchange: feed dialable records to the address book
        # (never overriding established entries — PX is a hint, not proof)
        hint = getattr(self.endpoint, "px_hint", None)
        if hint is None:
            return
        for rec in px[:PX_PEERS]:
            try:
                addr_part, pid = rec.rsplit("|", 1)
                host, port_s = addr_part.rsplit(":", 1)
                hint(pid, (host, int(port_s)))
            except ValueError:
                continue

    def _mesh_heartbeat(self, now: float) -> None:
        """Per-heartbeat mesh maintenance (gossipsub behaviour.rs
        heartbeat): expire backoffs, evict negative-score members, GRAFT up
        to D when below D_low, PRUNE down to D when above D_high."""
        pm = self.peer_manager
        connected = set(self.endpoint.connected_peers())
        with self._mesh_lock:
            for key in [k for k, d in self._graft_backoff.items() if d <= now]:
                del self._graft_backoff[key]
            mesh_snapshot = {t: set(m) for t, m in self.mesh.items()}
            backoff = dict(self._graft_backoff)
        for topic in sorted(self.subscriptions):
            snapshot = mesh_snapshot.get(topic, set())
            members = snapshot & connected
            removals = snapshot - connected  # gone peers leave the mesh
            bad = {p for p in members if pm.score(p) < 0}
            for p in bad:
                self._send_prune(p, topic)
            members -= bad
            removals |= bad
            additions: set = set()
            if len(members) < MESH_DEGREE_LOW:
                with self._mesh_lock:
                    subscribed = {p for p, ts in self.peer_topics.items()
                                  if topic in ts}
                candidates = [
                    p for p in connected
                    if p not in members
                    and pm.score(p) >= 0
                    and p in subscribed
                    and backoff.get((p, topic), 0.0) <= now
                ]
                ranked = sorted(candidates, key=self._rank_key(topic))
                graft = Envelope(kind="graft", sender=self.peer_id, topic=topic)
                for p in ranked[:MESH_DEGREE - len(members)]:
                    additions.add(p)
                    self.endpoint.send(p, graft)
            elif len(members) > MESH_DEGREE_HIGH:
                ranked = sorted(members, key=self._rank_key(topic))
                for p in ranked[MESH_DEGREE:]:
                    removals.add(p)
                    self._send_prune(p, topic)
            # Apply as DELTAS under the lock — an unsubscribe() or
            # disconnect that raced this round's snapshot must not be
            # clobbered by writing the snapshot back wholesale.
            with self._mesh_lock:
                if topic not in self.subscriptions:
                    self.mesh.pop(topic, None)
                    continue
                cur = self.mesh.setdefault(topic, set())
                cur -= removals
                cur |= additions

    @staticmethod
    def _topic_kind_label(topic: str) -> str:
        """Bounded-cardinality topic label: the topic KIND with subnet
        indices collapsed (64 attestation subnets are one label)."""
        try:
            kind = topic.split("/")[3]
        except IndexError:
            return "unknown"
        for prefix in ("beacon_attestation_", "sync_committee_",
                       "blob_sidecar_"):
            if kind.startswith(prefix) and kind[len(prefix):].isdigit():
                return prefix.rstrip("_")
        return kind or "unknown"

    def reject_gossip(self, sender: str, topic: str, reason: str,
                      action: Optional[str] = None, detail: str = "",
                      penalize: bool = True) -> None:
        """One funnel for every gossip validation REJECT: count it
        (``gossip_rejected_total{topic,reason}``) and report the sender into
        the scoring/graylist ladder.  ``reason`` is a bounded slug (the
        metric label); ``detail`` is the free-form part of the peer-manager
        report only.  ``penalize=False`` counts without scoring — for
        IGNORE-grade drops (view-lag races) that must stay visible but must
        never bleed honest peers."""
        from .peer_manager import PeerAction

        GOSSIP_REJECTED.inc(topic=self._topic_kind_label(topic), reason=reason)
        if penalize:
            self.peer_manager.report(
                sender, action or PeerAction.LOW_TOLERANCE,
                f"{reason}: {detail}" if detail else reason)

    def _graylisted(self, peer: str) -> bool:
        return self.peer_manager.score(peer) < GRAYLIST_THRESHOLD

    def _below_gossip_threshold(self, peer: str) -> bool:
        return self.peer_manager.score(peer) < GOSSIP_THRESHOLD

    def _on_gossip(self, env: Envelope) -> None:
        from . import snappy_codec

        if env.topic not in self.subscriptions or self._graylisted(env.sender):
            return
        try:
            uncompressed = snappy_codec.decompress(env.data)
        except snappy_codec.SnappyError:
            self.reject_gossip(env.sender, env.topic, "bad_snappy")
            return
        mid = message_id(uncompressed)
        with self._seen_lock:
            self._iwant_pending.pop(mid, None)  # pull satisfied (if any)
        if not self._mark_seen(mid):
            return
        # Router validates (possibly via the beacon processor) and calls
        # ``forward`` itself on acceptance — mirrors the reference's
        # propagate-after-validation flow.  The ctx-aware hook wins when
        # set; the 4-arg hook keeps its signature for existing callers.
        if self.on_gossip_ctx is not None:
            self.on_gossip_ctx(env.topic, uncompressed, env.data, env.sender,
                               env.trace_ctx)
        elif self.on_gossip is not None:
            self.on_gossip(env.topic, uncompressed, env.data, env.sender)

    def _on_ihave(self, env: Envelope) -> None:
        """Lazy-gossip advert: pull the message if we haven't seen it
        (gossipsub handle_ihave → IWANT)."""
        mid = env.data
        if (len(mid) != 20 or env.topic not in self.subscriptions
                or self._below_gossip_threshold(env.sender)):
            # v1.1: IHAVE from below-gossip-threshold peers is ignored
            return
        now = time.monotonic()
        with self._seen_lock:
            if mid in self._seen or mid in self._mcache:
                return
            pending = self._iwant_pending.get(mid)
            if pending is not None and now - pending[0] < IWANT_RETRY_SECS:
                return  # an earlier pull is still in flight
            # per-peer cap (reference caps IHAVEs per heartbeat): an
            # IHAVE-spammer must not evict everyone else's promise
            # tracking — excess adverts are simply not pulled
            outstanding = sum(
                1 for (_t, adv, _topic) in self._iwant_pending.values()
                if adv == env.sender)
            if outstanding >= MAX_PROMISES_PER_PEER:
                return
            stale = self._iwant_pending.pop(mid, None)
            self._iwant_pending[mid] = (now, env.sender, env.topic)
            evicted = []
            if stale is not None:
                # replacing an EXPIRED promise: its advertiser broke it —
                # a re-advertising attacker must not reset its own clock
                evicted.append(stale[1])
            while len(self._iwant_pending) > MCACHE_SIZE:
                _mid, (t0, adv, _topic) = self._iwant_pending.popitem(last=False)
                if now - t0 >= IWANT_RETRY_SECS:
                    # only an already-EXPIRED promise is broken; an
                    # in-window eviction is our own capacity problem, not
                    # the advertiser's fault
                    evicted.append(adv)
        from .peer_manager import PeerAction

        for advertiser in evicted:
            self.peer_manager.report(
                advertiser, PeerAction.HIGH_TOLERANCE, "broken gossip promise")
        self.endpoint.send(
            env.sender,
            Envelope(kind="iwant", sender=self.peer_id, topic=env.topic, data=mid),
        )

    def _expire_gossip_promises(self, now: float) -> None:
        """v1.1 gossip promises (reference gossip_promises.rs): an
        advertiser that never delivers after our IWANT is penalized — an
        attacker spamming IHAVEs for messages it won't serve wastes our
        pull budget and delays real delivery."""
        from .peer_manager import PeerAction

        with self._seen_lock:
            broken = [(mid, adv) for mid, (t, adv, _topic)
                      in self._iwant_pending.items()
                      if now - t >= IWANT_RETRY_SECS]
            for mid, _ in broken:
                del self._iwant_pending[mid]
        for _mid, advertiser in broken:
            # mild behaviour penalty (reference applies a quadratic
            # behaviour_penalty, not a violation strike): honest churn
            # costs -1; a persistent promise-breaker still accumulates out
            self.peer_manager.report(
                advertiser, PeerAction.HIGH_TOLERANCE, "broken gossip promise")

    def _on_iwant(self, env: Envelope) -> None:
        """Serve a cached message to a puller (gossipsub handle_iwant)."""
        if self._below_gossip_threshold(env.sender):
            return  # v1.1: no pull access below the gossip threshold
        with self._seen_lock:
            entry = self._mcache.get(env.data)
        if entry is None:
            return
        topic, compressed, trace_ctx = entry
        self.endpoint.send(
            env.sender,
            Envelope(kind="gossip", sender=self.peer_id, topic=topic,
                     data=compressed, trace_ctx=trace_ctx),
        )

    def _on_rpc_request(self, env: Envelope) -> None:
        from .peer_manager import PeerAction
        from .rate_limiter import RateLimitExceeded, request_cost

        try:
            request = rpc_mod.decode_request(env.protocol, env.data)
        except (rpc_mod.RpcError, Exception):
            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "bad rpc request")
            chunk = rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"bad request")
            self._send_response(env.sender, env.request_id, [chunk])
            return
        # DoS protection (reference rpc/rate_limiter.rs): cost-weighted
        # token buckets per (peer, protocol) before any chain work.
        try:
            self.rate_limiter.allow(
                env.sender, env.protocol, request_cost(env.protocol, request)
            )
        except RateLimitExceeded as e:
            self.peer_manager.report(
                env.sender,
                PeerAction.LOW_TOLERANCE if e.fatal else PeerAction.HIGH_TOLERANCE,
                "rpc rate limit",
            )
            code = rpc_mod.INVALID_REQUEST if e.fatal else rpc_mod.RESOURCE_UNAVAILABLE
            chunk = rpc_mod.encode_response_chunk(code, b"rate limited")
            self._send_response(env.sender, env.request_id, [chunk])
            return
        chunks: List[bytes] = []
        if self.on_rpc_request is not None:
            chunks = self.on_rpc_request(env.protocol, request, env.sender)
        self._send_response(env.sender, env.request_id, chunks)

    def _send_response(self, peer: str, request_id: int, chunks: List[bytes]) -> None:
        for chunk in chunks:
            self.endpoint.send(
                peer,
                Envelope(
                    kind="rpc_response",
                    sender=self.peer_id,
                    request_id=request_id,
                    data=chunk,
                ),
            )
        # stream end marker
        self.endpoint.send(
            peer,
            Envelope(kind="rpc_response", sender=self.peer_id, request_id=request_id, data=b""),
        )

    def _on_rpc_response(self, env: Envelope) -> None:
        with self._req_lock:
            entry = self._pending.get(env.request_id)
        if entry is None:
            return
        if env.sender != entry["peer"]:
            # Only the peer the request was sent to may answer it: request ids
            # are a predictable counter, so without this check any connected
            # peer could inject forged chunks into another peer's pending
            # request (poisoning sync and misattributing penalties).
            from .peer_manager import PeerAction

            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "forged rpc response")
            return
        if env.data == b"":
            with self._req_lock:
                self._pending.pop(env.request_id, None)
            entry["done"].set()
            return
        has_context = entry["protocol"] in rpc_mod.CONTEXT_PROTOCOLS
        result, payload, context, _ = rpc_mod.decode_response_chunk(env.data, has_context)
        entry["chunks"].append((result, payload, context))
