"""Per-node network service: gossip pub/sub + RPC streams over a transport
endpoint.

The role of the reference's ``lighthouse_network`` service composition
(`service/mod.rs`): owns the transport endpoint, the peer manager, topic
subscriptions, the seen-message cache, and RPC request/response correlation.

Gossip here is validated-then-flooded: inbound messages are deduplicated by
the eth2 message-id (SHA256(domain + uncompressed payload)[:20]), handed to
the router for validation, and forwarded to all connected peers only after
the router accepts — the same accept/reject propagation gating gossipsub
gives the reference (mesh degree/IWANT machinery is fabric-level detail the
in-process hub doesn't need; peer scoring still applies via the router's
reports).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from . import rpc as rpc_mod
from .peer_manager import PeerManager
from .transport import Endpoint, Envelope

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
SEEN_CACHE_SIZE = 16384


def message_id(uncompressed: bytes) -> bytes:
    """Spec gossip message-id for snappy-decodable messages."""
    return hashlib.sha256(MESSAGE_DOMAIN_VALID_SNAPPY + uncompressed).digest()[:20]


class NetworkService:
    def __init__(self, endpoint: Endpoint, peer_manager: Optional[PeerManager] = None,
                 rate_limiter=None):
        from .rate_limiter import RPCRateLimiter

        self.endpoint = endpoint
        self.peer_id = endpoint.peer_id
        self.peer_manager = peer_manager if peer_manager is not None else PeerManager()
        self.rate_limiter = rate_limiter if rate_limiter is not None else RPCRateLimiter()
        self.subscriptions: set = set()
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        self._seen_lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._next_request_id = 1
        self._pending: Dict[int, dict] = {}
        # router hooks, set by Router.attach
        self.on_gossip: Optional[Callable] = None  # (topic, data, sender) -> bool accept
        self.on_rpc_request: Optional[Callable] = None  # (protocol, req, sender) -> chunks
        self.on_peer_connected: Optional[Callable] = None
        self.on_peer_disconnected: Optional[Callable] = None

        endpoint.on_connect = self._handle_connect
        endpoint.on_disconnect = self._handle_disconnect
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._run, name=f"net-{self.peer_id}", daemon=True
        )
        self._thread.start()

    # ----------------------------------------------------------- lifecycle

    def _handle_connect(self, peer: str) -> None:
        if not self.peer_manager.on_connect(peer):
            self.endpoint.disconnect(peer)  # banned
            return
        if self.on_peer_connected:
            self.on_peer_connected(peer)

    def _handle_disconnect(self, peer: str) -> None:
        self.peer_manager.on_disconnect(peer)
        if self.on_peer_disconnected:
            self.on_peer_disconnected(peer)

    def shutdown(self) -> None:
        self._shutdown = True
        self.endpoint.inbound.put(None)  # wake the loop
        self._thread.join(timeout=2.0)

    # ------------------------------------------------------------- gossip

    def subscribe(self, topic: str) -> None:
        self.subscriptions.add(str(topic))

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.discard(str(topic))

    def _mark_seen(self, mid: bytes) -> bool:
        """True if newly seen."""
        with self._seen_lock:
            if mid in self._seen:
                return False
            self._seen[mid] = None
            while len(self._seen) > SEEN_CACHE_SIZE:
                self._seen.popitem(last=False)
            return True

    def publish(self, topic: str, uncompressed: bytes) -> int:
        """Publish locally-originated data; returns #peers reached."""
        from . import snappy_codec

        self._mark_seen(message_id(uncompressed))
        data = snappy_codec.compress(uncompressed)
        env = Envelope(kind="gossip", sender=self.peer_id, topic=str(topic), data=data)
        n = 0
        for peer in self.peer_manager.connected_peers():
            if self.endpoint.send(peer, env):
                n += 1
        return n

    def forward(self, topic: str, compressed: bytes, exclude: str) -> int:
        env = Envelope(kind="gossip", sender=self.peer_id, topic=str(topic), data=compressed)
        n = 0
        for peer in self.peer_manager.connected_peers():
            if peer != exclude and self.endpoint.send(peer, env):
                n += 1
        return n

    # ---------------------------------------------------------------- rpc

    def request(
        self, peer: str, protocol: str, request, timeout: float = 5.0
    ) -> List[Tuple[int, bytes, Optional[bytes]]]:
        """Blocking request; returns the response chunk list
        ``[(result, payload, context_bytes)]``."""
        with self._req_lock:
            rid = self._next_request_id
            self._next_request_id += 1
            entry = {"chunks": [], "done": threading.Event(), "protocol": protocol, "peer": peer}
            self._pending[rid] = entry
        env = Envelope(
            kind="rpc_request",
            sender=self.peer_id,
            protocol=protocol,
            request_id=rid,
            data=rpc_mod.encode_request(protocol, request),
        )
        if not self.endpoint.send(peer, env):
            with self._req_lock:
                self._pending.pop(rid, None)
            raise rpc_mod.RpcError(f"peer {peer} unreachable")
        if not entry["done"].wait(timeout):
            with self._req_lock:
                self._pending.pop(rid, None)
            raise rpc_mod.RpcError(f"request to {peer} timed out ({protocol})")
        return entry["chunks"]

    # ------------------------------------------------------------ inbound

    def _run(self) -> None:
        import queue as queue_mod

        while not self._shutdown:
            try:
                env = self.endpoint.inbound.get(timeout=0.5)
            except queue_mod.Empty:
                env = None
            # Drain score-triggered disconnects (reference: the peer
            # manager's heartbeat closes connections below the threshold).
            for peer in self.peer_manager.heartbeat():
                self.endpoint.disconnect(peer)
            if env is None:
                continue
            try:
                if env.kind == "gossip":
                    self._on_gossip(env)
                elif env.kind == "rpc_request":
                    self._on_rpc_request(env)
                elif env.kind == "rpc_response":
                    self._on_rpc_response(env)
            except Exception:
                # network loop must survive malformed input (reference:
                # codec errors → peer penalty, not a crash)
                from .peer_manager import PeerAction

                self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "codec error")

    def _on_gossip(self, env: Envelope) -> None:
        from . import snappy_codec
        from .peer_manager import PeerAction

        if env.topic not in self.subscriptions:
            return
        try:
            uncompressed = snappy_codec.decompress(env.data)
        except snappy_codec.SnappyError:
            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "bad snappy")
            return
        if not self._mark_seen(message_id(uncompressed)):
            return
        if self.on_gossip is None:
            return
        # Router validates (possibly via the beacon processor) and calls
        # ``forward`` itself on acceptance — mirrors the reference's
        # propagate-after-validation flow.
        self.on_gossip(env.topic, uncompressed, env.data, env.sender)

    def _on_rpc_request(self, env: Envelope) -> None:
        from .peer_manager import PeerAction
        from .rate_limiter import RateLimitExceeded, request_cost

        try:
            request = rpc_mod.decode_request(env.protocol, env.data)
        except (rpc_mod.RpcError, Exception):
            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "bad rpc request")
            chunk = rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"bad request")
            self._send_response(env.sender, env.request_id, [chunk])
            return
        # DoS protection (reference rpc/rate_limiter.rs): cost-weighted
        # token buckets per (peer, protocol) before any chain work.
        try:
            self.rate_limiter.allow(
                env.sender, env.protocol, request_cost(env.protocol, request)
            )
        except RateLimitExceeded as e:
            self.peer_manager.report(
                env.sender,
                PeerAction.LOW_TOLERANCE if e.fatal else PeerAction.HIGH_TOLERANCE,
                "rpc rate limit",
            )
            code = rpc_mod.INVALID_REQUEST if e.fatal else rpc_mod.RESOURCE_UNAVAILABLE
            chunk = rpc_mod.encode_response_chunk(code, b"rate limited")
            self._send_response(env.sender, env.request_id, [chunk])
            return
        chunks: List[bytes] = []
        if self.on_rpc_request is not None:
            chunks = self.on_rpc_request(env.protocol, request, env.sender)
        self._send_response(env.sender, env.request_id, chunks)

    def _send_response(self, peer: str, request_id: int, chunks: List[bytes]) -> None:
        for chunk in chunks:
            self.endpoint.send(
                peer,
                Envelope(
                    kind="rpc_response",
                    sender=self.peer_id,
                    request_id=request_id,
                    data=chunk,
                ),
            )
        # stream end marker
        self.endpoint.send(
            peer,
            Envelope(kind="rpc_response", sender=self.peer_id, request_id=request_id, data=b""),
        )

    def _on_rpc_response(self, env: Envelope) -> None:
        with self._req_lock:
            entry = self._pending.get(env.request_id)
        if entry is None:
            return
        if env.sender != entry["peer"]:
            # Only the peer the request was sent to may answer it: request ids
            # are a predictable counter, so without this check any connected
            # peer could inject forged chunks into another peer's pending
            # request (poisoning sync and misattributing penalties).
            from .peer_manager import PeerAction

            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "forged rpc response")
            return
        if env.data == b"":
            with self._req_lock:
                self._pending.pop(env.request_id, None)
            entry["done"].set()
            return
        has_context = entry["protocol"] in (
            rpc_mod.BLOCKS_BY_RANGE,
            rpc_mod.BLOCKS_BY_ROOT,
            rpc_mod.BLOBS_BY_RANGE,
            rpc_mod.BLOBS_BY_ROOT,
        )
        result, payload, context, _ = rpc_mod.decode_response_chunk(env.data, has_context)
        entry["chunks"].append((result, payload, context))
