"""Per-node network service: gossip pub/sub + RPC streams over a transport
endpoint.

The role of the reference's ``lighthouse_network`` service composition
(`service/mod.rs`): owns the transport endpoint, the peer manager, topic
subscriptions, the seen-message cache, and RPC request/response correlation.

Gossip here is validated-then-flooded: inbound messages are deduplicated by
the eth2 message-id (SHA256(domain + uncompressed payload)[:20]), handed to
the router for validation, and forwarded to all connected peers only after
the router accepts — the same accept/reject propagation gating gossipsub
gives the reference (mesh degree/IWANT machinery is fabric-level detail the
in-process hub doesn't need; peer scoring still applies via the router's
reports).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from . import rpc as rpc_mod
from .peer_manager import PeerManager
from .transport import Endpoint, Envelope

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
SEEN_CACHE_SIZE = 16384

# Gossipsub-shaped dissemination (reference vendored gossipsub: behaviour.rs
# mesh maintenance + IHAVE/IWANT lazy gossip).  Eager push goes to at most
# MESH_DEGREE peers per topic; up to LAZY_DEGREE others get an IHAVE with the
# message id and pull what they miss with IWANT.  With few peers everything
# degenerates to the old flood — same delivery, bounded amplification at
# scale.
MESH_DEGREE = 8  # gossipsub D
LAZY_DEGREE = 6  # gossip_lazy
MCACHE_SIZE = 512  # message cache entries servable via IWANT
IWANT_RETRY_SECS = 5.0  # re-pull window when an advertiser never delivers

# Gossipsub v1.1 peer-score thresholds (reference PeerScoreThresholds /
# lighthouse_network's gossipsub config), mapped onto THIS peer manager's
# score scale (disconnect at -20, ban at -50 — peer_manager.py):
#  - below GOSSIP: the peer gets no eager push and no IHAVE from us
#  - below PUBLISH: our own publications skip it too
#  - below GRAYLIST: every incoming gossip/control message is ignored
GOSSIP_THRESHOLD = -5.0
PUBLISH_THRESHOLD = -10.0
GRAYLIST_THRESHOLD = -16.0


def message_id(uncompressed: bytes) -> bytes:
    """Spec gossip message-id for snappy-decodable messages."""
    return hashlib.sha256(MESSAGE_DOMAIN_VALID_SNAPPY + uncompressed).digest()[:20]


class NetworkService:
    def __init__(self, endpoint: Endpoint, peer_manager: Optional[PeerManager] = None,
                 rate_limiter=None):
        from .rate_limiter import RPCRateLimiter

        self.endpoint = endpoint
        self.peer_id = endpoint.peer_id
        self.peer_manager = peer_manager if peer_manager is not None else PeerManager()
        self.rate_limiter = rate_limiter if rate_limiter is not None else RPCRateLimiter()
        self.subscriptions: set = set()
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        self._mcache: "OrderedDict[bytes, Tuple[str, bytes]]" = OrderedDict()
        self._iwant_pending: "OrderedDict[bytes, float]" = OrderedDict()
        self._seen_lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._next_request_id = 1
        self._pending: Dict[int, dict] = {}
        # router hooks, set by Router.attach
        self.on_gossip: Optional[Callable] = None  # (topic, data, sender) -> bool accept
        self.on_rpc_request: Optional[Callable] = None  # (protocol, req, sender) -> chunks
        self.on_peer_connected: Optional[Callable] = None
        self.on_peer_disconnected: Optional[Callable] = None

        endpoint.on_connect = self._handle_connect
        endpoint.on_disconnect = self._handle_disconnect
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._run, name=f"net-{self.peer_id}", daemon=True
        )
        self._thread.start()

    # ----------------------------------------------------------- lifecycle

    def _handle_connect(self, peer: str) -> None:
        if not self.peer_manager.on_connect(peer):
            self.endpoint.disconnect(peer)  # banned
            return
        if self.on_peer_connected:
            self.on_peer_connected(peer)

    def _handle_disconnect(self, peer: str) -> None:
        self.peer_manager.on_disconnect(peer)
        if self.on_peer_disconnected:
            self.on_peer_disconnected(peer)

    def shutdown(self) -> None:
        self._shutdown = True
        self.endpoint.inbound.put(None)  # wake the loop
        self._thread.join(timeout=2.0)

    # ------------------------------------------------------------- gossip

    def subscribe(self, topic: str) -> None:
        self.subscriptions.add(str(topic))

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.discard(str(topic))

    def _mark_seen(self, mid: bytes) -> bool:
        """True if newly seen."""
        with self._seen_lock:
            if mid in self._seen:
                return False
            self._seen[mid] = None
            while len(self._seen) > SEEN_CACHE_SIZE:
                self._seen.popitem(last=False)
            return True

    def _cache_message(self, mid: bytes, topic: str, compressed: bytes) -> None:
        with self._seen_lock:
            self._mcache[mid] = (topic, compressed)
            while len(self._mcache) > MCACHE_SIZE:
                self._mcache.popitem(last=False)

    def mesh_peers(self, topic: str, candidates) -> Tuple[list, list]:
        """(mesh, lazy) split: a stable per-(node, topic) choice of at most
        MESH_DEGREE full-message peers; up to LAZY_DEGREE of the rest get
        IHAVE.  OUR peer id is mixed into the ranking — a global order would
        make every node pick the same top peers and starve the tail; per-node
        orders give the random-graph connectivity gossipsub meshes rely on."""
        me = self.peer_id.encode()
        ranked = sorted(
            candidates,
            key=lambda p: hashlib.sha256(me + p.encode() + topic.encode()).digest(),
        )
        return ranked[:MESH_DEGREE], ranked[MESH_DEGREE:MESH_DEGREE + LAZY_DEGREE]

    def _disseminate(self, topic: str, mid: bytes, compressed: bytes,
                     exclude: Optional[str], publishing: bool = False) -> int:
        self._cache_message(mid, topic, compressed)
        # v1.1 score gates: low-scored peers fall out of gossip entirely,
        # and our OWN publications demand the stricter publish threshold.
        floor = PUBLISH_THRESHOLD if publishing else GOSSIP_THRESHOLD
        pm = self.peer_manager
        peers = [p for p in pm.connected_peers()
                 if p != exclude and pm.score(p) >= floor]
        mesh, lazy = self.mesh_peers(topic, peers)
        env = Envelope(kind="gossip", sender=self.peer_id, topic=topic, data=compressed)
        n = 0
        for peer in mesh:
            if self.endpoint.send(peer, env):
                n += 1
        if lazy:
            ihave = Envelope(kind="ihave", sender=self.peer_id, topic=topic, data=mid)
            for peer in lazy:
                self.endpoint.send(peer, ihave)
        return n

    def publish(self, topic: str, uncompressed: bytes) -> int:
        """Publish locally-originated data; returns #peers eagerly reached."""
        from . import snappy_codec

        mid = message_id(uncompressed)
        self._mark_seen(mid)
        return self._disseminate(
            str(topic), mid, snappy_codec.compress(uncompressed), exclude=None,
            publishing=True,
        )

    def forward(self, topic: str, compressed: bytes, exclude: str,
                uncompressed: Optional[bytes] = None) -> int:
        """Forward validated gossip.  Callers that hold the uncompressed
        bytes (the router always does) pass them to avoid re-decompressing
        multi-MB payloads on the propagation hot path."""
        from . import snappy_codec

        if uncompressed is None:
            try:
                uncompressed = snappy_codec.decompress(compressed)
            except snappy_codec.SnappyError:
                return 0
        return self._disseminate(
            str(topic), message_id(uncompressed), compressed, exclude=exclude
        )

    # ---------------------------------------------------------------- rpc

    def request(
        self, peer: str, protocol: str, request, timeout: float = 5.0
    ) -> List[Tuple[int, bytes, Optional[bytes]]]:
        """Blocking request; returns the response chunk list
        ``[(result, payload, context_bytes)]``."""
        with self._req_lock:
            rid = self._next_request_id
            self._next_request_id += 1
            entry = {"chunks": [], "done": threading.Event(), "protocol": protocol, "peer": peer}
            self._pending[rid] = entry
        env = Envelope(
            kind="rpc_request",
            sender=self.peer_id,
            protocol=protocol,
            request_id=rid,
            data=rpc_mod.encode_request(protocol, request),
        )
        if not self.endpoint.send(peer, env):
            with self._req_lock:
                self._pending.pop(rid, None)
            raise rpc_mod.RpcError(f"peer {peer} unreachable")
        if not entry["done"].wait(timeout):
            with self._req_lock:
                self._pending.pop(rid, None)
            raise rpc_mod.RpcError(f"request to {peer} timed out ({protocol})")
        return entry["chunks"]

    # ------------------------------------------------------------ inbound

    def _run(self) -> None:
        import queue as queue_mod

        while not self._shutdown:
            try:
                env = self.endpoint.inbound.get(timeout=0.5)
            except queue_mod.Empty:
                env = None
            # Drain score-triggered disconnects (reference: the peer
            # manager's heartbeat closes connections below the threshold).
            for peer in self.peer_manager.heartbeat():
                self.endpoint.disconnect(peer)
            if env is None:
                continue
            try:
                if env.kind == "gossip":
                    self._on_gossip(env)
                elif env.kind == "ihave":
                    self._on_ihave(env)
                elif env.kind == "iwant":
                    self._on_iwant(env)
                elif env.kind == "rpc_request":
                    self._on_rpc_request(env)
                elif env.kind == "rpc_response":
                    self._on_rpc_response(env)
            except Exception:
                # network loop must survive malformed input (reference:
                # codec errors → peer penalty, not a crash)
                from .peer_manager import PeerAction

                self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "codec error")

    def _graylisted(self, peer: str) -> bool:
        return self.peer_manager.score(peer) < GRAYLIST_THRESHOLD

    def _below_gossip_threshold(self, peer: str) -> bool:
        return self.peer_manager.score(peer) < GOSSIP_THRESHOLD

    def _on_gossip(self, env: Envelope) -> None:
        from . import snappy_codec
        from .peer_manager import PeerAction

        if env.topic not in self.subscriptions or self._graylisted(env.sender):
            return
        try:
            uncompressed = snappy_codec.decompress(env.data)
        except snappy_codec.SnappyError:
            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "bad snappy")
            return
        mid = message_id(uncompressed)
        with self._seen_lock:
            self._iwant_pending.pop(mid, None)  # pull satisfied (if any)
        if not self._mark_seen(mid):
            return
        if self.on_gossip is None:
            return
        # Router validates (possibly via the beacon processor) and calls
        # ``forward`` itself on acceptance — mirrors the reference's
        # propagate-after-validation flow.
        self.on_gossip(env.topic, uncompressed, env.data, env.sender)

    def _on_ihave(self, env: Envelope) -> None:
        """Lazy-gossip advert: pull the message if we haven't seen it
        (gossipsub handle_ihave → IWANT)."""
        mid = env.data
        if (len(mid) != 20 or env.topic not in self.subscriptions
                or self._below_gossip_threshold(env.sender)):
            # v1.1: IHAVE from below-gossip-threshold peers is ignored
            return
        now = time.monotonic()
        with self._seen_lock:
            if mid in self._seen or mid in self._mcache:
                return
            pending_at = self._iwant_pending.get(mid)
            if pending_at is not None and now - pending_at < IWANT_RETRY_SECS:
                return  # an earlier pull is still in flight
            # (re)pull: a prior advertiser may have disconnected or evicted
            # the entry before answering — later IHAVEs must be able to retry
            self._iwant_pending.pop(mid, None)
            self._iwant_pending[mid] = now
            while len(self._iwant_pending) > MCACHE_SIZE:
                self._iwant_pending.popitem(last=False)
        self.endpoint.send(
            env.sender,
            Envelope(kind="iwant", sender=self.peer_id, topic=env.topic, data=mid),
        )

    def _on_iwant(self, env: Envelope) -> None:
        """Serve a cached message to a puller (gossipsub handle_iwant)."""
        if self._below_gossip_threshold(env.sender):
            return  # v1.1: no pull access below the gossip threshold
        with self._seen_lock:
            entry = self._mcache.get(env.data)
        if entry is None:
            return
        topic, compressed = entry
        self.endpoint.send(
            env.sender,
            Envelope(kind="gossip", sender=self.peer_id, topic=topic, data=compressed),
        )

    def _on_rpc_request(self, env: Envelope) -> None:
        from .peer_manager import PeerAction
        from .rate_limiter import RateLimitExceeded, request_cost

        try:
            request = rpc_mod.decode_request(env.protocol, env.data)
        except (rpc_mod.RpcError, Exception):
            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "bad rpc request")
            chunk = rpc_mod.encode_response_chunk(rpc_mod.INVALID_REQUEST, b"bad request")
            self._send_response(env.sender, env.request_id, [chunk])
            return
        # DoS protection (reference rpc/rate_limiter.rs): cost-weighted
        # token buckets per (peer, protocol) before any chain work.
        try:
            self.rate_limiter.allow(
                env.sender, env.protocol, request_cost(env.protocol, request)
            )
        except RateLimitExceeded as e:
            self.peer_manager.report(
                env.sender,
                PeerAction.LOW_TOLERANCE if e.fatal else PeerAction.HIGH_TOLERANCE,
                "rpc rate limit",
            )
            code = rpc_mod.INVALID_REQUEST if e.fatal else rpc_mod.RESOURCE_UNAVAILABLE
            chunk = rpc_mod.encode_response_chunk(code, b"rate limited")
            self._send_response(env.sender, env.request_id, [chunk])
            return
        chunks: List[bytes] = []
        if self.on_rpc_request is not None:
            chunks = self.on_rpc_request(env.protocol, request, env.sender)
        self._send_response(env.sender, env.request_id, chunks)

    def _send_response(self, peer: str, request_id: int, chunks: List[bytes]) -> None:
        for chunk in chunks:
            self.endpoint.send(
                peer,
                Envelope(
                    kind="rpc_response",
                    sender=self.peer_id,
                    request_id=request_id,
                    data=chunk,
                ),
            )
        # stream end marker
        self.endpoint.send(
            peer,
            Envelope(kind="rpc_response", sender=self.peer_id, request_id=request_id, data=b""),
        )

    def _on_rpc_response(self, env: Envelope) -> None:
        with self._req_lock:
            entry = self._pending.get(env.request_id)
        if entry is None:
            return
        if env.sender != entry["peer"]:
            # Only the peer the request was sent to may answer it: request ids
            # are a predictable counter, so without this check any connected
            # peer could inject forged chunks into another peer's pending
            # request (poisoning sync and misattributing penalties).
            from .peer_manager import PeerAction

            self.peer_manager.report(env.sender, PeerAction.LOW_TOLERANCE, "forged rpc response")
            return
        if env.data == b"":
            with self._req_lock:
                self._pending.pop(env.request_id, None)
            entry["done"].set()
            return
        has_context = entry["protocol"] in (
            rpc_mod.BLOCKS_BY_RANGE,
            rpc_mod.BLOCKS_BY_ROOT,
            rpc_mod.BLOBS_BY_RANGE,
            rpc_mod.BLOBS_BY_ROOT,
        )
        result, payload, context, _ = rpc_mod.decode_response_chunk(env.data, has_context)
        entry["chunks"].append((result, payload, context))
