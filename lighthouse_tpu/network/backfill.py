"""Backfill sync: fill history BEHIND a checkpoint anchor, newest-first.

Equivalent of the reference's ``network/src/sync/backfill_sync/mod.rs``
(1,201 LoC): after a checkpoint boot the chain runs forward from the anchor;
backfill walks BlocksByRange batches backwards, authenticating each block by
hash linkage to the anchor (``block.parent_root`` chains are as strong as
the weak-subjectivity root itself), and persists them to the store so the
node can serve history.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..metrics import BACKFILL_BATCH_RETRIES
from . import rpc as rpc_mod
from .peer_manager import PeerAction
from .sync import decode_signed_block

BATCH_SLOTS = 32
REQUEST_TIMEOUT_S = 10.0


class BackfillSync:
    def __init__(self, *, chain, service):
        self.chain = chain
        self.service = service
        # The backfill frontier: the oldest block we hold and its parent.
        anchor = chain.get_block(chain.genesis_block_root)
        if anchor is not None:
            self.oldest_slot = int(anchor.message.slot)
            self.expected_parent = bytes(anchor.message.parent_root)
        else:
            self.oldest_slot = 0  # genesis boot: nothing to backfill
            self.expected_parent = b"\x00" * 32
        self.blocks_filled = 0

    @property
    def complete(self) -> bool:
        return self.oldest_slot <= 1 or self.expected_parent == b"\x00" * 32

    def backfill_from(self, peer: str, target_slot: int = 0, *,
                      request_timeout: float = REQUEST_TIMEOUT_S,
                      fallback_peers: Sequence[str] = ()) -> int:
        """Pull batches from ``peer`` until history reaches ``target_slot``
        (or the peer runs dry).  Returns #blocks persisted.

        Every batch request carries ``request_timeout``; a batch that fails
        (dead peer, RPC timeout) is retried ONCE against the next peer in
        ``fallback_peers`` (``backfill_batch_retries_total{outcome}``) —
        a single dead peer bounds the stall to one timeout instead of
        parking backfill forever (the churn scenarios kill the serving
        peer mid-backfill to prove exactly this)."""
        chain = self.chain
        filled = 0
        candidates = [peer] + [p for p in fallback_peers if p != peer]
        while not self.complete and self.oldest_slot > target_slot:
            start = max(target_slot, self.oldest_slot - BATCH_SLOTS)
            count = self.oldest_slot - start
            request = rpc_mod.BlocksByRangeRequest(start_slot=start, count=count)
            chunks = None
            self_limited = False
            failed: List[str] = []
            for attempt, serving in enumerate(candidates[:2]):
                try:
                    chunks = self.service.request(
                        serving, rpc_mod.BLOCKS_BY_RANGE, request,
                        timeout=request_timeout,
                    )
                except rpc_mod.RpcSelfLimited:
                    self_limited = True  # OUR throttle: resume later, no blame
                    break
                except rpc_mod.RpcError:
                    self.service.peer_manager.report(
                        serving, PeerAction.MID_TOLERANCE, "backfill rpc failed"
                    )
                    failed.append(serving)
                    if attempt == 0 and len(candidates) > 1:
                        BACKFILL_BATCH_RETRIES.inc(outcome="retried")
                        continue  # one retry, against a DIFFERENT peer
                    break
                if attempt > 0:
                    BACKFILL_BATCH_RETRIES.inc(outcome="recovered")
                peer = serving
                # future batches: the answering peer first, proven-dead
                # peers demoted LAST (a later failure must fall back to a
                # still-untried peer, not straight back to the dead one)
                candidates = ([serving]
                              + [p for p in candidates
                                 if p != serving and p not in failed]
                              + failed)
                break
            if chunks is None:
                if not self_limited and len(candidates) > 1:
                    BACKFILL_BATCH_RETRIES.inc(outcome="exhausted")
                break
            blocks = []
            for result, payload, _ctx in chunks:
                if result != rpc_mod.SUCCESS:
                    continue
                try:
                    blocks.append(decode_signed_block(chain, payload))
                except Exception:
                    self.service.peer_manager.report(
                        peer, PeerAction.LOW_TOLERANCE, "undecodable backfill block"
                    )
                    return filled
            if not blocks:
                break  # peer has nothing older (or pruned history)
            progressed = False
            # Walk newest->oldest verifying the parent-hash chain into the
            # frontier (backfill's authenticity comes from this linkage).
            for signed in sorted(blocks, key=lambda b: -int(b.message.slot)):
                root = signed.message.hash_tree_root()
                if root != self.expected_parent:
                    self.service.peer_manager.report(
                        peer, PeerAction.LOW_TOLERANCE,
                        "backfill block breaks the hash chain",
                    )
                    return filled
                chain.db.put_block(root, signed)
                self._backfill_blobs(peer, root, signed)
                self.expected_parent = bytes(signed.message.parent_root)
                self.oldest_slot = int(signed.message.slot)
                filled += 1
                self.blocks_filled += 1
                progressed = True
                if self.complete:
                    break
            if not progressed:
                break
        return filled

    def _backfill_blobs(self, peer: str, block_root: bytes, signed) -> None:
        """Fetch sidecars for a hash-chain-verified backfilled block inside
        the blob retention window (reference: backfill requests blobs
        alongside blocks post-Deneb).  Verification and persistence live at
        the chain layer (``store_backfilled_blobs``: exact index coverage,
        commitment equality against the verified block, KZG batch proof)."""
        chain = self.chain
        commitments = getattr(signed.message.body, "blob_kzg_commitments", None)
        if not commitments:
            return
        horizon = chain.current_slot() - (
            chain.spec.min_epochs_for_blob_sidecars_requests
            * chain.spec.slots_per_epoch
        )
        if int(signed.message.slot) < horizon:
            return  # outside retention: blocks only (spec behavior)
        try:
            chunks = self.service.request(
                peer, rpc_mod.BLOBS_BY_ROOT,
                rpc_mod.BlobsByRootRequest(
                    ids=[(block_root, i) for i in range(len(commitments))]
                ),
                timeout=10.0,
            )
        except rpc_mod.RpcSelfLimited:
            return  # OUR outbound throttle, not the peer's failure
        except rpc_mod.RpcError:
            self.service.peer_manager.report(
                peer, PeerAction.HIGH_TOLERANCE, "backfill blobs unavailable"
            )
            return
        sidecars = []
        for result, payload, _ctx in chunks:
            if result != rpc_mod.SUCCESS:
                continue
            try:
                sidecars.append(chain.types.BlobSidecar.from_ssz_bytes(payload))
            except Exception:
                self.service.peer_manager.report(
                    peer, PeerAction.LOW_TOLERANCE, "undecodable backfill sidecar"
                )
                return
        from ..chain.beacon_chain import BlockError

        try:
            # chain-layer verification: exact index coverage, commitment
            # equality, KZG batch proof; persisted in the DB where retention
            # pruning governs it
            chain.store_backfilled_blobs(signed, sidecars)
        except BlockError as e:
            # incomplete or invalid: penalize and leave unstored so another
            # peer can be asked (re-running backfill re-requests this span)
            self.service.peer_manager.report(
                peer, PeerAction.MID_TOLERANCE, f"backfill blobs rejected: {e}"
            )
