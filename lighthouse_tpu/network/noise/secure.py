"""libp2p-noise over a socket: identity payloads + encrypted framing.

The libp2p-noise spec on top of ``protocol.py``:

* every handshake message and every transport frame rides a 2-byte
  big-endian length prefix (max 65535);
* messages 2 and 3 carry a protobuf ``NoiseHandshakePayload`` proving the
  peer's libp2p IDENTITY key (secp256k1 for eth2) owns this connection:
  ``identity_sig = Sign(identity_key, "noise-libp2p-static-key:" ||
  noise_static_pubkey)``;
* after the handshake the connection is an AEAD-framed byte stream.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Optional, Tuple

from ..discv5 import secp256k1
from .protocol import CipherState, HandshakeState, NoiseError


class ConnectionClosed(NoiseError):
    """Clean transport EOF — distinct from AEAD/parse failures, which MUST
    surface (a tampered frame must never read as a graceful close)."""

SIGNATURE_PREFIX = b"noise-libp2p-static-key:"
MAX_FRAME = 65535

# libp2p crypto.proto key types
KEY_TYPE_SECP256K1 = 2


# ------------------------------------------------------- minimal protobuf

def _pb_tag(field: int, wire: int) -> bytes:
    return bytes([(field << 3) | wire])


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _pb_bytes(field: int, data: bytes) -> bytes:
    return _pb_tag(field, 2) + _pb_varint(len(data)) + data


def _read_pb_varint(data: bytes, pos: int):
    val = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise NoiseError("truncated protobuf varint")
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        shift += 7
        if shift > 63:
            raise NoiseError("oversized protobuf varint")
        if not b & 0x80:
            return val, pos


def _pb_read(data: bytes):
    """Yield (field, wire, value) triples of a flat protobuf message.
    Bounds-checked — remote handshake payloads are attacker-controlled and
    must be REJECTED (NoiseError), never crash the acceptor."""
    pos = 0
    while pos < len(data):
        tag, pos = _read_pb_varint(data, pos)  # tags themselves are varints
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_pb_varint(data, pos)
            yield field, wire, val
        elif wire == 2:
            ln, pos = _read_pb_varint(data, pos)
            if pos + ln > len(data):
                raise NoiseError("truncated protobuf field")
            yield field, wire, data[pos:pos + ln]
            pos += ln
        else:
            raise NoiseError(f"unsupported protobuf wire type {wire}")


def _identity_key_proto(pubkey_compressed: bytes) -> bytes:
    """libp2p crypto.proto PublicKey{Type=Secp256k1, Data}."""
    return (_pb_tag(1, 0) + _pb_varint(KEY_TYPE_SECP256K1)
            + _pb_bytes(2, pubkey_compressed))


def _handshake_payload(identity_priv: int, noise_static_pub: bytes) -> bytes:
    """NoiseHandshakePayload{identity_key, identity_sig}."""
    pub = secp256k1.compress(secp256k1.pubkey(identity_priv))
    msg = hashlib.sha256(SIGNATURE_PREFIX + noise_static_pub).digest()
    sig = secp256k1.sign(identity_priv, msg)
    return (_pb_bytes(1, _identity_key_proto(pub)) + _pb_bytes(2, sig))


def _verify_payload(payload: bytes, noise_static_pub: bytes) -> bytes:
    """Returns the peer's compressed identity pubkey; raises on a bad proof."""
    identity_key_raw = identity_sig = None
    for field, _wire, value in _pb_read(payload):
        if field == 1:
            identity_key_raw = value
        elif field == 2:
            identity_sig = value
    if identity_key_raw is None or identity_sig is None:
        raise NoiseError("handshake payload missing identity key/signature")
    key_type = key_data = None
    for field, wire, value in _pb_read(identity_key_raw):
        if field == 1 and wire == 0:
            key_type = value
        elif field == 2:
            key_data = value
    if key_type != KEY_TYPE_SECP256K1 or key_data is None:
        raise NoiseError("unsupported libp2p identity key type")
    pub = secp256k1.decompress(key_data)
    msg = hashlib.sha256(SIGNATURE_PREFIX + noise_static_pub).digest()
    if not secp256k1.verify(pub, msg, identity_sig):
        raise NoiseError("libp2p identity signature invalid")
    return key_data


# ------------------------------------------------------------ connection


def _send_frame(sock: socket.socket, data: bytes) -> None:
    if len(data) > MAX_FRAME:
        raise NoiseError("noise frame exceeds 65535 bytes")
    sock.sendall(struct.pack(">H", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            raise ConnectionClosed(f"socket error: {e}") from e
        if not chunk:
            raise ConnectionClosed("connection closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> bytes:
    (ln,) = struct.unpack(">H", _recv_exact(sock, 2))
    return _recv_exact(sock, ln)


class NoiseConnection:
    """An AEAD-framed byte stream after a completed handshake."""

    def __init__(self, sock: socket.socket, send: CipherState,
                 recv: CipherState, remote_identity: bytes) -> None:
        self.sock = sock
        self._send = send
        self._recv = recv
        self.remote_identity = remote_identity  # compressed secp256k1 key
        self._rx_buf = b""

    @property
    def remote_peer_pub(self):
        return secp256k1.decompress(self.remote_identity)

    def send(self, data: bytes) -> None:
        # AEAD adds 16 bytes; chunk so every frame fits the u16 prefix.
        limit = MAX_FRAME - 16
        for off in range(0, len(data), limit):
            _send_frame(self.sock,
                        self._send.encrypt_with_ad(b"", data[off:off + limit]))

    def recv(self, n: int) -> bytes:
        """Up to ``n`` decrypted bytes (at least 1, blocking), '' on clean
        EOF.  An AEAD failure (tampered/injected frame) RAISES — active
        attacks must never masquerade as graceful close."""
        if not self._rx_buf:
            try:
                self._rx_buf = self._recv.decrypt_with_ad(
                    b"", _recv_frame(self.sock))
            except ConnectionClosed:
                return b""
        out, self._rx_buf = self._rx_buf[:n], self._rx_buf[n:]
        return out

    def recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.recv(n - len(buf))
            if not chunk:
                raise ConnectionClosed("connection closed mid-read")
            buf += chunk
        return buf

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def secure_dial(sock: socket.socket, identity_priv: int) -> NoiseConnection:
    """Initiator side of the libp2p-noise XX handshake."""
    hs = HandshakeState(initiator=True)
    _send_frame(sock, hs.write_message_1())
    payload2 = hs.read_message_2(_recv_frame(sock))
    remote_identity = _verify_payload(payload2, hs.rs)
    msg3, send, recv = hs.write_message_3(
        _handshake_payload(identity_priv, hs.s_pub)
    )
    _send_frame(sock, msg3)
    return NoiseConnection(sock, send, recv, remote_identity)


def secure_accept(sock: socket.socket, identity_priv: int) -> NoiseConnection:
    """Responder side of the libp2p-noise XX handshake."""
    hs = HandshakeState(initiator=False)
    hs.read_message_1(_recv_frame(sock))
    _send_frame(sock, hs.write_message_2(
        _handshake_payload(identity_priv, hs.s_pub)
    ))
    payload3, send, recv = hs.read_message_3(_recv_frame(sock))
    remote_identity = _verify_payload(payload3, hs.rs)
    return NoiseConnection(sock, send, recv, remote_identity)
