"""libp2p transport security + stream multiplexing on the real wire format.

Equivalent of the reference's connection upgrade stack
(``lighthouse_network``'s libp2p transport: ``noise`` then ``yamux`` —
service/mod.rs builds exactly this ladder): TCP connections are secured
with the Noise XX handshake (Noise_XX_25519_ChaChaPoly_SHA256, the
libp2p-noise spec, carrying a secp256k1 libp2p identity proof in the
handshake payload) and then multiplexed with yamux framing.

Modules:
- ``x25519``     — RFC 7748 curve25519 (pinned to the RFC's test vectors)
- ``protocol``   — the Noise protocol core (CipherState/SymmetricState/XX)
- ``secure``     — libp2p-noise over a socket: identity payloads, length-
                   prefixed encrypted frames
- ``yamux``      — the yamux multiplexer (SYN/ACK/FIN/RST, windows, ping)
- ``multistream``— multistream-select 1.0: the upgrade ladder entry points
                   (``upgrade_outbound``/``upgrade_inbound``) and per-stream
                   protocol negotiation
"""

from .multistream import upgrade_inbound, upgrade_outbound
from .secure import NoiseConnection, secure_accept, secure_dial
from .yamux import YamuxSession

__all__ = ["NoiseConnection", "secure_accept", "secure_dial",
           "YamuxSession", "upgrade_inbound", "upgrade_outbound"]
