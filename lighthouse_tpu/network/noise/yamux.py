"""yamux stream multiplexing on the real wire format.

The yamux spec (hashicorp/yamux, the multiplexer libp2p and the reference
negotiate over noise): 12-byte headers

    version(u8)=0 | type(u8) | flags(u16) | stream_id(u32) | length(u32)

big-endian; types Data=0 WindowUpdate=1 Ping=2 GoAway=3; flags SYN=1
ACK=2 FIN=4 RST=8.  Odd stream ids belong to the dialing side, even to
the accepting side.  Every stream starts with a 256 KiB receive window;
consumed bytes are re-credited with WindowUpdate frames.
"""

from __future__ import annotations

import queue
import struct
import threading
from typing import Callable, Dict, Optional

TYPE_DATA = 0
TYPE_WINDOW_UPDATE = 1
TYPE_PING = 2
TYPE_GOAWAY = 3

FLAG_SYN = 0x1
FLAG_ACK = 0x2
FLAG_FIN = 0x4
FLAG_RST = 0x8

INITIAL_WINDOW = 256 * 1024
HEADER = struct.Struct(">BBHII")


class YamuxError(Exception):
    pass


class YamuxStream:
    def __init__(self, session: "YamuxSession", stream_id: int) -> None:
        self.session = session
        self.stream_id = stream_id
        self._rx: "queue.Queue[bytes]" = queue.Queue()
        self._rx_buf = b""
        self._recv_window = INITIAL_WINDOW  # what we granted the peer
        self._send_window = INITIAL_WINDOW  # what the peer granted us
        self._pending_credit = 0  # consumed bytes not yet re-credited
        self._window_cv = threading.Condition()
        self.closed_local = False
        self.closed_remote = False

    # ---------------------------------------------------------------- api

    def send(self, data: bytes) -> None:
        if self.closed_local:
            raise YamuxError("stream closed")
        view = memoryview(data)
        while view:
            with self._window_cv:
                while self._send_window == 0 and not self.closed_remote:
                    self._window_cv.wait(timeout=5.0)
                if self.closed_remote:
                    raise YamuxError("peer closed the stream")
                n = min(len(view), self._send_window)
                self._send_window -= n
            self.session._send_frame(TYPE_DATA, 0, self.stream_id,
                                     bytes(view[:n]))
            view = view[n:]

    def recv(self, n: int, timeout: Optional[float] = 10.0) -> bytes:
        """Up to n bytes; b'' on remote FIN with nothing buffered."""
        if not self._rx_buf:
            if self.closed_remote and self._rx.empty():
                return b""
            try:
                self._rx_buf = self._rx.get(timeout=timeout)
            except queue.Empty:
                if self.closed_remote:
                    return b""
                raise YamuxError("stream recv timeout")
            if self._rx_buf == b"":  # FIN sentinel
                self.closed_remote = True
                return b""
        out, self._rx_buf = self._rx_buf[:n], self._rx_buf[n:]
        # Re-credit the peer for consumed bytes, BATCHED at half a window
        # (hashicorp yamux's delta threshold): per-byte reads (multistream
        # varints) must not emit one encrypted frame per byte, and a
        # blocked sender always unblocks because its window only empties
        # after a full window of bytes was consumed here.  Best effort:
        # bytes already delivered must not be lost to a dead session.
        with self._window_cv:
            self._recv_window += len(out)
            self._pending_credit += len(out)
            credit = 0
            if self._pending_credit >= INITIAL_WINDOW // 2:
                credit, self._pending_credit = self._pending_credit, 0
        if credit:
            try:
                self.session._send_frame(TYPE_WINDOW_UPDATE, 0,
                                         self.stream_id, b"", length=credit)
            except Exception:
                pass
        return out

    def recv_exact(self, n: int, timeout: Optional[float] = 10.0) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.recv(n - len(buf), timeout=timeout)
            if not chunk:
                raise YamuxError("stream closed mid-read")
            buf += chunk
        return buf

    def close(self) -> None:
        if not self.closed_local:
            self.closed_local = True
            self.session._send_frame(TYPE_DATA, FLAG_FIN, self.stream_id, b"")

    # ------------------------------------------------------------ session

    def _on_data(self, data: bytes) -> bool:
        """Queue received bytes; False when the peer overran our window
        (flow-control violation — the caller RSTs the stream)."""
        with self._window_cv:
            if len(data) > self._recv_window:
                return False
            self._recv_window -= len(data)
        self._rx.put(data)
        return True

    def _on_fin(self) -> None:
        self._rx.put(b"")

    def _on_window_update(self, credit: int) -> None:
        with self._window_cv:
            self._send_window += credit
            self._window_cv.notify_all()


class YamuxSession:
    """One multiplexed session over a NoiseConnection (or any object with
    send()/recv_exact()/close())."""

    def __init__(self, conn, *, dialer: bool,
                 on_stream: Optional[Callable[[YamuxStream], None]] = None):
        self.conn = conn
        self.dialer = dialer
        self.on_stream = on_stream
        self._next_id = 1 if dialer else 2
        self.streams: Dict[int, YamuxStream] = {}
        self._accept_q: "queue.Queue[YamuxStream]" = queue.Queue()
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._running = True
        self._ping_seq = 0
        self._pings: Dict[int, threading.Event] = {}
        self._rx_thread = threading.Thread(
            target=self._rx_loop, daemon=True, name="yamux-rx")
        self._rx_thread.start()

    # ------------------------------------------------------------- frames

    def _send_frame(self, ftype: int, flags: int, stream_id: int,
                    payload: bytes, length: Optional[int] = None) -> None:
        if length is None:
            length = len(payload)
        header = HEADER.pack(0, ftype, flags, stream_id, length)
        with self._send_lock:
            self.conn.send(header + payload)

    # ---------------------------------------------------------------- api

    def open_stream(self) -> YamuxStream:
        with self._lock:
            sid = self._next_id
            self._next_id += 2
            stream = YamuxStream(self, sid)
            self.streams[sid] = stream
        self._send_frame(TYPE_WINDOW_UPDATE, FLAG_SYN, sid, b"", length=0)
        return stream

    def accept_stream(self, timeout: float = 10.0) -> YamuxStream:
        try:
            return self._accept_q.get(timeout=timeout)
        except queue.Empty:
            raise YamuxError("no inbound stream")

    def ping(self, timeout: float = 5.0) -> bool:
        with self._lock:
            self._ping_seq += 1
            opaque = self._ping_seq
            ev = self._pings[opaque] = threading.Event()
        try:
            self._send_frame(TYPE_PING, FLAG_SYN, 0, b"", length=opaque)
            return ev.wait(timeout)
        finally:
            with self._lock:
                self._pings.pop(opaque, None)

    def close(self) -> None:
        self._running = False
        try:
            self._send_frame(TYPE_GOAWAY, 0, 0, b"", length=0)
        except Exception:
            pass
        self.conn.close()

    # ------------------------------------------------------------ receive

    def _stream_for(self, sid: int, flags: int) -> Optional[YamuxStream]:
        created = None
        with self._lock:
            stream = self.streams.get(sid)
            if stream is None and flags & FLAG_SYN:
                stream = created = YamuxStream(self, sid)
                self.streams[sid] = stream
        if created is not None:
            # ACK + hand-off OUTSIDE the session lock: the callback may
            # call back into the session (open a reply stream), and the
            # ACK send can block on TCP backpressure — neither may wedge
            # the rx thread against _lock.
            self._send_frame(TYPE_WINDOW_UPDATE, FLAG_ACK, sid, b"",
                             length=0)
            if self.on_stream is not None:
                self.on_stream(created)  # the callback owns it...
            else:
                self._accept_q.put(created)  # ...or accept_stream() does
        return stream

    def _rx_loop(self) -> None:
        while self._running:
            try:
                header = self.conn.recv_exact(HEADER.size)
            except Exception:
                break
            version, ftype, flags, sid, length = HEADER.unpack(header)
            if version != 0:
                break
            if ftype == TYPE_DATA:
                payload = (self.conn.recv_exact(length) if length else b"")
                stream = self._stream_for(sid, flags)
                if stream is None:
                    continue
                if payload and not stream._on_data(payload):
                    # Flow-control violation: kill the stream, not the node.
                    self._send_frame(TYPE_DATA, FLAG_RST, sid, b"")
                    stream.closed_remote = True
                    stream._on_fin()
                    continue
                if flags & FLAG_FIN:
                    stream._on_fin()
                if flags & FLAG_RST:
                    stream.closed_remote = True
                    stream._on_fin()
            elif ftype == TYPE_WINDOW_UPDATE:
                stream = self._stream_for(sid, flags)
                if stream is not None and length:
                    stream._on_window_update(length)
                if stream is not None and flags & FLAG_FIN:
                    stream._on_fin()
            elif ftype == TYPE_PING:
                if flags & FLAG_SYN:
                    self._send_frame(TYPE_PING, FLAG_ACK, 0, b"", length=length)
                elif flags & FLAG_ACK:
                    # the opaque value pairs the ACK with ITS ping — a
                    # stale ACK must not satisfy a later probe
                    with self._lock:
                        ev = self._pings.get(length)
                    if ev is not None:
                        ev.set()
            elif ftype == TYPE_GOAWAY:
                break
        self._running = False
        # wake every blocked reader/writer
        with self._lock:
            for stream in self.streams.values():
                stream.closed_remote = True
                stream._on_fin()
                with stream._window_cv:
                    stream._window_cv.notify_all()
