"""RFC 7748 curve25519 Diffie-Hellman (X25519), pure Python.

Handshake-scale only (two scalar mults per connection).  Pinned to the
RFC's published test vectors in ``tests/test_noise_yamux.py``."""

from __future__ import annotations

import secrets

P = 2**255 - 19
A24 = 121665  # (486662 - 2) / 4


def _decode_u(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("u-coordinate must be 32 bytes")
    u = bytearray(data)
    u[31] &= 0x7F  # mask the unused high bit
    return int.from_bytes(u, "little")


def _decode_scalar(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("scalar must be 32 bytes")
    k = bytearray(data)
    k[0] &= 248
    k[31] &= 127
    k[31] |= 64
    return int.from_bytes(k, "little")


def x25519(scalar: bytes, u_bytes: bytes) -> bytes:
    """The X25519 function: Montgomery ladder, constant structure."""
    k = _decode_scalar(scalar)
    u = _decode_u(u_bytes) % P

    x1 = u
    x2, z2 = 1, 0
    x3, z3 = u, 1
    swap = 0
    for t in range(254, -1, -1):
        k_t = (k >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t

        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = x1 * (z3 * z3 % P) % P
        x2 = aa * bb % P
        z2 = e * (aa + A24 * e) % P

    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, P - 2, P) % P
    return out.to_bytes(32, "little")


BASE_POINT = (9).to_bytes(32, "little")


def keypair(priv: bytes = None):
    """(private, public) X25519 key pair."""
    if priv is None:
        priv = secrets.token_bytes(32)
    return priv, x25519(priv, BASE_POINT)
