"""Noise protocol framework core: Noise_XX_25519_ChaChaPoly_SHA256.

The exact pattern libp2p-noise mandates (and the reference's transport
uses).  Implements the framework's CipherState / SymmetricState /
HandshakeState objects (Noise spec rev 34) for the XX pattern:

    XX:
      -> e
      <- e, ee, s, es
      -> s, se

Both parties transmit their STATIC Noise key encrypted (identity-hiding),
and the final split() yields one CipherState per direction."""

from __future__ import annotations

import hashlib
import hmac
from typing import List, Optional, Tuple

from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from . import x25519

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
HASHLEN = 32
DHLEN = 32


class NoiseError(Exception):
    pass


def _hmac_sha256(key: bytes, data: bytes) -> bytes:
    return hmac.new(key, data, hashlib.sha256).digest()


def _hkdf(chaining_key: bytes, ikm: bytes, n: int) -> List[bytes]:
    """Noise-spec HKDF: n in {2, 3}."""
    temp = _hmac_sha256(chaining_key, ikm)
    out1 = _hmac_sha256(temp, b"\x01")
    out2 = _hmac_sha256(temp, out1 + b"\x02")
    if n == 2:
        return [out1, out2]
    out3 = _hmac_sha256(temp, out2 + b"\x03")
    return [out1, out2, out3]


class CipherState:
    def __init__(self) -> None:
        self.k: Optional[bytes] = None
        self.n = 0

    def initialize_key(self, key: Optional[bytes]) -> None:
        self.k = key
        self.n = 0

    def has_key(self) -> bool:
        return self.k is not None

    def _nonce(self) -> bytes:
        # ChaChaPoly nonce: 4 zero bytes || little-endian u64 counter
        return b"\x00" * 4 + self.n.to_bytes(8, "little")

    def encrypt_with_ad(self, ad: bytes, plaintext: bytes) -> bytes:
        if self.k is None:
            return plaintext
        out = ChaCha20Poly1305(self.k).encrypt(self._nonce(), plaintext, ad)
        self.n += 1
        return out

    def decrypt_with_ad(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self.k is None:
            return ciphertext
        try:
            out = ChaCha20Poly1305(self.k).decrypt(self._nonce(), ciphertext, ad)
        except Exception as e:
            raise NoiseError(f"AEAD decryption failed: {e}") from e
        self.n += 1
        return out


class SymmetricState:
    def __init__(self) -> None:
        if len(PROTOCOL_NAME) <= HASHLEN:
            self.h = PROTOCOL_NAME.ljust(HASHLEN, b"\x00")
        else:
            self.h = hashlib.sha256(PROTOCOL_NAME).digest()
        self.ck = self.h
        self.cipher = CipherState()

    def mix_key(self, ikm: bytes) -> None:
        self.ck, temp_k = _hkdf(self.ck, ikm, 2)
        self.cipher.initialize_key(temp_k)

    def mix_hash(self, data: bytes) -> None:
        self.h = hashlib.sha256(self.h + data).digest()

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cipher.encrypt_with_ad(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cipher.decrypt_with_ad(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> Tuple[CipherState, CipherState]:
        k1, k2 = _hkdf(self.ck, b"", 2)
        c1, c2 = CipherState(), CipherState()
        c1.initialize_key(k1)
        c2.initialize_key(k2)
        return c1, c2


class HandshakeState:
    """The XX pattern only — exactly what libp2p-noise speaks."""

    def __init__(self, initiator: bool, s_priv: Optional[bytes] = None,
                 prologue: bytes = b"") -> None:
        self.initiator = initiator
        self.ss = SymmetricState()
        self.ss.mix_hash(prologue)
        self.s_priv, self.s_pub = x25519.keypair(s_priv)
        self.e_priv: Optional[bytes] = None
        self.e_pub: Optional[bytes] = None
        self.rs: Optional[bytes] = None  # remote static
        self.re: Optional[bytes] = None  # remote ephemeral
        self.message_index = 0

    # -- message 1: -> e --------------------------------------------------

    def write_message_1(self, payload: bytes = b"") -> bytes:
        assert self.initiator and self.message_index == 0
        self.e_priv, self.e_pub = x25519.keypair()
        self.ss.mix_hash(self.e_pub)
        out = self.e_pub + self.ss.encrypt_and_hash(payload)
        self.message_index = 1
        return out

    def read_message_1(self, message: bytes) -> bytes:
        assert not self.initiator and self.message_index == 0
        self.re = message[:DHLEN]
        self.ss.mix_hash(self.re)
        payload = self.ss.decrypt_and_hash(message[DHLEN:])
        self.message_index = 1
        return payload

    # -- message 2: <- e, ee, s, es ---------------------------------------

    def write_message_2(self, payload: bytes = b"") -> bytes:
        assert not self.initiator and self.message_index == 1
        self.e_priv, self.e_pub = x25519.keypair()
        self.ss.mix_hash(self.e_pub)
        out = self.e_pub
        self.ss.mix_key(x25519.x25519(self.e_priv, self.re))          # ee
        out += self.ss.encrypt_and_hash(self.s_pub)                   # s
        self.ss.mix_key(x25519.x25519(self.s_priv, self.re))          # es
        out += self.ss.encrypt_and_hash(payload)
        self.message_index = 2
        return out

    def read_message_2(self, message: bytes) -> bytes:
        assert self.initiator and self.message_index == 1
        self.re = message[:DHLEN]
        self.ss.mix_hash(self.re)
        self.ss.mix_key(x25519.x25519(self.e_priv, self.re))          # ee
        enc_s = message[DHLEN:DHLEN + DHLEN + 16]
        self.rs = self.ss.decrypt_and_hash(enc_s)                     # s
        self.ss.mix_key(x25519.x25519(self.e_priv, self.rs))          # es
        payload = self.ss.decrypt_and_hash(message[DHLEN + DHLEN + 16:])
        self.message_index = 2
        return payload

    # -- message 3: -> s, se ----------------------------------------------

    def write_message_3(self, payload: bytes = b"") -> Tuple[bytes, CipherState, CipherState]:
        assert self.initiator and self.message_index == 2
        out = self.ss.encrypt_and_hash(self.s_pub)                    # s
        self.ss.mix_key(x25519.x25519(self.s_priv, self.re))          # se
        out += self.ss.encrypt_and_hash(payload)
        send, recv = self.ss.split()  # initiator sends with c1
        return out, send, recv

    def read_message_3(self, message: bytes) -> Tuple[bytes, CipherState, CipherState]:
        assert not self.initiator and self.message_index == 2
        enc_s = message[:DHLEN + 16]
        self.rs = self.ss.decrypt_and_hash(enc_s)                     # s
        self.ss.mix_key(x25519.x25519(self.e_priv, self.rs))          # se
        payload = self.ss.decrypt_and_hash(message[DHLEN + 16:])
        c1, c2 = self.ss.split()
        return payload, c2, c1  # responder sends with c2
