"""multistream-select 1.0 — libp2p's protocol negotiation.

The first bytes on every libp2p connection (and on every new stream)
negotiate what is spoken next: each message is a uvarint length prefix,
the protocol path, and a trailing newline.  The reference's connection
upgrade runs ``/multistream/1.0.0`` then ``/noise`` on the raw TCP
connection, multistream again for ``/yamux/1.0.0`` on the secured one,
and once more per stream for the application protocol (an eth2 RPC
protocol id or gossipsub's ``/meshsub/1.1.0``).

``na\\n`` answers an unsupported proposal; the dialer may then propose an
alternative or give up."""

from __future__ import annotations

from typing import Sequence

from ..snappy_codec import _write_varint as _uvarint  # shared varint encoder

MULTISTREAM_PROTO = "/multistream/1.0.0"
NA = "na"


class MultistreamError(Exception):
    pass


# A dialer proposing more than this many protocols on one negotiation is
# hostile or broken: answer-with-na loops must terminate.
MAX_PROPOSALS = 16


def _encode(msg: str) -> bytes:
    payload = msg.encode() + b"\n"
    return _uvarint(len(payload)) + payload


def _read_uvarint(conn) -> int:
    val = 0
    shift = 0
    while True:
        byte = conn.recv_exact(1)[0]
        val |= (byte & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise MultistreamError("oversized multistream length")
        if not byte & 0x80:
            return val

def _read_message(conn) -> str:
    length = _read_uvarint(conn)
    if length == 0 or length > 1024:
        raise MultistreamError("bad multistream message length")
    payload = conn.recv_exact(length)
    if not payload.endswith(b"\n"):
        raise MultistreamError("multistream message missing newline")
    try:
        return payload[:-1].decode()
    except UnicodeDecodeError as e:
        raise MultistreamError("non-UTF-8 multistream message") from e


def negotiate_outbound(conn, protocols: Sequence[str]) -> str:
    """Dialer side: propose ``protocols`` in order; returns the accepted
    one.  ``conn`` needs send()/recv_exact()."""
    conn.send(_encode(MULTISTREAM_PROTO))
    if _read_message(conn) != MULTISTREAM_PROTO:
        raise MultistreamError("peer does not speak multistream 1.0")
    for proto in protocols:
        conn.send(_encode(proto))
        answer = _read_message(conn)
        if answer == proto:
            return proto
        if answer != NA:
            raise MultistreamError(f"unexpected negotiation answer {answer!r}")
    raise MultistreamError(f"peer rejected all of {list(protocols)}")


def negotiate_inbound(conn, supported: Sequence[str]) -> str:
    """Listener side: echo the header, accept the first supported proposal."""
    if _read_message(conn) != MULTISTREAM_PROTO:
        raise MultistreamError("peer does not speak multistream 1.0")
    conn.send(_encode(MULTISTREAM_PROTO))
    for _ in range(MAX_PROPOSALS):
        proposal = _read_message(conn)
        if proposal in supported:
            conn.send(_encode(proposal))
            return proposal
        conn.send(_encode(NA))
    raise MultistreamError("peer exceeded the proposal budget")


class _SocketAdapter:
    """multistream over a raw socket (pre-noise stage)."""

    def __init__(self, sock) -> None:
        self.sock = sock

    def send(self, data: bytes) -> None:
        self.sock.sendall(data)

    def recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise MultistreamError("connection closed mid-negotiation")
            buf += chunk
        return buf


def upgrade_outbound(sock, identity_priv: int):
    """The full dial-side libp2p ladder: multistream -> /noise -> secure
    handshake -> multistream -> /yamux/1.0.0 -> session.  Returns the
    YamuxSession."""
    from .secure import secure_dial
    from .yamux import YamuxSession

    raw = _SocketAdapter(sock)
    negotiate_outbound(raw, ["/noise"])
    conn = secure_dial(sock, identity_priv)
    negotiate_outbound(conn, ["/yamux/1.0.0"])
    # The yamux rx thread must NEVER run with a socket timeout: a timeout
    # set for the handshake would fire on the first idle gap and kill the
    # session (an in-flight recv also ignores later settimeout calls).
    # Every read before this point ran in the calling thread, bounded.
    sock.settimeout(None)
    return YamuxSession(conn, dialer=True)


def upgrade_inbound(sock, identity_priv: int, on_stream=None):
    """Listener-side ladder; returns the YamuxSession."""
    from .secure import secure_accept
    from .yamux import YamuxSession

    raw = _SocketAdapter(sock)
    negotiate_inbound(raw, ["/noise"])
    conn = secure_accept(sock, identity_priv)
    negotiate_inbound(conn, ["/yamux/1.0.0"])
    sock.settimeout(None)  # see upgrade_outbound: the rx thread starts now
    return YamuxSession(conn, dialer=False, on_stream=on_stream)
