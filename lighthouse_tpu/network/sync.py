"""Sync manager: range sync + parent (single-block) lookups.

Equivalent of the reference's ``network/src/sync/manager.rs`` (doc ``:1-35``)
with ``range_sync/`` (forward sync in epoch batches from a peer ahead of us)
and ``block_lookups/`` (fetch unknown parents by root, import the chain in
order).  Backfill (checkpoint→genesis) arrives with checkpoint sync.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional

from ..chain.beacon_chain import BlockError
from ..logs import get_logger
from ..metrics import SYNC_LOOKUP_ABORTED
from . import rpc as rpc_mod
from .peer_manager import PeerAction

log = get_logger("network.sync")

BATCH_SLOTS = 16  # 2 epochs on the minimal preset (reference: 2-epoch batches)
PARENT_DEPTH_LIMIT = 32  # reference ``block_lookups`` parent chain bound


def _lookup_aborted(reason: str) -> None:
    """One counter for every path that gives up on a lookup before import —
    the churn scenarios' evidence that a dead/lying peer bounded the chase
    instead of stalling it (``sync_lookup_aborted_total{reason}``)."""
    SYNC_LOOKUP_ABORTED.inc(reason=reason)


class SyncState:
    SYNCED = "synced"
    SYNCING = "syncing"


def decode_signed_block(chain, payload: bytes):
    """Decode a SignedBeaconBlock of unknown fork from SSZ bytes.

    The container is variable-size: bytes 0..4 are the offset of ``message``
    (past the 96-byte signature); the message's first field is the slot,
    which selects the fork's container class."""
    (message_off,) = struct.unpack_from("<I", payload, 0)
    slot = struct.unpack_from("<Q", payload, message_off)[0]
    fork = chain.spec.fork_name_at_slot(slot)
    return chain.types.signed_block[fork].from_ssz_bytes(payload)


class SyncManager:
    def __init__(self, *, chain, service, router):
        self.chain = chain
        self.service = service
        self.router = router
        router.sync = self
        self.state = SyncState.SYNCED
        self._lock = threading.Lock()
        self._sync_thread: Optional[threading.Thread] = None
        self._lookups_in_flight: set = set()

    def busy(self) -> bool:
        """True while range sync or any single-block lookup is in flight —
        the simulator's quiescence check (``Simulator.settle``) must not
        call a fabric settled while a background chase is still importing
        blocks."""
        with self._lock:
            if self._lookups_in_flight:
                return True
            return (self._sync_thread is not None
                    and self._sync_thread.is_alive())

    # ------------------------------------------------------------- status

    def on_peer_status(self, peer: str, status: rpc_mod.Status) -> None:
        """A peer ahead of our head triggers range sync
        (reference ``manager.rs`` ``add_peer`` → RangeSync)."""
        local_head_slot = self.chain._blocks_slot(self.chain.head_root)
        if status.head_slot <= local_head_slot:
            return
        if status.head_root and self.chain.fork_choice.contains_block(status.head_root):
            return
        with self._lock:
            if self._sync_thread is not None and self._sync_thread.is_alive():
                return
            self.state = SyncState.SYNCING
            self._sync_thread = threading.Thread(
                target=self._range_sync, args=(peer, status), daemon=True,
                name=f"range-sync-{self.service.peer_id}",
            )
            self._sync_thread.start()

    # --------------------------------------------------------- range sync

    def _decode_block_chunk(self, payload: bytes):
        return decode_signed_block(self.chain, payload)

    def _range_sync(self, peer: str, status: rpc_mod.Status) -> None:
        chain = self.chain
        log.info("range sync started", peer=peer,
                 from_slot=chain._blocks_slot(chain.head_root),
                 target_slot=int(status.head_slot))
        try:
            prev_start = -1
            while True:
                start = chain._blocks_slot(chain.head_root) + 1
                if start > status.head_slot:
                    break
                log.debug("range sync batch", peer=peer, start_slot=start,
                          target_slot=int(status.head_slot))
                if start == prev_start:
                    # No head progress over a full batch (e.g. the peer keeps
                    # serving a fork our fork choice doesn't prefer): stop
                    # rather than livelock re-requesting the same span.
                    break
                prev_start = start
                try:
                    chunks = self.service.request(
                        peer,
                        rpc_mod.BLOCKS_BY_RANGE,
                        rpc_mod.BlocksByRangeRequest(start_slot=start, count=BATCH_SLOTS),
                        timeout=10.0,
                    )
                except rpc_mod.RpcSelfLimited:
                    break  # OUR outbound throttle: retry next round, no blame
                except rpc_mod.RpcError:
                    self.service.peer_manager.report(peer, PeerAction.MID_TOLERANCE, "sync rpc failed")
                    break
                if not chunks:
                    break  # peer had nothing for the span: caught up or lying
                for result, payload, _ctx in chunks:
                    if result != rpc_mod.SUCCESS:
                        continue
                    try:
                        signed = self._decode_block_chunk(payload)
                        self._import_with_blobs(peer, signed)
                        self.router._publish_light_client_updates()
                    except BlockError as e:
                        # Narrower than _TRANSIENT_BLOCK_ERRORS on purpose:
                        # the bare "blob" fragment there would also excuse a
                        # peer that fails to serve sidecars for its OWN
                        # blocks — that stays penalized.  Self-limited blob
                        # fetches match "pending availability".
                        if any(t in str(e) for t in
                               ("future slot", "pending availability",
                                "unknown parent")):
                            return  # not the peer's fault (incl. OUR throttle)
                        self.service.peer_manager.report(
                            peer, PeerAction.LOW_TOLERANCE, f"bad sync block: {e}"
                        )
                        return
        finally:
            self.state = SyncState.SYNCED
            log.info("range sync finished", peer=peer,
                     head_slot=chain._blocks_slot(chain.head_root))

    def _import_with_blobs(self, peer: str, signed) -> None:
        """Import a synced block, fetching its blob sidecars over
        BlobsByRoot first when the body carries commitments (reference
        ``network_context.rs`` block+blob coupling)."""
        chain = self.chain
        commitments = getattr(signed.message.body, "blob_kzg_commitments", None)
        if not commitments:
            chain.process_block(signed)
            return
        block_root = signed.message.hash_tree_root()
        ids = [(block_root, i) for i in range(len(commitments))]
        try:
            chunks = self.service.request(
                peer, rpc_mod.BLOBS_BY_ROOT,
                rpc_mod.BlobsByRootRequest(ids=ids), timeout=10.0,
            )
        except rpc_mod.RpcSelfLimited:
            raise BlockError("pending availability: blob fetch self-limited")
        except rpc_mod.RpcError as e:
            raise BlockError(f"peer did not serve blobs: {e}") from e
        sidecars = []
        for result, payload, _ctx in chunks:
            if result != rpc_mod.SUCCESS:
                continue
            try:
                sidecars.append(chain.types.BlobSidecar.from_ssz_bytes(payload))
            except Exception as e:
                raise BlockError(f"undecodable blob sidecar: {e}") from e
        chain.process_block_with_blobs(signed, sidecars)

    # ------------------------------------------------- single-block lookup

    # BlockError fragments that are TRANSIENT or PEER-ATTRIBUTABLE: the
    # block may import fine later (clock skew, ancestry still fetching) or
    # a different peer may serve good sidecars ("blob" covers missing /
    # undecodable / unverifiable sidecars — blob faults belong to the
    # serving peer, not the root).  None of these may poison the root as
    # pre-finalization.
    _TRANSIENT_BLOCK_ERRORS = ("future slot", "pending availability",
                               "unknown parent", "blob")
    MAX_CONCURRENT_LOOKUPS = 8

    def lookup_block(self, block_root: bytes, peer: str) -> None:
        """Fetch one unknown block by root (attestation-triggered single
        block lookup, reference ``block_lookups/single_block_lookup.rs``) and
        import it.  Only a root-verified block that PERMANENTLY fails import
        is remembered as rejected — a transient failure or a peer serving
        the wrong bytes must not let an attacker poison an honest root."""
        chain = self.chain
        block_root = bytes(block_root)
        try:
            if chain.fork_choice.contains_block(block_root):
                return
            try:
                chunks = self.service.request(
                    peer,
                    rpc_mod.BLOCKS_BY_ROOT,
                    rpc_mod.BlocksByRootRequest(roots=[block_root]),
                    timeout=5.0,
                )
            except rpc_mod.RpcError:
                _lookup_aborted("rpc_error")
                return
            got = [c for c in chunks if c[0] == rpc_mod.SUCCESS]
            if not got:
                _lookup_aborted("not_found")
                return  # peer doesn't have it either: learn nothing
            try:
                signed = self._decode_block_chunk(got[0][1])
            except Exception:
                self.service.peer_manager.report(
                    peer, PeerAction.LOW_TOLERANCE, "undecodable lookup block")
                _lookup_aborted("undecodable")
                return
            if signed.message.hash_tree_root() != block_root:
                # The response is NOT the requested block: penalize the
                # server; the root itself has proven nothing.
                self.service.peer_manager.report(
                    peer, PeerAction.LOW_TOLERANCE,
                    "lookup block root mismatch")
                _lookup_aborted("root_mismatch")
                return
            try:
                self._import_with_blobs(peer, signed)
                log.debug("single-block lookup imported",
                          root=block_root.hex()[:16], peer=peer)
            except BlockError as e:
                msg = str(e)
                if "unknown parent" in msg:
                    try:
                        self.on_unknown_parent(signed, peer)
                    except Exception:
                        pass
                    if chain.fork_choice.contains_block(block_root):
                        return
                if any(t in msg for t in self._TRANSIENT_BLOCK_ERRORS):
                    return  # may import later: learn nothing yet
                # Root-verified block, permanent rejection: remember
                # (reference pre_finalization_block_rejected).
                chain.pre_finalization_cache.block_rejected(block_root)
                log.debug("single-block lookup rejected",
                          root=block_root.hex()[:16], reason=msg[:80])
        finally:
            with self._lock:
                self._lookups_in_flight.discard(block_root)

    def lookup_block_async(self, block_root: bytes, peer: str) -> None:
        """Bounded, de-duplicated spawn: one thread per distinct root, at
        most MAX_CONCURRENT_LOOKUPS in flight (gossip flooding random roots
        must not exhaust threads — the DoS the pre-finalization cache
        exists to blunt)."""
        block_root = bytes(block_root)
        with self._lock:
            if block_root in self._lookups_in_flight:
                return
            if len(self._lookups_in_flight) >= self.MAX_CONCURRENT_LOOKUPS:
                return
            self._lookups_in_flight.add(block_root)
        threading.Thread(
            target=self.lookup_block, args=(block_root, peer),
            daemon=True, name="single-block-lookup",
        ).start()

    # ------------------------------------------------------ parent lookup

    def on_unknown_parent(self, orphan_block, peer: str,
                          depth_limit: int = PARENT_DEPTH_LIMIT) -> None:
        """Fetch the missing ancestry by root and import in order
        (reference ``block_lookups/`` parent lookups).  The chase is bounded
        by ``depth_limit``: a peer feeding an endless orphan chain (or a
        reorg deeper than the cap) aborts with a penalty and a
        ``sync_lookup_aborted_total{reason="depth_limit"}`` tick instead of
        chasing forever."""
        chain = self.chain
        ancestry: List[object] = [orphan_block]
        parent_root = bytes(orphan_block.message.parent_root)
        for _ in range(depth_limit):
            if chain.fork_choice.contains_block(parent_root):
                break
            try:
                chunks = self.service.request(
                    peer,
                    rpc_mod.BLOCKS_BY_ROOT,
                    rpc_mod.BlocksByRootRequest(roots=[parent_root]),
                    timeout=5.0,
                )
            except rpc_mod.RpcError:
                _lookup_aborted("rpc_error")
                return
            got = [c for c in chunks if c[0] == rpc_mod.SUCCESS]
            if not got:
                self.service.peer_manager.report(
                    peer, PeerAction.MID_TOLERANCE, "parent lookup failed"
                )
                _lookup_aborted("not_found")
                return
            try:
                parent = self._decode_block_chunk(got[0][1])
            except Exception:
                self.service.peer_manager.report(
                    peer, PeerAction.LOW_TOLERANCE, "undecodable parent block")
                _lookup_aborted("undecodable")
                return
            ancestry.append(parent)
            parent_root = bytes(parent.message.parent_root)
        else:
            self.service.peer_manager.report(peer, PeerAction.LOW_TOLERANCE, "parent chain too deep")
            _lookup_aborted("depth_limit")
            log.warning("parent chase aborted at depth limit",
                        peer=peer, depth=depth_limit,
                        orphan=bytes(orphan_block.message.hash_tree_root()).hex()[:16])
            return
        for block in reversed(ancestry):
            try:
                chain.process_block(block)
                self.router._publish_light_client_updates()
            except BlockError:
                return
