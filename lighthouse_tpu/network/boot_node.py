"""Boot node: a standalone peer-introduction service.

Equivalent of the reference's ``boot_node/`` binary (609 LoC — a discv5-only
process new nodes contact first).  In this stack's transport idiom the
bootstrap role is peer exchange over TCP: the boot node accepts connections,
remembers every dialer's listen address, and answers ``peer_exchange/1`` with
the addresses it knows — it never gossips, serves blocks, or holds chain
state.

Node-side, ``discover_peers`` (on ``LocalNode``) walks connected peers'
exchange answers and dials unknown addresses — the FINDNODE round of discv5.
"""

from __future__ import annotations

import threading
from typing import Optional

from . import rpc as rpc_mod
from .service import NetworkService
from .tcp_transport import TcpEndpoint


class BootNode:
    def __init__(self, *, peer_id: str = "boot", host: str = "127.0.0.1",
                 port: int = 0):
        self.endpoint = TcpEndpoint(peer_id, host=host, port=port)
        self.service = NetworkService(self.endpoint)
        self.service.on_rpc_request = self._on_rpc

    @property
    def listen_addr(self):
        return self.endpoint.listen_addr

    def _on_rpc(self, protocol: str, request, sender: str):
        if protocol == rpc_mod.PING:
            return [rpc_mod.encode_response_chunk(
                rpc_mod.SUCCESS, rpc_mod.Ping(0).to_bytes()
            )]
        if protocol == rpc_mod.PEER_EXCHANGE:
            return [rpc_mod.serve_peer_exchange(
                self.endpoint, sender, request.max_peers
            )]
        if protocol == rpc_mod.GOODBYE:
            self.endpoint.disconnect(sender)
            return []
        return [rpc_mod.encode_response_chunk(
            rpc_mod.INVALID_REQUEST, b"boot node serves discovery only"
        )]

    def stop(self) -> None:
        self.service.shutdown()
        self.endpoint.close()


def run_forever(host: str, port: int) -> None:  # pragma: no cover - CLI loop
    import time

    node = BootNode(host=host, port=port)
    print(f"boot node listening on {node.listen_addr[0]}:{node.listen_addr[1]}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
