"""Gossipsub protobuf wire codec (libp2p ``/meshsub/1.1.0``).

Hand-rolled proto2 encode/decode for the gossipsub RPC schema the
reference vendors (`beacon_node/lighthouse_network/gossipsub/src/generated/
rpc.proto`): ``RPC { repeated SubOpts subscriptions = 1; repeated Message
publish = 2; ControlMessage control = 3 }`` with IHAVE/IWANT/GRAFT/PRUNE
control messages (PRUNE carries v1.1 peer-exchange ``PeerInfo`` + backoff
seconds).  Messages follow Eth2's ``StrictNoSign`` policy: only ``data`` and
``topic`` are populated; ``from``/``seqno``/``signature``/``key`` MUST be
absent on the wire and are rejected on receipt (consensus spec p2p:
``message.signature — this field MUST NOT be present``).

This module is pure wire math — no dependency on the transport.  Decode is
tolerant of unknown fields (skipped per wire type) so future protocol
revisions don't break framing, but strict about StrictNoSign and about
truncated/overlong varints.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class PbError(Exception):
    pass


# ------------------------------------------------------------ primitives


def write_uvarint(n: int) -> bytes:
    if n < 0:
        raise PbError("negative varint")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Returns (value, new_pos).  Bounds to 64 bits like protobuf."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise PbError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >> 64:
                raise PbError("varint overflows 64 bits")
            return result, pos
        shift += 7
        if shift >= 70:
            raise PbError("varint too long")


def _utf8(val: bytes) -> str:
    """Topic strings must be valid UTF-8; anything else is a framing
    violation (PbError), not a stray UnicodeDecodeError that would slip
    past the transport's violation handling."""
    try:
        return val.decode()
    except UnicodeDecodeError as e:
        raise PbError(f"invalid utf-8 in string field: {e}") from e


def _key(field_no: int, wire_type: int) -> bytes:
    return write_uvarint((field_no << 3) | wire_type)


def _len_delim(field_no: int, payload: bytes) -> bytes:
    return _key(field_no, 2) + write_uvarint(len(payload)) + payload


def _varint_field(field_no: int, value: int) -> bytes:
    return _key(field_no, 0) + write_uvarint(value)


def _skip(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = read_uvarint(buf, pos)
        return pos
    if wire_type == 1:
        if pos + 8 > len(buf):
            raise PbError("truncated fixed64")
        return pos + 8
    if wire_type == 2:
        n, pos = read_uvarint(buf, pos)
        if pos + n > len(buf):
            raise PbError("truncated length-delimited field")
        return pos + n
    if wire_type == 5:
        if pos + 4 > len(buf):
            raise PbError("truncated fixed32")
        return pos + 4
    raise PbError(f"unsupported wire type {wire_type}")


def _fields(buf: bytes):
    """Iterate (field_no, wire_type, value_or_bytes, next_pos)."""
    pos = 0
    while pos < len(buf):
        key, pos = read_uvarint(buf, pos)
        field_no, wire_type = key >> 3, key & 7
        if field_no == 0:
            raise PbError("field number 0")
        if wire_type == 0:
            val, pos = read_uvarint(buf, pos)
            yield field_no, wire_type, val
        elif wire_type == 2:
            n, pos = read_uvarint(buf, pos)
            if pos + n > len(buf):
                raise PbError("truncated length-delimited field")
            yield field_no, wire_type, buf[pos:pos + n]
            pos += n
        else:
            start = pos
            pos = _skip(buf, pos, wire_type)
            yield field_no, wire_type, buf[start:pos]


# -------------------------------------------------------------- messages


@dataclass
class SubOpts:
    subscribe: bool = True
    topic_id: str = ""

    def encode(self) -> bytes:
        out = _varint_field(1, 1 if self.subscribe else 0)
        out += _len_delim(2, self.topic_id.encode())
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "SubOpts":
        sub = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 0:
                sub.subscribe = bool(val)
            elif fno == 2 and wt == 2:
                sub.topic_id = _utf8(val)
        return sub


@dataclass
class Message:
    """StrictNoSign message: topic (field 4, required) + data (field 2)."""

    data: bytes = b""
    topic: str = ""

    def encode(self) -> bytes:
        return _len_delim(2, self.data) + _len_delim(4, self.topic.encode())

    @classmethod
    def decode(cls, buf: bytes) -> "Message":
        msg = cls()
        saw_topic = False
        for fno, wt, val in _fields(buf):
            if fno == 2 and wt == 2:
                msg.data = val
            elif fno == 4 and wt == 2:
                msg.topic = _utf8(val)
                saw_topic = True
            elif fno in (1, 3, 5, 6):
                # StrictNoSign: from/seqno/signature/key MUST NOT be present
                raise PbError(f"StrictNoSign violation: field {fno} present")
        if not saw_topic:
            raise PbError("Message missing required topic")
        return msg


@dataclass
class ControlIHave:
    topic_id: str = ""
    message_ids: List[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        out = _len_delim(1, self.topic_id.encode())
        for mid in self.message_ids:
            out += _len_delim(2, mid)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "ControlIHave":
        c = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                c.topic_id = _utf8(val)
            elif fno == 2 and wt == 2:
                c.message_ids.append(val)
        return c


@dataclass
class ControlIWant:
    message_ids: List[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        return b"".join(_len_delim(1, mid) for mid in self.message_ids)

    @classmethod
    def decode(cls, buf: bytes) -> "ControlIWant":
        c = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                c.message_ids.append(val)
        return c


@dataclass
class ControlGraft:
    topic_id: str = ""

    def encode(self) -> bytes:
        return _len_delim(1, self.topic_id.encode())

    @classmethod
    def decode(cls, buf: bytes) -> "ControlGraft":
        c = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                c.topic_id = _utf8(val)
        return c


@dataclass
class PeerInfo:
    """v1.1 peer exchange: an ENR-capable peer id (we carry the dialable
    ``host:port|peer_id`` record the PRUNEd peer can reconnect through)."""

    peer_id: bytes = b""
    signed_peer_record: bytes = b""

    def encode(self) -> bytes:
        out = b""
        if self.peer_id:
            out += _len_delim(1, self.peer_id)
        if self.signed_peer_record:
            out += _len_delim(2, self.signed_peer_record)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "PeerInfo":
        p = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                p.peer_id = val
            elif fno == 2 and wt == 2:
                p.signed_peer_record = val
        return p


@dataclass
class ControlPrune:
    topic_id: str = ""
    peers: List[PeerInfo] = field(default_factory=list)
    backoff: Optional[int] = None  # seconds

    def encode(self) -> bytes:
        out = _len_delim(1, self.topic_id.encode())
        for p in self.peers:
            out += _len_delim(2, p.encode())
        if self.backoff is not None:
            out += _varint_field(3, self.backoff)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "ControlPrune":
        c = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                c.topic_id = _utf8(val)
            elif fno == 2 and wt == 2:
                c.peers.append(PeerInfo.decode(val))
            elif fno == 3 and wt == 0:
                c.backoff = val
        return c


@dataclass
class ControlMessage:
    ihave: List[ControlIHave] = field(default_factory=list)
    iwant: List[ControlIWant] = field(default_factory=list)
    graft: List[ControlGraft] = field(default_factory=list)
    prune: List[ControlPrune] = field(default_factory=list)

    def __bool__(self) -> bool:
        return bool(self.ihave or self.iwant or self.graft or self.prune)

    def encode(self) -> bytes:
        out = b""
        for c in self.ihave:
            out += _len_delim(1, c.encode())
        for c in self.iwant:
            out += _len_delim(2, c.encode())
        for c in self.graft:
            out += _len_delim(3, c.encode())
        for c in self.prune:
            out += _len_delim(4, c.encode())
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "ControlMessage":
        c = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                c.ihave.append(ControlIHave.decode(val))
            elif fno == 2 and wt == 2:
                c.iwant.append(ControlIWant.decode(val))
            elif fno == 3 and wt == 2:
                c.graft.append(ControlGraft.decode(val))
            elif fno == 4 and wt == 2:
                c.prune.append(ControlPrune.decode(val))
        return c


@dataclass
class RPC:
    subscriptions: List[SubOpts] = field(default_factory=list)
    publish: List[Message] = field(default_factory=list)
    control: Optional[ControlMessage] = None

    def encode(self) -> bytes:
        out = b""
        for s in self.subscriptions:
            out += _len_delim(1, s.encode())
        for m in self.publish:
            out += _len_delim(2, m.encode())
        if self.control is not None and self.control:
            out += _len_delim(3, self.control.encode())
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "RPC":
        rpc = cls()
        for fno, wt, val in _fields(buf):
            if fno == 1 and wt == 2:
                rpc.subscriptions.append(SubOpts.decode(val))
            elif fno == 2 and wt == 2:
                rpc.publish.append(Message.decode(val))
            elif fno == 3 and wt == 2:
                rpc.control = ControlMessage.decode(val)
        return rpc


# ------------------------------------------------------------- framing

MAX_RPC_SIZE = 10 * 1024 * 1024  # reference gossipsub max_transmit_size class


def encode_frame(rpc: RPC) -> bytes:
    """One length-prefixed RPC as it appears on a meshsub stream."""
    payload = rpc.encode()
    if len(payload) > MAX_RPC_SIZE:
        raise PbError("RPC exceeds max transmit size")
    return write_uvarint(len(payload)) + payload


def read_frame(recv_exact) -> RPC:
    """Read one varint-delimited RPC via a ``recv_exact(n) -> bytes``
    callable (a yamux stream).  Raises PbError on framing violations."""
    # uvarint arrives byte-at-a-time: up to 5 bytes covers MAX_RPC_SIZE
    length = 0
    shift = 0
    while True:
        chunk = recv_exact(1)
        if len(chunk) != 1:
            raise PbError("stream closed mid-length")
        b = chunk[0]
        length |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift >= 35:
            raise PbError("frame length varint too long")
    if length > MAX_RPC_SIZE:
        raise PbError("frame exceeds max transmit size")
    payload = recv_exact(length)
    if len(payload) != length:
        raise PbError("stream closed mid-frame")
    return RPC.decode(payload)
