"""Req/resp RPC protocol: typed requests/responses + ssz_snappy codec.

Equivalent of the reference's ``lighthouse_network/src/rpc/protocol.rs``
(Status/Goodbye/BlocksByRange/BlocksByRoot/BlobsByRange/BlobsByRoot/Ping/
Metadata protocol ids) and ``rpc/codec/ssz_snappy.rs`` (length-prefixed
snappy-framed SSZ chunks with a result byte and per-fork context bytes on
block responses).

Wire shape per response chunk:
    [u8 result] [varint ssz_length] [4-byte context (forked types only)]
    [snappy-framed SSZ payload]
result 0 = success, 1 = invalid request, 2 = server error, 3 = resource
unavailable (reference ``RPCResponseErrorCode``).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple

from . import snappy_codec
from .snappy_codec import _read_varint, _write_varint  # shared varint

PROTOCOL_PREFIX = "/eth2/beacon_chain/req"
ENCODING_SUFFIX = "ssz_snappy"


def _pid(name_version: str) -> str:
    """Full spec protocol id (reference ``protocol.rs`` ``ProtocolId``)."""
    return f"{PROTOCOL_PREFIX}/{name_version}/{ENCODING_SUFFIX}"


STATUS = _pid("status/1")
GOODBYE = _pid("goodbye/1")
BLOCKS_BY_RANGE = _pid("beacon_blocks_by_range/2")
BLOCKS_BY_ROOT = _pid("beacon_blocks_by_root/2")
BLOBS_BY_RANGE = _pid("blob_sidecars_by_range/1")
BLOBS_BY_ROOT = _pid("blob_sidecars_by_root/1")
PING = _pid("ping/1")
METADATA = _pid("metadata/2")
# Not a consensus-spec protocol: this transport's discovery analog (the role
# discv5 plays for the reference) — peers exchange known listen addresses.
PEER_EXCHANGE = _pid("peer_exchange/1")
# light-client req/resp (reference rpc/protocol.rs SupportedProtocol::
# LightClient{Bootstrap,OptimisticUpdate,FinalityUpdate}V1)
LIGHT_CLIENT_BOOTSTRAP = _pid("light_client_bootstrap/1")
LIGHT_CLIENT_OPTIMISTIC_UPDATE = _pid("light_client_optimistic_update/1")
LIGHT_CLIENT_FINALITY_UPDATE = _pid("light_client_finality_update/1")

# Protocols whose SUCCESS chunks carry 4 context bytes (fork digest of the
# payload's era).  ONE owner: the router encodes and the service decodes
# from this same set — editing only one side silently corrupts decoding.
CONTEXT_PROTOCOLS = frozenset({
    BLOCKS_BY_RANGE, BLOCKS_BY_ROOT, BLOBS_BY_RANGE, BLOBS_BY_ROOT,
    LIGHT_CLIENT_BOOTSTRAP, LIGHT_CLIENT_OPTIMISTIC_UPDATE,
    LIGHT_CLIENT_FINALITY_UPDATE,
})

SUCCESS = 0
INVALID_REQUEST = 1
SERVER_ERROR = 2
RESOURCE_UNAVAILABLE = 3

MAX_REQUEST_BLOCKS = 1024


class RpcError(ValueError):
    pass


class RpcSelfLimited(RpcError):
    """Our OWN outbound throttle refused/timed out the request — the peer
    did nothing wrong and must not be penalized for it."""


@dataclass
class Status:
    """Reference ``StatusMessage`` — the handshake that drives sync."""

    fork_digest: bytes
    finalized_root: bytes
    finalized_epoch: int
    head_root: bytes
    head_slot: int

    def to_bytes(self) -> bytes:
        return (
            self.fork_digest
            + self.finalized_root
            + struct.pack("<Q", self.finalized_epoch)
            + self.head_root
            + struct.pack("<Q", self.head_slot)
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "Status":
        if len(data) != 84:
            raise RpcError(f"status must be 84 bytes, got {len(data)}")
        return cls(
            fork_digest=data[0:4],
            finalized_root=data[4:36],
            finalized_epoch=struct.unpack_from("<Q", data, 36)[0],
            head_root=data[44:76],
            head_slot=struct.unpack_from("<Q", data, 76)[0],
        )


@dataclass
class Goodbye:
    reason: int  # 1 shutdown, 2 irrelevant network, 3 fault/error

    def to_bytes(self) -> bytes:
        return struct.pack("<Q", self.reason)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Goodbye":
        return cls(struct.unpack("<Q", data)[0])


@dataclass
class Ping:
    seq_number: int

    def to_bytes(self) -> bytes:
        return struct.pack("<Q", self.seq_number)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Ping":
        return cls(struct.unpack("<Q", data)[0])


@dataclass
class MetaData:
    seq_number: int
    attnets: int  # 64-bit bitfield
    syncnets: int  # 4-bit bitfield (1 byte on the wire)

    def to_bytes(self) -> bytes:
        return struct.pack("<Q", self.seq_number) + struct.pack("<Q", self.attnets) + bytes(
            [self.syncnets]
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "MetaData":
        return cls(
            struct.unpack_from("<Q", data, 0)[0],
            struct.unpack_from("<Q", data, 8)[0],
            data[16],
        )


@dataclass
class BlocksByRangeRequest:
    start_slot: int
    count: int

    def to_bytes(self) -> bytes:
        # v2 drops `step`; encoded as step=1 for v1 compat in the reference
        return struct.pack("<QQQ", self.start_slot, self.count, 1)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlocksByRangeRequest":
        start, count, _step = struct.unpack("<QQQ", data)
        return cls(start, count)


@dataclass
class BlocksByRootRequest:
    roots: List[bytes]

    def to_bytes(self) -> bytes:
        return b"".join(self.roots)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlocksByRootRequest":
        if len(data) % 32:
            raise RpcError("roots payload not a multiple of 32")
        return cls([data[i : i + 32] for i in range(0, len(data), 32)])


@dataclass
class BlobsByRangeRequest:
    start_slot: int
    count: int

    def to_bytes(self) -> bytes:
        return struct.pack("<QQ", self.start_slot, self.count)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlobsByRangeRequest":
        start, count = struct.unpack("<QQ", data)
        return cls(start, count)


@dataclass
class BlobsByRootRequest:
    """List of (block_root, index) blob identifiers (spec BlobIdentifier)."""

    ids: List[Tuple[bytes, int]]

    def to_bytes(self) -> bytes:
        return b"".join(r + struct.pack("<Q", i) for r, i in self.ids)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlobsByRootRequest":
        if len(data) % 40:
            raise RpcError("blob identifiers must be 40 bytes each")
        return cls([
            (data[i:i + 32], struct.unpack_from("<Q", data, i + 32)[0])
            for i in range(0, len(data), 40)
        ])


@dataclass
class PeerExchangeRequest:
    max_peers: int

    def to_bytes(self) -> bytes:
        return struct.pack("<Q", self.max_peers)

    @classmethod
    def from_bytes(cls, data: bytes) -> "PeerExchangeRequest":
        return cls(struct.unpack("<Q", data)[0])


@dataclass
class PeerEntry:
    peer_id: str
    host: str
    port: int


def encode_peer_entries(entries) -> bytes:
    out = bytearray(struct.pack(">H", len(entries)))
    for e in entries:
        pid = e.peer_id.encode()
        host = e.host.encode()
        out += struct.pack(">B", len(pid)) + pid
        out += struct.pack(">B", len(host)) + host
        out += struct.pack(">H", e.port)
    return bytes(out)


def decode_peer_entries(data: bytes):
    (count,) = struct.unpack_from(">H", data, 0)
    pos = 2
    out = []
    for _ in range(count):
        (plen,) = struct.unpack_from(">B", data, pos); pos += 1
        pid = data[pos:pos + plen].decode(); pos += plen
        (hlen,) = struct.unpack_from(">B", data, pos); pos += 1
        host = data[pos:pos + hlen].decode(); pos += hlen
        (port,) = struct.unpack_from(">H", data, pos); pos += 2
        out.append(PeerEntry(pid, host, port))
    return out


def serve_peer_exchange(endpoint, sender: str, max_peers) -> bytes:
    """One answer for both the router and the boot node: known listen
    addresses, excluding the requester, capped."""
    addrs = (endpoint.known_peer_addrs()
             if hasattr(endpoint, "known_peer_addrs") else {})
    entries = [
        PeerEntry(pid, host, port)
        for pid, (host, port) in addrs.items()
        if pid != sender
    ][: max(0, min(int(max_peers), 64))]
    return encode_response_chunk(SUCCESS, encode_peer_entries(entries))


@dataclass
class LightClientBootstrapRequest:
    """Request body = the block root to bootstrap from (spec
    light_client_bootstrap req/resp)."""

    root: bytes

    def to_bytes(self) -> bytes:
        return bytes(self.root)

    @classmethod
    def from_bytes(cls, data: bytes) -> "LightClientBootstrapRequest":
        if len(data) != 32:
            raise RpcError("light_client_bootstrap request must be 32 bytes")
        return cls(data)


REQUEST_TYPES = {
    STATUS: Status,
    GOODBYE: Goodbye,
    PING: Ping,
    METADATA: type(None),  # metadata request has an empty body
    BLOCKS_BY_RANGE: BlocksByRangeRequest,
    BLOCKS_BY_ROOT: BlocksByRootRequest,
    BLOBS_BY_RANGE: BlobsByRangeRequest,
    BLOBS_BY_ROOT: BlobsByRootRequest,
    PEER_EXCHANGE: PeerExchangeRequest,
    LIGHT_CLIENT_BOOTSTRAP: LightClientBootstrapRequest,
    LIGHT_CLIENT_OPTIMISTIC_UPDATE: type(None),  # empty request body
    LIGHT_CLIENT_FINALITY_UPDATE: type(None),
}


def encode_request(protocol: str, request) -> bytes:
    body = b"" if request is None else request.to_bytes()
    return _write_varint(len(body)) + snappy_codec.frame_compress(body)


def decode_request(protocol: str, data: bytes):
    length, pos = _read_varint(data, 0)
    body = snappy_codec.frame_decompress(data[pos:])
    if len(body) != length:
        raise RpcError("request length prefix mismatch")
    cls = REQUEST_TYPES[protocol]
    return None if cls is type(None) else cls.from_bytes(body)


def encode_response_chunk(
    result: int, payload: bytes, context_bytes: Optional[bytes] = None
) -> bytes:
    out = bytes([result]) + _write_varint(len(payload))
    if context_bytes is not None:
        out += context_bytes
    return out + snappy_codec.frame_compress(payload)


def decode_response_chunk(
    data: bytes, has_context: bool = False
) -> Tuple[int, bytes, Optional[bytes], int]:
    """Returns (result, payload, context_bytes, bytes_consumed)."""
    if not data:
        raise RpcError("empty chunk")
    result = data[0]
    length, pos = _read_varint(data, 1)
    context = None
    if has_context and result == SUCCESS:
        context = data[pos : pos + 4]
        pos += 4
    # frames are self-delimiting only via content; chunks here are one
    # frame-stream each, delimited by the transport message boundary.
    payload = snappy_codec.frame_decompress(data[pos:])
    if len(payload) != length:
        raise RpcError("response length prefix mismatch")
    return result, payload, context, len(data)
