"""In-process network fabric.

The test/simulator transport: N nodes on one process exchanging gossip and
RPC bytes through queues — the topology of the reference's
``testing/simulator`` (N in-process beacon nodes on one runtime,
``testing/node_test_rig``).  The ``Endpoint`` interface is what a real
libp2p-style TCP/QUIC transport would implement; everything above it
(gossip dedup/forwarding, RPC codecs, peer scoring, sync) is
transport-agnostic.

Fault fabric (the levers the reference's sync tests and ``fallback-sim``
pull, plus the scenario soak's adversarial half):

- a partition map (``set_partition``) severing groups of peers,
- per-link :class:`LinkPlan` faults — drop probability, delivery latency in
  hub *ticks* with jitter, duplication, reordering — each decision derived
  from ``sha256(seed | directed link | per-link message index)``, so a run
  replays **byte-identically** per link regardless of thread interleaving,
- the ``net.deliver`` fault-injection point (``fault_injection.py``): an
  ``error`` plan drops the envelope, ``hang`` stalls the sender, and
  ``corrupt`` flips one payload byte before delivery,
- a delayed-delivery queue drained by :meth:`Hub.advance_tick` (the
  simulator calls it once per slot; scenario pumps call it faster).

Every drop/delay/duplicate is counted (``fault_counters``) and, when
recording is enabled, appended to a per-directed-link schedule whose
:meth:`Hub.schedule_digest` is the determinism fingerprint scenario soak
artifacts carry.
"""

from __future__ import annotations

import hashlib
import heapq
import queue
import random
import threading
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import locksmith
from ..metrics import (
    NET_ENVELOPES_DELAYED,
    NET_ENVELOPES_DROPPED,
    NET_ENVELOPES_DUPLICATED,
    NET_ENVELOPES_REORDERED,
)


@dataclass
class Envelope:
    # gossip-class kinds ("gossip", "ihave", "iwant", "graft", "prune",
    # "subscribe", "unsubscribe") ride the real gossipsub protobuf wire on
    # secured TCP connections; rpc kinds stay on the envelope stream
    kind: str
    sender: str
    topic: Optional[str] = None  # gossip
    protocol: Optional[str] = None  # rpc
    request_id: int = 0
    data: bytes = b""
    #: Cross-node trace propagation (telemetry_scope.envelope_trace_ctx):
    #: the sender's active trace id, node id, and a read-only Lamport stamp.
    #: Observability sidecar only — never serialized into ``data``, never
    #: part of ``Hub.record_schedule``'s determinism digest (the hub logs
    #: link names + delivery decisions, not envelope contents).
    trace_ctx: Optional[dict] = None


# ---------------------------------------------------------- prune payload
#
# A PRUNE's envelope data carries the v1.1 backoff + peer-exchange records
# (gossipsub rpc.proto ControlPrune: backoff seconds + PeerInfo list).  A
# PX record is our dialable form "host:port|peer_id" — the information the
# reference puts in a signed peer record.


def encode_prune_data(backoff_secs: int, px_records: Optional[list] = None) -> bytes:
    import struct as _struct

    # clamp: the wire allows uint64 backoffs but anything beyond an hour is
    # abuse — and must never raise out of a transport read loop
    backoff = max(0, min(int(backoff_secs), 3600))
    body = b"\n".join(r.encode() for r in (px_records or []))
    return _struct.pack(">I", backoff) + body


def decode_prune_data(data: bytes):
    """Returns (backoff_secs, [px_record str])."""
    import struct as _struct

    if len(data) < 4:
        return 60, []
    (backoff,) = _struct.unpack(">I", data[:4])
    records = [r.decode() for r in data[4:].split(b"\n") if r]
    return backoff, records


class Endpoint:
    def __init__(self, hub: "Hub", peer_id: str):
        self.hub = hub
        self.peer_id = peer_id
        self.inbound: "queue.Queue[Envelope]" = queue.Queue()
        self.on_connect: Optional[Callable[[str], None]] = None
        self.on_disconnect: Optional[Callable[[str], None]] = None
        #: The owning node's telemetry scope (set by LocalNode) — endpoints
        #: outlive any contextvar activation, so the scope rides here.
        self.scope = None

    def connected_peers(self) -> Set[str]:
        return self.hub.peers_of(self.peer_id)

    def send(self, to: str, env: Envelope) -> bool:
        if env.trace_ctx is None and self.scope is not None:
            from .. import telemetry_scope

            env.trace_ctx = telemetry_scope.envelope_trace_ctx(self.scope)
        return self.hub.deliver(self.peer_id, to, env)

    def disconnect(self, peer: str) -> None:
        self.hub.disconnect(self.peer_id, peer)


@dataclass
class LinkPlan:
    """Seeded fault plan for one link (or the whole fabric as default).

    ``delay``/``jitter`` are in hub *ticks* (the simulator advances one tick
    per slot; scenario pumps advance faster while waiting on sync) — a
    delayed envelope sits in the hub until :meth:`Hub.advance_tick` reaches
    its due tick.  ``kinds`` restricts the plan to envelope kinds (e.g.
    ``{"gossip"}`` to make gossip lossy while RPC stays reliable); ``None``
    affects everything."""

    drop: float = 0.0        # P(drop) per envelope
    delay: int = 0           # base delivery latency, in ticks
    jitter: int = 0          # + uniform [0, jitter] extra ticks
    duplicate: float = 0.0   # P(deliver a second copy)
    reorder: float = 0.0     # P(jump ahead of earlier-due traffic)
    kinds: Optional[frozenset] = None

    def applies_to(self, kind: str) -> bool:
        return self.kinds is None or kind in self.kinds

    def is_noop(self) -> bool:
        return (self.drop == 0.0 and self.delay == 0 and self.jitter == 0
                and self.duplicate == 0.0 and self.reorder == 0.0)

    def to_dict(self) -> dict:
        out = {"drop": self.drop, "delay": self.delay, "jitter": self.jitter,
               "duplicate": self.duplicate, "reorder": self.reorder}
        if self.kinds is not None:
            out["kinds"] = sorted(self.kinds)
        return out


class Hub:
    """The wire: tracks links, delivers envelopes, injects faults."""

    def __init__(self, seed: int = 0):
        self._endpoints: Dict[str, Endpoint] = {}
        self._links: Set[Tuple[str, str]] = set()
        self._lock = locksmith.lock("Hub._lock")
        self._rng = random.Random(seed)
        self.seed = seed
        self.drop_probability: float = 0.0
        self._partitions: Dict[str, int] = {}  # peer -> partition id
        # -------- fault fabric state (all guarded by self._lock) --------
        # unordered pair -> plans; the FIRST plan matching the envelope's
        # kind decides (so gossip can be lossy while RPC is merely slow)
        self._link_plans: Dict[Tuple[str, str], List[LinkPlan]] = {}
        self._default_plan: Optional[LinkPlan] = None
        self._link_seq: Dict[Tuple[str, str], int] = {}  # DIRECTED msg index
        self._delayed: List[tuple] = []  # heap of (due, prio, seq, to, env)
        self._delayed_seq = 0
        self._tick = 0
        self._counters: Dict[str, int] = {}
        self._schedule: Optional[Dict[str, List[str]]] = None
        # Optional per-tick hook, invoked outside the fabric lock after
        # each advance_tick.  The scenario engine installs the virtual
        # clock's advance here, making "ticks = hub ticks" structural.
        self.on_tick: Optional[Callable[[], None]] = None

    def register(self, peer_id: str) -> Endpoint:
        with self._lock:
            if peer_id in self._endpoints:
                raise ValueError(f"duplicate peer id {peer_id}")
            ep = Endpoint(self, peer_id)
            self._endpoints[peer_id] = ep
            return ep

    def unregister(self, peer_id: str) -> None:
        """Remove a peer and its links (node churn: a killed node's id must
        be re-registrable on restart, and in-flight delayed traffic to it
        must drop as ``dead``, not queue forever)."""
        peers = self.peers_of(peer_id)
        for other in peers:
            self.disconnect(peer_id, other)
        with self._lock:
            self._endpoints.pop(peer_id, None)

    def connect(self, a: str, b: str) -> None:
        """Symmetric dial (reference: libp2p connection established)."""
        with self._lock:
            self._links.add((min(a, b), max(a, b)))
        for x, y in ((a, b), (b, a)):
            ep = self._endpoints.get(x)
            if ep and ep.on_connect:
                ep.on_connect(y)

    def disconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._links.discard((min(a, b), max(a, b)))
        for x, y in ((a, b), (b, a)):
            ep = self._endpoints.get(x)
            if ep and ep.on_disconnect:
                ep.on_disconnect(y)

    def peers_of(self, peer_id: str) -> Set[str]:
        with self._lock:
            out = set()
            for a, b in self._links:
                if a == peer_id:
                    out.add(b)
                elif b == peer_id:
                    out.add(a)
            return out

    def set_partition(self, peer_id: str, partition: int) -> None:
        with self._lock:
            self._partitions[peer_id] = partition

    def clear_partitions(self) -> None:
        with self._lock:
            self._partitions.clear()

    # ------------------------------------------------------- fault fabric

    def set_link_plan(self, a: str, b: str, plan: Optional[LinkPlan],
                      append: bool = False) -> None:
        """Install (or with ``None`` remove) a fault plan on the a<->b link.
        ``append=True`` stacks another plan; the first plan whose ``kinds``
        match an envelope decides for it.  Composes with partitions: a
        partition drops outright before the plan's dice ever roll."""
        key = (min(a, b), max(a, b))
        with self._lock:
            if plan is None:
                self._link_plans.pop(key, None)
            elif append and key in self._link_plans:
                self._link_plans[key].append(plan)
            else:
                self._link_plans[key] = [plan]

    def set_default_link_plan(self, plan: Optional[LinkPlan]) -> None:
        with self._lock:
            self._default_plan = plan

    def clear_link_plans(self) -> None:
        with self._lock:
            self._link_plans.clear()
            self._default_plan = None

    def record_schedule(self, enable: bool = True) -> None:
        """Start (or stop) recording per-directed-link delivery decisions —
        the byte-identical evidence the determinism tests compare."""
        with self._lock:
            self._schedule = {} if enable else None

    def schedule(self) -> Dict[str, List[str]]:
        with self._lock:
            return {k: list(v) for k, v in (self._schedule or {}).items()}

    def schedule_digest(self) -> str:
        """SHA-256 over the recorded per-link decision streams, link-sorted —
        stable under cross-link thread interleaving (each directed link's
        stream is already deterministic)."""
        h = hashlib.sha256()
        for link, entries in sorted(self.schedule().items()):
            h.update(link.encode())
            for e in entries:
                h.update(e.encode())
        return h.hexdigest()

    def fault_counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    @property
    def tick(self) -> int:
        return self._tick

    def _count(self, key: str) -> None:
        self._counters[key] = self._counters.get(key, 0) + 1

    def _drop(self, reason: str) -> bool:
        with self._lock:
            self._count(f"dropped_{reason}")
        NET_ENVELOPES_DROPPED.inc(reason=reason)
        return False

    def _uniforms(self, sender: str, to: str, n: int) -> Tuple[float, float, float, int]:
        """Per-envelope decision randomness: a pure function of
        (seed, directed link, per-link message index) so the schedule of any
        one link replays byte-identically whatever the thread interleaving."""
        digest = hashlib.sha256(
            f"{self.seed}|{sender}>{to}|{n}".encode()).digest()
        u_drop = int.from_bytes(digest[0:8], "big") / 2.0 ** 64
        u_dup = int.from_bytes(digest[8:16], "big") / 2.0 ** 64
        u_reorder = int.from_bytes(digest[16:24], "big") / 2.0 ** 64
        jitter_raw = int.from_bytes(digest[24:28], "big")
        return u_drop, u_dup, u_reorder, jitter_raw

    def _log_schedule(self, sender: str, to: str, n: int, entry: str) -> None:
        if self._schedule is not None:
            self._schedule.setdefault(f"{sender}>{to}", []).append(f"{n}:{entry}")

    def deliver(self, sender: str, to: str, env: Envelope) -> bool:
        with self._lock:
            linked = (min(sender, to), max(sender, to)) in self._links
        if not linked:
            return self._drop("unlinked")
        if self._partitions.get(sender, 0) != self._partitions.get(to, 0):
            return self._drop("partition")
        if self.drop_probability and self._rng.random() < self.drop_probability:
            return self._drop("plan")
        # net.deliver injection point: error => drop, hang => stall the
        # sending thread, corrupt => flip one payload byte (the receiver's
        # decoders and penalties absorb it).
        from .. import fault_injection

        if fault_injection.ACTIVE:
            try:
                action = fault_injection.fire("net.deliver", op=env.kind)
            except fault_injection.InjectedFault:
                return self._drop("fault")
            if action == "corrupt" and env.data:
                flip = hashlib.sha256(env.data).digest()[0] % len(env.data)
                data = bytearray(env.data)
                data[flip] ^= 0xFF
                env = replace(env, data=bytes(data))
        # Decision, schedule log, and (for delayed traffic) heap insertion
        # happen under ONE lock hold with the per-link index assignment:
        # concurrent senders on the same directed link must not interleave
        # entries out of index order (the byte-identical-schedule contract).
        with self._lock:
            plan = None
            pair = (min(sender, to), max(sender, to))
            candidates = self._link_plans.get(pair)
            if candidates is None and self._default_plan is not None:
                candidates = [self._default_plan]
            for candidate in candidates or ():
                if candidate.applies_to(env.kind) and not candidate.is_noop():
                    plan = candidate
                    n = self._link_seq.get((sender, to), 0)
                    self._link_seq[(sender, to)] = n + 1
                    break
            if plan is not None:
                u_drop, u_dup, u_reorder, jitter_raw = self._uniforms(sender, to, n)
                if u_drop < plan.drop:
                    self._log_schedule(sender, to, n, "drop")
                    self._count("dropped_plan")
                    dropped = True
                else:
                    dropped = False
                    delay = plan.delay + (
                        jitter_raw % (plan.jitter + 1) if plan.jitter else 0)
                    dup = u_dup < plan.duplicate
                    reordered = delay > 0 and u_reorder < plan.reorder
                    entry = (f"d{delay}" + ("+dup" if dup else "")
                             + ("+ro" if reordered else ""))
                    self._log_schedule(sender, to, n, entry)
                    if delay > 0:
                        due = self._tick + delay
                        prio = 0 if reordered else 1
                        for _ in range(2 if dup else 1):
                            heapq.heappush(
                                self._delayed,
                                (due, prio, self._delayed_seq, to, env))
                            self._delayed_seq += 1
                        self._count("delayed")
                        if reordered:
                            self._count("reordered")
                    if dup:
                        self._count("duplicated")
        if plan is None:
            return self._put(to, env)
        if dropped:
            NET_ENVELOPES_DROPPED.inc(reason="plan")
            return False
        if delay == 0:
            ok = self._put(to, env)
            if dup:
                NET_ENVELOPES_DUPLICATED.inc()
                self._put(to, env)
            return ok
        NET_ENVELOPES_DELAYED.inc()
        if dup:
            NET_ENVELOPES_DUPLICATED.inc()
        if reordered:
            NET_ENVELOPES_REORDERED.inc()
        return True

    def _put(self, to: str, env: Envelope) -> bool:
        ep = self._endpoints.get(to)
        if ep is None:
            return self._drop("dead")
        ep.inbound.put(env)
        return True

    def advance_tick(self, tick: Optional[int] = None) -> int:
        """Advance the fabric clock and deliver every due delayed envelope.
        Reordered envelopes (prio 0) in a due batch deliver before normal
        ones; partitions and links are re-checked at drain time, so a
        message sent before a partition does not tunnel through it.
        Returns how many envelopes were delivered."""
        due_entries: List[tuple] = []
        with self._lock:
            self._tick = self._tick + 1 if tick is None else int(tick)
            while self._delayed and self._delayed[0][0] <= self._tick:
                due_entries.append(heapq.heappop(self._delayed))
            on_tick = self.on_tick
        if on_tick is not None:
            # outside the lock: the hook (a VirtualClock advance in
            # scenario runs) must not nest under the fabric lock
            on_tick()
        due_entries.sort(key=lambda e: (e[0], e[1], e[2]))
        delivered = 0
        for _due, _prio, _seq, to, env in due_entries:
            with self._lock:
                linked = (min(env.sender, to), max(env.sender, to)) in self._links
            if not linked:
                self._drop("unlinked")
                continue
            if self._partitions.get(env.sender, 0) != self._partitions.get(to, 0):
                self._drop("partition")
                continue
            if self._put(to, env):
                delivered += 1
        return delivered

    def pending_delayed(self) -> int:
        with self._lock:
            return len(self._delayed)
