"""In-process network fabric.

The test/simulator transport: N nodes on one process exchanging gossip and
RPC bytes through queues — the topology of the reference's
``testing/simulator`` (N in-process beacon nodes on one runtime,
``testing/node_test_rig``).  The ``Endpoint`` interface is what a real
libp2p-style TCP/QUIC transport would implement; everything above it
(gossip dedup/forwarding, RPC codecs, peer scoring, sync) is
transport-agnostic.

Fault injection: per-link drop probability and a partition set — the levers
the reference's sync tests and ``fallback-sim`` pull.
"""

from __future__ import annotations

import queue
import random
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Set, Tuple


@dataclass
class Envelope:
    # gossip-class kinds ("gossip", "ihave", "iwant", "graft", "prune",
    # "subscribe", "unsubscribe") ride the real gossipsub protobuf wire on
    # secured TCP connections; rpc kinds stay on the envelope stream
    kind: str
    sender: str
    topic: Optional[str] = None  # gossip
    protocol: Optional[str] = None  # rpc
    request_id: int = 0
    data: bytes = b""


# ---------------------------------------------------------- prune payload
#
# A PRUNE's envelope data carries the v1.1 backoff + peer-exchange records
# (gossipsub rpc.proto ControlPrune: backoff seconds + PeerInfo list).  A
# PX record is our dialable form "host:port|peer_id" — the information the
# reference puts in a signed peer record.


def encode_prune_data(backoff_secs: int, px_records: Optional[list] = None) -> bytes:
    import struct as _struct

    # clamp: the wire allows uint64 backoffs but anything beyond an hour is
    # abuse — and must never raise out of a transport read loop
    backoff = max(0, min(int(backoff_secs), 3600))
    body = b"\n".join(r.encode() for r in (px_records or []))
    return _struct.pack(">I", backoff) + body


def decode_prune_data(data: bytes):
    """Returns (backoff_secs, [px_record str])."""
    import struct as _struct

    if len(data) < 4:
        return 60, []
    (backoff,) = _struct.unpack(">I", data[:4])
    records = [r.decode() for r in data[4:].split(b"\n") if r]
    return backoff, records


class Endpoint:
    def __init__(self, hub: "Hub", peer_id: str):
        self.hub = hub
        self.peer_id = peer_id
        self.inbound: "queue.Queue[Envelope]" = queue.Queue()
        self.on_connect: Optional[Callable[[str], None]] = None
        self.on_disconnect: Optional[Callable[[str], None]] = None

    def connected_peers(self) -> Set[str]:
        return self.hub.peers_of(self.peer_id)

    def send(self, to: str, env: Envelope) -> bool:
        return self.hub.deliver(self.peer_id, to, env)

    def disconnect(self, peer: str) -> None:
        self.hub.disconnect(self.peer_id, peer)


class Hub:
    """The wire: tracks links, delivers envelopes, injects faults."""

    def __init__(self, seed: int = 0):
        self._endpoints: Dict[str, Endpoint] = {}
        self._links: Set[Tuple[str, str]] = set()
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.drop_probability: float = 0.0
        self._partitions: Dict[str, int] = {}  # peer -> partition id

    def register(self, peer_id: str) -> Endpoint:
        with self._lock:
            if peer_id in self._endpoints:
                raise ValueError(f"duplicate peer id {peer_id}")
            ep = Endpoint(self, peer_id)
            self._endpoints[peer_id] = ep
            return ep

    def connect(self, a: str, b: str) -> None:
        """Symmetric dial (reference: libp2p connection established)."""
        with self._lock:
            self._links.add((min(a, b), max(a, b)))
        for x, y in ((a, b), (b, a)):
            ep = self._endpoints.get(x)
            if ep and ep.on_connect:
                ep.on_connect(y)

    def disconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._links.discard((min(a, b), max(a, b)))
        for x, y in ((a, b), (b, a)):
            ep = self._endpoints.get(x)
            if ep and ep.on_disconnect:
                ep.on_disconnect(y)

    def peers_of(self, peer_id: str) -> Set[str]:
        with self._lock:
            out = set()
            for a, b in self._links:
                if a == peer_id:
                    out.add(b)
                elif b == peer_id:
                    out.add(a)
            return out

    def set_partition(self, peer_id: str, partition: int) -> None:
        self._partitions[peer_id] = partition

    def clear_partitions(self) -> None:
        self._partitions.clear()

    def deliver(self, sender: str, to: str, env: Envelope) -> bool:
        with self._lock:
            linked = (min(sender, to), max(sender, to)) in self._links
        if not linked:
            return False
        if self._partitions.get(sender, 0) != self._partitions.get(to, 0):
            return False
        if self.drop_probability and self._rng.random() < self.drop_probability:
            return False
        ep = self._endpoints.get(to)
        if ep is None:
            return False
        ep.inbound.put(env)
        return True
