"""Peer database + scoring + ban lifecycle.

Equivalent of the reference's ``peer_manager/`` + ``peerdb/score.rs``: a
real-valued score per peer combining protocol penalties, decaying toward
zero, with disconnect/ban thresholds.  Numbers mirror the reference's
(`peerdb/score.rs`: MIN_SCORE_BEFORE_DISCONNECT = -20,
MIN_SCORE_BEFORE_BAN = -50, halflife-driven decay).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..logs import get_logger

log = get_logger("network.peers")

MIN_SCORE_BEFORE_DISCONNECT = -20.0
MIN_SCORE_BEFORE_BAN = -50.0
SCORE_HALFLIFE_SECS = 600.0
BANNED_BEFORE_DECAY_SECS = 1800.0
DEFAULT_TARGET_PEERS = 16


class PeerAction:
    """Reference ``PeerAction`` severity ladder."""

    FATAL = "fatal"  # instant ban
    LOW_TOLERANCE = "low"  # -10: ban after ~5
    MID_TOLERANCE = "mid"  # -5
    HIGH_TOLERANCE = "high"  # -1

    PENALTIES = {FATAL: -100.0, LOW_TOLERANCE: -10.0, MID_TOLERANCE: -5.0, HIGH_TOLERANCE: -1.0}


class ConnectionState:
    CONNECTED = "connected"
    DISCONNECTED = "disconnected"
    BANNED = "banned"


@dataclass
class PeerInfo:
    peer_id: str
    state: str = ConnectionState.DISCONNECTED
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    banned_at: Optional[float] = None
    metadata: Optional[object] = None
    status: Optional[object] = None  # last Status handshake

    def decayed_score(self, now: float) -> float:
        dt = max(0.0, now - self.last_update)
        if self.banned_at is not None and now - self.banned_at < BANNED_BEFORE_DECAY_SECS:
            return self.score  # banned scores freeze before decaying
        return self.score * math.exp(-dt * math.log(2) / SCORE_HALFLIFE_SECS)


class PeerManager:
    def __init__(self, target_peers: int = DEFAULT_TARGET_PEERS,
                 clock=time.monotonic):
        # Injectable clock (same seam as RateLimiter): score decay and ban
        # lifts are control-path time — a scenario/virtual-time harness
        # supplies its own clock so decay cannot race thresholds against
        # host load (ROADMAP item 4; wallclock_pass holds this line).
        self.peers: Dict[str, PeerInfo] = {}
        self.target_peers = target_peers
        self._clock = clock
        self._disconnect_requests: List[str] = []

    def _peer(self, peer_id: str) -> PeerInfo:
        info = self.peers.get(peer_id)
        if info is None:
            info = self.peers[peer_id] = PeerInfo(
                peer_id, last_update=self._clock())
        return info

    # --------------------------------------------------------- lifecycle

    def on_connect(self, peer_id: str) -> bool:
        """Returns False when the peer is banned and must be refused."""
        info = self._peer(peer_id)
        if self.is_banned(peer_id):
            log.debug("refused banned peer", peer=peer_id)
            return False
        info.state = ConnectionState.CONNECTED
        log.info("peer connected", peer=peer_id,
                 connected=len(self.connected_peers()))
        return True

    def on_disconnect(self, peer_id: str) -> None:
        info = self._peer(peer_id)
        if info.state != ConnectionState.BANNED:
            info.state = ConnectionState.DISCONNECTED
            log.info("peer disconnected", peer=peer_id,
                     connected=len(self.connected_peers()))

    # ----------------------------------------------------------- scoring

    def report(self, peer_id: str, action: str, _reason: str = "") -> None:
        """Apply a penalty (reference ``report_peer``)."""
        now = self._clock()
        info = self._peer(peer_id)
        info.score = info.decayed_score(now) + PeerAction.PENALTIES[action]
        info.last_update = now
        # epsilon absorbs sub-second decay so "5 low-tolerance strikes ban"
        # holds exactly, as in the reference's threshold arithmetic
        if info.score <= MIN_SCORE_BEFORE_BAN + 1e-3:
            info.score = min(info.score, MIN_SCORE_BEFORE_BAN)
            info.state = ConnectionState.BANNED
            info.banned_at = now
            log.warning("peer banned", peer=peer_id, action=action,
                        score=round(info.score, 1), reason=_reason)
        elif info.score <= MIN_SCORE_BEFORE_DISCONNECT:
            if info.state == ConnectionState.CONNECTED:
                info.state = ConnectionState.DISCONNECTED
                self._disconnect_requests.append(peer_id)

    def score(self, peer_id: str) -> float:
        info = self.peers.get(peer_id)
        return info.decayed_score(self._clock()) if info else 0.0

    def is_banned(self, peer_id: str) -> bool:
        info = self.peers.get(peer_id)
        if info is None:
            return False
        if info.state != ConnectionState.BANNED:
            return False
        # bans lift once the decayed score recovers past the ban threshold
        if info.decayed_score(self._clock()) > MIN_SCORE_BEFORE_BAN:
            info.state = ConnectionState.DISCONNECTED
            info.banned_at = None
            return False
        return True

    def heartbeat(self) -> List[str]:
        """Periodic maintenance; returns peers to disconnect
        (reference ``PeerManager::heartbeat``)."""
        out, self._disconnect_requests = self._disconnect_requests, []
        return out

    # ----------------------------------------------------------- queries

    def connected_peers(self) -> List[str]:
        return [p for p, i in self.peers.items() if i.state == ConnectionState.CONNECTED]

    def best_peer_by_head(self) -> Optional[str]:
        """Connected peer with the highest advertised head slot."""
        best, best_slot = None, -1
        for pid in self.connected_peers():
            st = self.peers[pid].status
            if st is not None and st.head_slot > best_slot:
                best, best_slot = pid, st.head_slot
        return best
