"""Gossip topics ↔ fork digests.

Equivalent of the reference's ``lighthouse_network/src/types/topics.rs``
(466 LoC): topic strings ``/eth2/{fork_digest}/{kind}/ssz_snappy`` with
subnet-indexed attestation / sync-committee / blob topics, and the set of
core topics a node subscribes for a fork.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..consensus import helpers as h
from ..types.spec import ChainSpec

ENCODING = "ssz_snappy"

BEACON_BLOCK = "beacon_block"
BEACON_AGGREGATE_AND_PROOF = "beacon_aggregate_and_proof"
BEACON_ATTESTATION_PREFIX = "beacon_attestation_"
VOLUNTARY_EXIT = "voluntary_exit"
PROPOSER_SLASHING = "proposer_slashing"
ATTESTER_SLASHING = "attester_slashing"
SYNC_COMMITTEE_CONTRIBUTION_AND_PROOF = "sync_committee_contribution_and_proof"
SYNC_COMMITTEE_PREFIX = "sync_committee_"
BLS_TO_EXECUTION_CHANGE = "bls_to_execution_change"
LIGHT_CLIENT_FINALITY_UPDATE = "light_client_finality_update"
LIGHT_CLIENT_OPTIMISTIC_UPDATE = "light_client_optimistic_update"
BLOB_SIDECAR_PREFIX = "blob_sidecar_"


@dataclass(frozen=True)
class GossipTopic:
    fork_digest: bytes  # 4 bytes
    kind: str

    def __str__(self) -> str:
        return f"/eth2/{self.fork_digest.hex()}/{self.kind}/{ENCODING}"

    @classmethod
    def parse(cls, s: str) -> "GossipTopic":
        parts = s.split("/")
        if len(parts) != 5 or parts[1] != "eth2" or parts[4] != ENCODING:
            raise ValueError(f"bad topic {s!r}")
        return cls(bytes.fromhex(parts[2]), parts[3])

    @property
    def subnet_id(self) -> int:
        for prefix in (BEACON_ATTESTATION_PREFIX, SYNC_COMMITTEE_PREFIX, BLOB_SIDECAR_PREFIX):
            if self.kind.startswith(prefix):
                return int(self.kind[len(prefix):])
        raise ValueError(f"{self.kind} is not a subnet topic")


def fork_digest(state_or_version, genesis_validators_root: bytes, spec: ChainSpec = None) -> bytes:
    if isinstance(state_or_version, bytes):
        return h.compute_fork_digest(state_or_version, genesis_validators_root)
    state = state_or_version
    return h.compute_fork_digest(
        bytes(state.fork.current_version), bytes(state.genesis_validators_root)
    )


def core_topics(digest: bytes, fork_name: str, spec: ChainSpec) -> List[GossipTopic]:
    """Topics every beacon node subscribes (reference ``CORE_TOPICS`` +
    fork-dependent additions)."""
    kinds = [
        BEACON_BLOCK,
        BEACON_AGGREGATE_AND_PROOF,
        VOLUNTARY_EXIT,
        PROPOSER_SLASHING,
        ATTESTER_SLASHING,
    ]
    if fork_name != "phase0":
        kinds.append(SYNC_COMMITTEE_CONTRIBUTION_AND_PROOF)
        # LC servers gossip their updates (p2p spec light_client topics)
        kinds.append(LIGHT_CLIENT_FINALITY_UPDATE)
        kinds.append(LIGHT_CLIENT_OPTIMISTIC_UPDATE)
    if fork_name in ("capella", "deneb", "electra"):
        kinds.append(BLS_TO_EXECUTION_CHANGE)
    if fork_name in ("deneb", "electra"):
        kinds += [f"{BLOB_SIDECAR_PREFIX}{i}" for i in range(spec.max_blobs_per_block)]
    return [GossipTopic(digest, k) for k in kinds]


def attestation_subnet_topic(digest: bytes, subnet_id: int) -> GossipTopic:
    return GossipTopic(digest, f"{BEACON_ATTESTATION_PREFIX}{subnet_id}")


def fork_name_for_digest(digest: bytes, genesis_validators_root: bytes,
                         spec: ChainSpec):
    """Which fork a topic's digest belongs to (reference types/topics.rs
    fork-digest mapping) — None for an unknown digest."""
    for fork in ("phase0", "altair", "bellatrix", "capella", "deneb",
                 "electra"):
        version = spec.fork_version_for(fork)
        if h.compute_fork_digest(version, genesis_validators_root) == digest:
            return fork
    return None


def compute_subnet_for_attestation(state, slot: int, committee_index: int, spec: ChainSpec) -> int:
    """Spec ``compute_subnet_for_attestation``."""
    committees_per_slot = h.get_committee_count_per_slot(
        state, h.compute_epoch_at_slot(slot, spec), spec
    )
    slots_since_epoch_start = slot % spec.slots_per_epoch
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % spec.attestation_subnet_count
