"""Attestation + sync-committee subnet subscription scheduling.

Equivalent of the reference's ``beacon_node/network/src/subnet_service/``
(``attestation_subnets.rs`` 687 LoC + ``sync_subnets.rs`` 359 LoC): a node
keeps two kinds of subnet subscriptions —

- **backbone**: ``SUBNETS_PER_NODE`` long-lived attestation subnets derived
  deterministically from the node id and rotated every
  ``EPOCHS_PER_SUBNET_SUBSCRIPTION`` epochs (consensus-spec phase0 p2p
  ``compute_subscribed_subnets``), so the network as a whole covers all 64
  subnets without anyone subscribing to everything;
- **duty-driven**: short-lived subscriptions requested by validator clients
  via ``POST /eth/v1/validator/beacon_committee_subscriptions`` (aggregators
  must see the unaggregated traffic for their slot) and
  ``.../sync_committee_subscriptions``, expiring after the duty.

``subscribe_all`` reproduces the reference's ``--subscribe-all-subnets``
flag — also the right mode for small in-process simulations, where two
backbone subnets per node would partition the traffic.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Set

from . import topics as topics_mod

# consensus-spec phase0/p2p-interface constants
ATTESTATION_SUBNET_EXTRA_BITS = 0
EPOCHS_PER_SUBNET_SUBSCRIPTION = 256
SUBNETS_PER_NODE = 2
NODE_ID_BITS = 256


def compute_subscribed_subnets(node_id: int, epoch: int, spec) -> List[int]:
    """Spec ``compute_subscribed_subnets``: the node's backbone subnets at
    ``epoch`` (stable for EPOCHS_PER_SUBNET_SUBSCRIPTION epochs, offset
    per-node so the whole network doesn't rotate at once)."""
    from ..consensus.shuffling import compute_shuffled_index

    count = spec.attestation_subnet_count
    prefix_bits = (count - 1).bit_length() + ATTESTATION_SUBNET_EXTRA_BITS
    node_id_prefix = node_id >> (NODE_ID_BITS - prefix_bits)
    node_offset = node_id % EPOCHS_PER_SUBNET_SUBSCRIPTION
    period = (epoch + node_offset) // EPOCHS_PER_SUBNET_SUBSCRIPTION
    permutation_seed = hashlib.sha256(
        period.to_bytes(8, "little")).digest()
    permutated_prefix = compute_shuffled_index(
        node_id_prefix, 1 << prefix_bits, permutation_seed,
        spec.preset.shuffle_round_count,
    )
    return [(permutated_prefix + i) % count for i in range(SUBNETS_PER_NODE)]


class SubnetService:
    """Owns every subnet subscription decision for one node and applies the
    resulting subscribe/unsubscribe calls to the gossip service."""

    def __init__(self, *, service, digest: bytes, spec, node_id: int,
                 subscribe_all: bool = False):
        self.service = service
        self.digest = digest
        self.spec = spec
        self.node_id = node_id
        self.subscribe_all = subscribe_all
        self._lock = threading.RLock()
        self._backbone: Set[int] = set()
        # attestation subnet -> last slot it is needed for (duty-driven)
        self._duty_until_slot: Dict[int, int] = {}
        # sync subnet -> until_epoch (exclusive, per beacon-api semantics)
        self._sync_until_epoch: Dict[int, int] = {}

        if subscribe_all:
            for subnet in range(spec.attestation_subnet_count):
                self._subscribe_att(subnet)
            self._backbone = set(range(spec.attestation_subnet_count))

    # ------------------------------------------------------------ helpers

    def _subscribe_att(self, subnet: int) -> None:
        self.service.subscribe(
            str(topics_mod.attestation_subnet_topic(self.digest, subnet)))

    def _unsubscribe_att(self, subnet: int) -> None:
        self.service.unsubscribe(
            str(topics_mod.attestation_subnet_topic(self.digest, subnet)))

    def _sync_topic(self, subnet: int) -> str:
        return str(topics_mod.GossipTopic(
            self.digest, f"{topics_mod.SYNC_COMMITTEE_PREFIX}{subnet}"))

    # ----------------------------------------------------------- backbone

    def update_epoch(self, epoch: int) -> List[int]:
        """Rotate the backbone for ``epoch``; returns the active set."""
        if self.subscribe_all:
            return sorted(self._backbone)
        want = set(compute_subscribed_subnets(self.node_id, epoch, self.spec))
        # Decision AND side effect share one critical section: releasing
        # the lock between them lets a concurrent duty subscription for a
        # dropped subnet be immediately undone by our stale snapshot —
        # silently unsubscribing an aggregator for its whole duty window.
        with self._lock:
            drop = self._backbone - want
            add = want - self._backbone
            self._backbone = want
            for subnet in drop:
                if subnet not in self._duty_until_slot:
                    self._unsubscribe_att(subnet)
            for subnet in add:
                self._subscribe_att(subnet)
        return sorted(want)

    # --------------------------------------------------------- duty-driven

    def on_committee_subscriptions(self, entries: List[dict]) -> int:
        """``beacon_committee_subscriptions`` body: subscribe aggregators'
        subnets until their duty slot passes (attestation_subnets.rs
        handle_validator_subscriptions).  Returns #subnets touched."""
        touched = 0
        for entry in entries or []:
            try:
                slot = int(entry["slot"])
                committee_index = int(entry["committee_index"])
                committees_at_slot = int(entry["committees_at_slot"])
                is_aggregator = bool(entry.get("is_aggregator", False))
            except (KeyError, TypeError, ValueError):
                continue
            if not is_aggregator:
                continue  # non-aggregators only need their own attestation
            since_epoch_start = slot % self.spec.slots_per_epoch
            subnet = (
                committees_at_slot * since_epoch_start + committee_index
            ) % self.spec.attestation_subnet_count
            with self._lock:
                known = subnet in self._backbone or subnet in self._duty_until_slot
                prev = self._duty_until_slot.get(subnet, -1)
                self._duty_until_slot[subnet] = max(prev, slot)
                if not known and not self.subscribe_all:
                    self._subscribe_att(subnet)
            touched += 1
        return touched

    def on_sync_committee_subscriptions(self, entries: List[dict]) -> int:
        """``sync_committee_subscriptions`` body: subscribe the listed sync
        subnets until ``until_epoch`` (sync_subnets.rs)."""
        touched = 0
        for entry in entries or []:
            try:
                until_epoch = int(entry["until_epoch"])
                indices = [int(i) for i in entry["sync_committee_indices"]]
            except (KeyError, TypeError, ValueError):
                continue
            for idx in indices:
                subnet = idx // max(
                    1,
                    self.spec.preset.sync_committee_size
                    // self.spec.sync_committee_subnet_count,
                )
                if not 0 <= subnet < self.spec.sync_committee_subnet_count:
                    continue  # out-of-range index: never advertise a
                    # nonexistent sync topic to the network
                with self._lock:
                    fresh = subnet not in self._sync_until_epoch
                    prev = self._sync_until_epoch.get(subnet, -1)
                    self._sync_until_epoch[subnet] = max(prev, until_epoch)
                    if fresh:
                        self.service.subscribe(self._sync_topic(subnet))
                touched += 1
        return touched

    # ------------------------------------------------------------- expiry

    def prune(self, current_slot: int) -> None:
        """Drop expired duty subscriptions (called on the per-slot tick)."""
        current_epoch = current_slot // self.spec.slots_per_epoch
        # expiry decision + unsubscribe in ONE critical section (see
        # update_epoch: a stale snapshot applied after release races
        # concurrent re-subscriptions for the same subnet)
        with self._lock:
            expired_att = [s for s, until in self._duty_until_slot.items()
                           if until < current_slot]
            for s in expired_att:
                del self._duty_until_slot[s]
                if not self.subscribe_all and s not in self._backbone:
                    self._unsubscribe_att(s)
            expired_sync = [s for s, until in self._sync_until_epoch.items()
                            if until <= current_epoch]
            # sync subnets were never part of the subscribe-all initial set
            # — their until_epoch contract holds in EVERY mode
            for s in expired_sync:
                del self._sync_until_epoch[s]
                self.service.unsubscribe(self._sync_topic(s))

    # ----------------------------------------------------------- introspect

    def active_attestation_subnets(self) -> Set[int]:
        with self._lock:
            return set(self._backbone) | set(self._duty_until_slot)

    def active_sync_subnets(self) -> Set[int]:
        with self._lock:
            return set(self._sync_until_epoch)


# ----------------------------------------------------- ENR attnets field


def attnets_bitfield(subnets, count: int = 64) -> bytes:
    """SSZ Bitvector[64] bytes for the eth2 ENR ``attnets`` entry: bit i
    set = subscribed to attestation subnet i (consensus-spec p2p ENR
    structure)."""
    bits = bytearray((count + 7) // 8)
    for s in subnets:
        s = int(s)
        if 0 <= s < count:
            bits[s // 8] |= 1 << (s % 8)
    return bytes(bits)


def enr_attnets(enr) -> set:
    """Attestation subnets an ENR advertises (empty when the field is
    absent — pre-fork records; the predicate must not hard-fail them)."""
    raw = enr.pairs.get(b"attnets")
    if not raw:
        return set()
    out = set()
    for i in range(len(raw) * 8):
        if raw[i // 8] & (1 << (i % 8)):
            out.add(i)
    return out


def subnet_predicate(enr, wanted) -> bool:
    """True when the ENR advertises ANY of the wanted attestation subnets
    (reference discovery/subnet_predicate.rs)."""
    if not wanted:
        return True
    return bool(enr_attnets(enr) & set(int(s) for s in wanted))
