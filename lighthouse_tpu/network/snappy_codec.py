"""Snappy codec (raw block format + streaming frame format).

The reference's wire encodings are ``ssz_snappy`` everywhere: raw snappy for
gossip payloads (``types/pubsub.rs``) and snappy *frames* for req/resp
streams (``rpc/codec/ssz_snappy.rs``, 1,680 LoC).  No snappy library ships in
this image, so the format is implemented here:

- ``decompress`` handles the full raw format (literals + all three copy
  element kinds) for interop with real peers;
- ``compress`` emits a spec-valid literal-only stream (snappy explicitly
  permits uncompressed literal runs).  Trading compression ratio for zero
  dependencies is fine for the in-process fabric; a native matcher can slot
  in later without touching callers.
- frame format: stream identifier + compressed/uncompressed chunks with
  masked CRC32C checksums, per the snappy framing spec.
"""

from __future__ import annotations

import struct
from typing import List

MAX_UNCOMPRESSED = 1 << 24  # sanity bound for this stack's payloads


class SnappyError(ValueError):
    pass


# ------------------------------------------------------------- raw format


def _read_varint(data: bytes, pos: int):
    out = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def compress(data: bytes) -> bytes:
    """Literal-only raw-snappy encoding (valid per the format spec)."""
    out = bytearray(_write_varint(len(data)))
    pos = 0
    while pos < len(data):
        run = data[pos : pos + 65536]
        n = len(run) - 1
        if n < 60:
            out.append(n << 2)
        elif n < 256:
            out.append(60 << 2)
            out.append(n)
        else:
            out.append(61 << 2)
            out += struct.pack("<H", n)
        out += run
        pos += len(run)
    return bytes(out)


def decompress(data: bytes) -> bytes:
    """Full raw-snappy decoder (literals + 1/2/4-byte-offset copies)."""
    expected, pos = _read_varint(data, 0)
    if expected > MAX_UNCOMPRESSED:
        raise SnappyError(f"declared size {expected} too large")
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            n = tag >> 2
            if n >= 60:
                extra = n - 59
                if pos + extra > len(data):
                    raise SnappyError("truncated literal length")
                n = int.from_bytes(data[pos : pos + extra], "little")
                pos += extra
            n += 1
            if pos + n > len(data):
                raise SnappyError("truncated literal")
            out += data[pos : pos + n]
            pos += n
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            if pos >= len(data):
                raise SnappyError("truncated copy-1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > len(data):
                raise SnappyError("truncated copy-2")
            offset = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > len(data):
                raise SnappyError("truncated copy-4")
            offset = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("copy offset out of range")
        for _ in range(length):  # overlapping copies must go byte-by-byte
            out.append(out[-offset])
    if len(out) != expected:
        raise SnappyError(f"decoded {len(out)} bytes, header said {expected}")
    return bytes(out)


# ------------------------------------------------------------ frame format

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"
_CRC_TABLE: List[int] = []


def _crc32c(data: bytes) -> int:
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            _CRC_TABLE.append(crc)
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def frame_compress(data: bytes) -> bytes:
    """Encode as a snappy frame stream (identifier + chunks of <=64KiB)."""
    out = bytearray(_STREAM_ID)
    pos = 0
    while pos < len(data) or (pos == 0 and not data):
        chunk = data[pos : pos + 65536]
        pos += len(chunk) or 1
        body = struct.pack("<I", _masked_crc(chunk)) + compress(chunk)
        if len(body) < 4 + len(chunk):
            out.append(0x00)  # compressed chunk
        else:
            body = struct.pack("<I", _masked_crc(chunk)) + chunk
            out.append(0x01)  # uncompressed chunk
        out += struct.pack("<I", len(body))[:3]
        out += body
        if not data:
            break
    return bytes(out)


def frame_decompress(data: bytes) -> bytes:
    if not data.startswith(_STREAM_ID):
        raise SnappyError("missing snappy stream identifier")
    pos = len(_STREAM_ID)
    out = bytearray()
    while pos < len(data):
        if pos + 4 > len(data):
            raise SnappyError("truncated chunk header")
        kind = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        if pos + length > len(data):
            raise SnappyError("truncated chunk")
        body = data[pos : pos + length]
        pos += length
        if kind == 0x00:
            (crc,) = struct.unpack_from("<I", body, 0)
            chunk = decompress(body[4:])
            if _masked_crc(chunk) != crc:
                raise SnappyError("chunk checksum mismatch")
            out += chunk
        elif kind == 0x01:
            (crc,) = struct.unpack_from("<I", body, 0)
            chunk = body[4:]
            if _masked_crc(chunk) != crc:
                raise SnappyError("chunk checksum mismatch")
            out += chunk
        elif 0x80 <= kind <= 0xFE:
            continue  # skippable padding
        else:
            raise SnappyError(f"unknown chunk kind {kind:#x}")
    return bytes(out)
