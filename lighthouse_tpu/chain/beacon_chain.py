"""The chain core: block import pipeline, attestation processing, block and
attestation production, canonical-head management.

Equivalent of the reference's ``beacon_node/beacon_chain`` crate
(`beacon_chain.rs:378-504` ``BeaconChain``; import pipeline
`block_verification.rs:21-45`; production `beacon_chain.rs:4137,4720`;
head recompute `canonical_head.rs:496`), scaled to the harness/test surface
first: everything here runs against ``MemoryStore`` + ``ManualSlotClock`` +
``MockExecutionEngine`` with no networking, the reference's own test topology
(SURVEY.md §4 tier 3).

Block import is the same staged pipeline, with bulk signature verification
(all of a block's signatures in one batched multi-pairing — the TPU hot path)
happening inside ``state_transition(strategy=VERIFY_BULK)``.
"""

from __future__ import annotations

import time

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .. import metrics, tracing

from ..logs import get_logger

log = get_logger("chain")
from ..consensus import helpers as h
from ..consensus.per_block import BlockProcessingError, BlockSignatureStrategy
from ..consensus.per_slot import process_slots
from ..consensus.state_transition import state_transition
from ..fork_choice import ExecutionStatus, ForkChoice, InvalidAttestation
from ..op_pool import attester_slashing_indices
from ..store import HotColdDB, MemoryStore
from ..types.spec import ChainSpec
from .events import EventBus
from .mock_el import MockExecutionEngine
from .slot_clock import ManualSlotClock, SlotClock


class ChainError(Exception):
    pass


class BlockError(ChainError):
    pass


class AttestationError(ChainError):
    pass


def genesis_block_root_of(state) -> bytes:
    """Canonical genesis block root: the state's latest header with its
    state_root filled in (how the reference derives it at anchor time)."""
    header = state.latest_block_header.copy()
    header.state_root = state.hash_tree_root()
    return header.hash_tree_root()


class NaiveAggregationPool:
    """Aggregate same-data attestations by OR-ing bits and summing signatures
    (reference: ``beacon_chain/src/naive_aggregation_pool.rs``)."""

    SLOT_RETENTION = 64

    def __init__(self) -> None:
        # (slot, data_root) -> {bits_tuple} aggregated attestation
        self._pool: Dict[Tuple[int, bytes], object] = {}

    def insert(self, attestation) -> None:
        from ..crypto.bls import api as bls

        key = (int(attestation.data.slot), h.attestation_dedup_key(attestation))
        existing = self._pool.get(key)
        if existing is None:
            self._pool[key] = attestation.copy()
            return
        new_bits = list(attestation.aggregation_bits)
        old_bits = list(existing.aggregation_bits)
        if any(a and b for a, b in zip(new_bits, old_bits)):
            return  # overlapping — naive pool only merges disjoint signers
        agg = bls.AggregateSignature.from_bytes(bytes(existing.signature))
        agg.add_assign(bls.Signature.from_bytes(bytes(attestation.signature)))
        existing.aggregation_bits = [a or b for a, b in zip(new_bits, old_bits)]
        existing.signature = agg.to_bytes()

    def get_for_block(self, state, spec: ChainSpec, limit: int) -> List[object]:
        """Attestations eligible for inclusion in a block on ``state``."""
        out = []
        state_slot = int(state.slot)
        for (slot, _), att in sorted(self._pool.items(), key=lambda kv: -kv[0][0]):
            if not spec.attestation_includable(slot, state_slot):
                continue
            out.append(att)
            if len(out) >= limit:
                break
        return out

    def get_aggregate(self, slot: int, data_root: bytes,
                      committee_index: Optional[int] = None):
        """Best aggregate for (slot, attestation_data_root) — the
        ``aggregate_attestation`` API's source (naive_aggregation_pool.rs get).

        Electra entries are keyed with committee_bits appended to the data
        root (attestation_dedup_key), so a plain (slot, data_root) lookup
        must scan key prefixes — otherwise the API 404s for every
        post-electra aggregate (round-2 advisor finding).  ``committee_index``
        (the v2 API's parameter) narrows to one committee; without it the
        fullest matching aggregate wins."""
        slot = int(slot)
        data_root = bytes(data_root)
        att = self._pool.get((slot, data_root))
        if att is not None:
            return att.copy()
        best = None
        best_bits = -1
        for (s, key), cand in self._pool.items():
            if s != slot or not key.startswith(data_root):
                continue
            cb = getattr(cand, "committee_bits", None)
            if committee_index is not None:
                if cb is None or not (
                    committee_index < len(cb) and cb[committee_index]
                ):
                    continue
            nbits = sum(1 for b in cand.aggregation_bits if b)
            if nbits > best_bits:
                best, best_bits = cand, nbits
        return None if best is None else best.copy()

    def prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.SLOT_RETENTION
        self._pool = {k: v for k, v in self._pool.items() if k[0] >= cutoff}


class NaiveSyncContributionPool:
    """Aggregate sync-committee messages into per-subcommittee contributions
    and contributions into block sync aggregates (reference
    ``naive_aggregation_pool.rs``'s SyncContribution flavor +
    ``op_pool``'s sync-contribution handling)."""

    SLOT_RETENTION = 8

    def __init__(self, types, spec: ChainSpec):
        self.types = types
        self.spec = spec
        # (slot, block_root, subcommittee) -> SyncCommitteeContribution
        self._pool: Dict[Tuple[int, bytes, int], object] = {}

    def _sub_size(self) -> int:
        return self.spec.preset.sync_committee_size // self.spec.sync_committee_subnet_count

    def insert_signature(self, slot: int, block_root: bytes, subcommittee: int,
                         position_in_subcommittee: int, signature: bytes) -> None:
        """Merge one already-verified committee member signature."""
        from ..consensus.signature_sets import _sig as cached_sig
        from ..crypto.bls import api as bls

        key = (int(slot), bytes(block_root), int(subcommittee))
        existing = self._pool.get(key)
        if existing is None:
            bits = [False] * self._sub_size()
            bits[position_in_subcommittee] = True
            self._pool[key] = self.types.SyncCommitteeContribution(
                slot=slot,
                beacon_block_root=bytes(block_root),
                subcommittee_index=subcommittee,
                aggregation_bits=bits,
                signature=bytes(signature),
            )
            return
        if existing.aggregation_bits[position_in_subcommittee]:
            return  # already aggregated
        # cached parses: G2 decompression dominates pool merges otherwise
        agg = bls.AggregateSignature.from_signature(cached_sig(bytes(existing.signature)))
        agg.add_assign(cached_sig(bytes(signature)))
        existing.aggregation_bits[position_in_subcommittee] = True
        existing.signature = agg.to_bytes()

    def insert_contribution(self, contribution) -> None:
        """Merge an already-verified (multi-bit) contribution if it has more
        participants than what we hold (best-wins, like the reference pool)."""
        key = (
            int(contribution.slot),
            bytes(contribution.beacon_block_root),
            int(contribution.subcommittee_index),
        )
        existing = self._pool.get(key)
        if existing is None or (
            sum(contribution.aggregation_bits) > sum(existing.aggregation_bits)
        ):
            self._pool[key] = contribution.copy()

    def get_contribution(self, slot: int, block_root: bytes, subcommittee: int):
        c = self._pool.get((int(slot), bytes(block_root), int(subcommittee)))
        return None if c is None else c.copy()

    def best_sync_aggregate(self, slot: int, block_root: bytes):
        """Combine per-subcommittee contributions into a block's
        ``SyncAggregate`` over ``block_root`` signed at ``slot``."""
        from ..consensus.signature_sets import _sig as cached_sig
        from ..crypto.bls import api as bls

        size = self.spec.preset.sync_committee_size
        bits = [False] * size
        agg = bls.AggregateSignature.infinity()
        sub_size = self._sub_size()
        found = False
        for sub in range(self.spec.sync_committee_subnet_count):
            c = self._pool.get((int(slot), bytes(block_root), sub))
            if c is None:
                continue
            found = True
            for i, b in enumerate(c.aggregation_bits):
                if b:
                    bits[sub * sub_size + i] = True
            agg.add_assign(cached_sig(bytes(c.signature)))
        return self.types.SyncAggregate(
            sync_committee_bits=bits,
            sync_committee_signature=agg.to_bytes() if found else b"\xc0" + b"\x00" * 95,
        )

    def prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.SLOT_RETENTION
        self._pool = {k: v for k, v in self._pool.items() if k[0] >= cutoff}


class AttestationCandidate:
    """A spec-checked, indexed attestation awaiting signature verification
    (the unit the gossip batch verifier coalesces).  ``state`` is the state
    the attestation was indexed against (needed to build the aggregate's
    extra signature sets without re-deriving committees)."""

    __slots__ = ("attestation", "indexed", "signature_set", "state")

    def __init__(self, attestation, indexed, signature_set, state=None):
        self.attestation = attestation
        self.indexed = indexed
        self.signature_set = signature_set
        self.state = state


class AggregateCandidate:
    """A spec-checked SignedAggregateAndProof awaiting signature verification.

    Carries the reference's THREE signature sets per aggregate
    (``attestation_verification/batch.rs:31-135``): selection proof, outer
    AggregateAndProof signature, inner indexed-attestation set."""

    __slots__ = ("signed_aggregate", "inner", "signature_sets")

    def __init__(self, signed_aggregate, inner: AttestationCandidate, signature_sets):
        self.signed_aggregate = signed_aggregate
        self.inner = inner
        self.signature_sets = signature_sets


class BeaconChain:
    def __init__(
        self,
        *,
        genesis_state,
        types,
        spec: ChainSpec,
        store: Optional[MemoryStore] = None,
        db: Optional[HotColdDB] = None,
        slot_clock: Optional[SlotClock] = None,
        execution_engine: Optional[MockExecutionEngine] = None,
        kzg=None,
        anchor_block=None,
    ):
        """``anchor_block``: checkpoint sync (weak subjectivity) — boot from a
        finalized (state, block) pair instead of genesis: ``genesis_state``
        is then the anchor block's post-state, the anchor root plays the
        genesis-root role in fork choice, and backfill later fills history
        behind it (reference ``client/src/builder.rs:341-528``)."""
        self.spec = spec
        self.types = types
        if db is not None:
            if store is not None:
                raise ChainError("pass either store= or db=, not both")
            db.types = types if db.types is None else db.types
            db.spec = spec if db.spec is None else db.spec
            self.db = db
            self.store = db.hot
        else:
            self.store = store if store is not None else MemoryStore()
            self.db = HotColdDB(hot=self.store, types=types, spec=spec)
        self.execution_engine = (
            execution_engine if execution_engine is not None else MockExecutionEngine()
        )
        if hasattr(self.execution_engine, "on_payload_attributes"):
            # SSE payload_attributes (reference events.rs topic): emit what
            # rides forkchoiceUpdated so external builders can prepare
            from . import events as ev

            def _emit_payload_attributes(fork, st, attributes):
                try:
                    proposer = h.get_beacon_proposer_index(st, self.spec)
                except Exception:
                    proposer = 0
                exec_header = getattr(
                    st, "latest_execution_payload_header", None)
                self.events.publish(ev.TOPIC_PAYLOAD_ATTRIBUTES, {
                    "version": fork,
                    "data": {
                        # beacon-API SsePayloadAttributes shape
                        "proposer_index": str(int(proposer)),
                        "proposal_slot": str(int(st.slot)),
                        "parent_block_number": str(
                            int(exec_header.block_number) if exec_header else 0),
                        "parent_block_root": "0x" + bytes(
                            st.latest_block_header.hash_tree_root()).hex(),
                        "parent_block_hash": "0x" + (
                            bytes(exec_header.block_hash).hex()
                            if exec_header else "00" * 32),
                        "payload_attributes": attributes,
                    },
                })

            self.execution_engine.on_payload_attributes = _emit_payload_attributes
        self.kzg = kzg
        self.genesis_state = genesis_state
        self.genesis_time = int(genesis_state.genesis_time)
        self.genesis_validators_root = bytes(genesis_state.genesis_validators_root)
        self.slot_clock = (
            slot_clock
            if slot_clock is not None
            else ManualSlotClock(self.genesis_time, spec.seconds_per_slot)
        )

        self.genesis_block_root = genesis_block_root_of(genesis_state)
        self.anchor_slot = int(genesis_state.slot)  # 0 for a genesis boot
        # Object caches over the store (the reference's snapshot/state caches).
        self._blocks: Dict[bytes, object] = {}
        self._states: Dict[bytes, object] = {}  # post-state by block root
        self._state_class: Dict[bytes, type] = {}
        # Payload-free persistence + on-read reconstruction (reference
        # beacon_block_streamer.rs): with store_payloads=False, post-merge
        # blocks hit the DB blinded and get_block rebuilds the payload from
        # the EL via engine_getPayloadBodiesByHash.  Must exist before the
        # anchor/genesis _store_block below.
        from .block_streamer import BeaconBlockStreamer

        self.store_payloads: bool = True
        self.block_streamer = BeaconBlockStreamer(self)
        if anchor_block is not None:
            anchor_root = anchor_block.message.hash_tree_root()
            if anchor_root != self.genesis_block_root:
                raise ChainError(
                    "anchor_block does not match the anchor state's latest header"
                )
            self._store_block(anchor_root, anchor_block, genesis_state)
        else:
            self._store_block(self.genesis_block_root, None, genesis_state)

        self.fork_choice = ForkChoice(
            spec=spec,
            genesis_block_root=self.genesis_block_root,
            genesis_state=genesis_state,
        )
        self.fork_choice.set_justified_state_provider(self.get_state)
        from ..op_pool import OperationPool

        self.head_root = self.genesis_block_root
        self.attestation_pool = NaiveAggregationPool()
        self.sync_contribution_pool = NaiveSyncContributionPool(types, spec)
        from .light_client import LightClientServerCache

        self.lc_cache = LightClientServerCache(types, spec)
        self.builder = None  # external MEV relay client (set by the builder)
        self.eth1_service = None  # deposit follower + eth1 voting (optional)
        # state-advance cache: (head_root, slot, advanced_state)
        self._advanced: Optional[Tuple[bytes, int, object]] = None
        self._advance_hits = 0
        # validator index -> fee recipient (reference proposer_prep_service /
        # prepare_beacon_proposer; consumed by payload production)
        self.proposer_preparations: Dict[int, bytes] = {}
        from .validator_monitor import ValidatorMonitor

        self.validator_monitor = ValidatorMonitor(spec)
        self.builder_pubkey = None  # operator-pinned relay identity (optional)
        from .attester_cache import EarlyAttesterCache

        self.early_attester_cache = EarlyAttesterCache()
        # Late-block proposer re-org config (reference chain_config.rs:6-10
        # defaults; set re_org_head_threshold to None to disable).
        self.re_org_head_threshold: Optional[int] = 20
        self.re_org_parent_threshold: int = 160
        self.re_org_max_epochs_since_finalization: int = 2
        self.re_org_cutoff_denominator: int = 12
        self.re_org_disallowed_offsets: tuple = ()
        # Import-time block arrival delays (root -> seconds into its slot),
        # consulted by the proposer re-org head_late gate
        # (beacon_chain.rs:4289-4290).  Bounded: pruned FIFO past 128 roots.
        self._block_delays: "OrderedDict[bytes, float]" = OrderedDict()
        self.op_pool = OperationPool()
        self.observed_block_roots: set = set()
        self._migrated_slot = 0
        self.events = EventBus()
        # Device circuit-breaker transitions (device_supervisor.py) publish
        # to this bus as `device_breaker` SSE events (weakly registered —
        # harness-built chains drop out on GC).
        from .. import device_supervisor

        device_supervisor.register_event_bus(self.events)
        # Synchronous import-completion hooks (root) — the router's
        # reprocess queue releases parked unknown-head attestations here
        # the moment the block they vote for lands (any import path:
        # gossip, range sync, parent chase).
        self.block_imported_hooks: list = []
        self._last_finalized_epoch = 0
        from .observed import ObservedCaches

        self.observed = ObservedCaches()
        from .da import DataAvailabilityChecker

        self.da_checker = DataAvailabilityChecker(
            spec=spec, types=types, kzg=kzg,
            header_verifier=self.verify_block_header_signature,
            slot_provider=self.current_slot,
        )
        self._blob_sidecars: Dict[bytes, list] = {}
        from .pre_finalization_cache import PreFinalizationBlockCache

        self.pre_finalization_cache = PreFinalizationBlockCache()
        from .graffiti_calculator import GraffitiCalculator

        self.graffiti_calculator = GraffitiCalculator(
            execution_engine=self.execution_engine
        )
        from .otb_verification import OtbStore

        self.otb_store = OtbStore(self.db)

    # ------------------------------------------------------------- storage

    def _store_block(self, block_root: bytes, signed_block, post_state) -> None:
        if signed_block is not None:
            self._blocks[block_root] = signed_block
            if not self.store_payloads and hasattr(
                signed_block.message.body, "execution_payload"
            ):
                from .block_streamer import blind_signed_block

                self.db.put_blinded_block(
                    block_root, blind_signed_block(signed_block, self.types)
                )
            else:
                self.db.put_block(block_root, signed_block)
            # The post-state root was verified against the block's claim in
            # state_transition — reuse it instead of re-merkleizing.
            state_root = bytes(signed_block.message.state_root)
        else:
            state_root = post_state.hash_tree_root()  # genesis
        self._states[block_root] = post_state
        self.db.put_state(state_root, post_state, block_root)

    def get_block(self, block_root: bytes):
        """FULL block by root — object cache first, store fallback (the
        reference can always reach the store when its block cache misses),
        then the early-attester cache for a block that is verified but not
        yet written (peers may request it over RPC the moment it hits
        gossip).  A blinded store hit is reconstructed through the block
        streamer (payload from the EL), so every serving path — HTTP blocks,
        BlocksByRange/Root — hands out full blocks transparently."""
        block = self._blocks.get(block_root)
        if block is None:
            block = self.db.get_block(block_root)
            if block is not None:
                from .block_streamer import is_blinded

                if is_blinded(block):
                    block = self.block_streamer.reconstruct_one(block)
        if block is None:
            block = self.early_attester_cache.get_block(block_root)
        return block

    def _raw_block(self, block_root: bytes):
        """The stored form without payload reconstruction (may be blinded):
        memory cache -> db -> early-attester cache.  The EL-free invariant
        every slot/metadata lookup depends on lives HERE only."""
        block = self._blocks.get(block_root) or self.db.get_block(block_root)
        if block is None:
            block = self.early_attester_cache.get_block(block_root)
        return block

    def get_blocks(self, block_roots) -> list:
        """FULL blocks for many roots with ONE batched EL round trip for
        every blinded store hit (the reference's beacon_block_streamer range
        path) — N-block BlocksByRange must not cost N
        engine_getPayloadBodiesByHash calls."""
        return self.block_streamer.reconstruct(
            [self._raw_block(root) for root in block_roots]
        )

    def get_blinded_block(self, block_root: bytes):
        """The block in blinded form (payload header), reading the blinded
        store representation directly when present."""
        from .block_streamer import blind_signed_block, is_blinded

        block = self._raw_block(block_root)
        if block is None or is_blinded(block):
            return block
        if not hasattr(block.message.body, "execution_payload"):
            return block  # pre-merge: blinded == full
        return blind_signed_block(block, self.types)

    def get_blobs(self, block_root: bytes) -> list:
        """Blob sidecars stored at import or backfill (memory first, store
        fallback — the blob_sidecars API's and blob RPC's source)."""
        mem = self._blob_sidecars.get(block_root)
        if mem is not None:
            return list(mem)
        return self.db.get_blobs(block_root)

    def store_backfilled_blobs(self, signed_block, sidecars) -> None:
        """Persist sidecars for a hash-chain-verified BACKFILLED block.

        Full verification, not just commitment equality: exact index
        coverage, commitment match against the verified block, and the KZG
        batch proof (a copied commitment over garbage blob bytes must not
        be served).  Raises ``BlockError`` on any failure."""
        commitments = list(
            getattr(signed_block.message.body, "blob_kzg_commitments", []) or []
        )
        block_root = signed_block.message.hash_tree_root()
        got = sorted(sidecars, key=lambda s: int(s.index))
        if [int(s.index) for s in got] != list(range(len(commitments))):
            raise BlockError("backfilled sidecars do not cover indices exactly")
        for sc in got:
            if bytes(sc.kzg_commitment) != bytes(commitments[int(sc.index)]):
                raise BlockError("backfilled sidecar commitment mismatch")
        if self.kzg is None:
            raise BlockError("no KZG engine: cannot verify backfilled blobs")
        if not self.kzg.verify_blob_kzg_proof_batch(
            [bytes(sc.blob) for sc in got],
            [bytes(sc.kzg_commitment) for sc in got],
            [bytes(sc.kzg_proof) for sc in got],
        ):
            raise BlockError("backfilled blob KZG verification failed")
        self.db.put_blobs(block_root, got)

    def get_state(self, block_root: bytes):
        """Post-state for ``block_root`` — object cache first, then the hot
        store by the block's claimed state root, then cold-store replay
        (reference snapshot-cache-miss path, ``beacon_chain.rs:378-504``:
        a cache miss is a slow path, never an error)."""
        state = self._states.get(block_root)
        if state is not None:
            return state
        if block_root == self.genesis_block_root:
            state = self.genesis_state
        else:
            block = self.get_block(block_root)
            if block is None:
                return None
            state = self.db.get_hot_state(bytes(block.message.state_root))
            if state is None:
                # Finalized history: rebuild from the nearest restore point.
                # Only canonical-finalized roots exist cold-side, so verify
                # the block root at that slot matches before trusting it.
                slot = int(block.message.slot)
                if self.db.cold_block_root_at_slot(slot) == block_root:
                    state = self.db.load_cold_state_by_slot(slot)
        if state is not None:
            self._states[block_root] = state
        return state

    @property
    def head_state(self):
        state = self.get_state(self.head_root)
        if state is None:
            raise ChainError(
                f"head state for {self.head_root.hex()[:16]} missing from cache and store"
            )
        return state

    def head_slot(self) -> int:
        """Slot of the current head block (the notifier/monitoring figure)."""
        return self._blocks_slot(self.head_root)

    def current_slot(self) -> int:
        now = self.slot_clock.now()
        return now if now is not None else 0

    # ------------------------------------------------------- block import

    def process_block(self, signed_block, block_delay_seconds: Optional[float] = None) -> bytes:
        """Full import pipeline (reference ``beacon_chain.rs:3035``
        ``process_block`` + ``:3362 import_block``): state catch-up, bulk
        signature verification, state-root check, payload notify, fork choice,
        persistence, head recompute."""
        with tracing.span(
            "block_import", hist=metrics.BLOCK_IMPORT_SECONDS,
            slot=int(signed_block.message.slot),
        ):
            return self._process_block_inner(signed_block, block_delay_seconds)

    def process_block_with_blobs(self, signed_block, sidecars,
                                 block_delay_seconds: Optional[float] = None) -> bytes:
        """Import a block together with its blob sidecars (RPC/API path)."""
        with tracing.span(
            "block_import", hist=metrics.BLOCK_IMPORT_SECONDS,
            slot=int(signed_block.message.slot),
        ):
            return self._process_block_inner(
                signed_block, block_delay_seconds, sidecars=sidecars
            )

    def _process_block_inner(self, signed_block, block_delay_seconds, sidecars=None):
        t_import = time.perf_counter()
        block = signed_block.message
        block_root = block.hash_tree_root()
        tracing.annotate(root="0x" + block_root.hex()[:16])
        # Key the whole trace by this import's slot, whatever span is the
        # root (work:gossip_block, http_request, or block_import itself).
        tracing.annotate_trace(slot=int(block.slot))
        if block_root in self._blocks or block_root == self.genesis_block_root:
            return block_root  # duplicate import is a no-op
        current_slot = self.current_slot()
        if int(block.slot) > current_slot:
            raise BlockError(f"block from future slot {block.slot} (now {current_slot})")
        parent_root = bytes(block.parent_root)
        parent_state = self.get_state(parent_root)
        if parent_state is None:
            raise BlockError(f"unknown parent {parent_root.hex()[:16]}")

        # Deneb data-availability gate (data_availability_checker.rs): a
        # block with commitments imports only when every blob is verified.
        # Runs AFTER the slot/parent sanity checks so junk blocks can never
        # park in the pending store (DoS surface).
        if getattr(block.body, "blob_kzg_commitments", None):
            from .da import BlobError

            try:
                with tracing.span("da_check", hist=metrics.BLOCK_DA_CHECK_SECONDS):
                    status, result = self.da_checker.check_availability(
                        signed_block, sidecars=sidecars
                    )
            except BlobError as e:
                raise BlockError(f"blob verification failed: {e}") from e
            if status != "available":
                # Only proposer-authenticated blocks may park in the capped
                # pending store — unsigned junk must not be able to evict an
                # honest block waiting for its blobs.
                header = self.types.SignedBeaconBlockHeader(
                    message=self.types.BeaconBlockHeader(
                        slot=block.slot,
                        proposer_index=block.proposer_index,
                        parent_root=block.parent_root,
                        state_root=block.state_root,
                        body_root=block.body.hash_tree_root(),
                    ),
                    signature=signed_block.signature,
                )
                if self.verify_block_header_signature(header):
                    self.da_checker.put_pending_block(signed_block)
                raise BlockError(f"pending availability: missing blobs {result}")
            blob_sidecars = result
        else:
            blob_sidecars = []

        state = parent_state.copy()
        try:
            with tracing.span(
                "state_transition", hist=metrics.BLOCK_STATE_TRANSITION_SECONDS
            ):
                state = state_transition(
                    state,
                    signed_block,
                    self.types,
                    self.spec,
                    strategy=BlockSignatureStrategy.VERIFY_BULK,
                    validate_result=True,
                    payload_verifier=self._payload_verifier_for(signed_block),
                )
        except (BlockProcessingError, ValueError) as e:
            raise BlockError(f"state transition failed: {e}") from e

        if block_delay_seconds is None:
            # Delay relative to the BLOCK'S OWN slot start (reference
            # block_times_cache semantics) — a slot-N block arriving during
            # slot N+1 is very late, not "0.5 s into the current slot".
            now = self.slot_clock._seconds()
            start = self.slot_clock.start_of(int(block.slot))
            block_delay_seconds = max(0.0, now - start)
        self._block_delays[block_root] = float(block_delay_seconds)
        while len(self._block_delays) > 128:
            self._block_delays.popitem(last=False)
        metrics.BLOCK_ARRIVAL_DELAY_SECONDS.observe(float(block_delay_seconds))
        tracing.annotate(arrival_delay_s=round(float(block_delay_seconds), 3))
        if hasattr(block.body, "execution_payload"):
            ph = bytes(block.body.execution_payload.block_hash)
            optimistic = getattr(self.execution_engine, "optimistic_hashes", None)
            payload_status = (
                ExecutionStatus.OPTIMISTIC
                if optimistic is not None and ph in optimistic
                else ExecutionStatus.VALID
            )
            if payload_status == ExecutionStatus.OPTIMISTIC:
                from ..consensus.per_block import is_merge_transition_complete

                if not is_merge_transition_complete(parent_state) and any(ph):
                    # The MERGE TRANSITION block went in unverified: its PoW
                    # parent must be TTD-checked once the EL can answer
                    # (otb_verification_service.rs).
                    self.otb_store.register(block_root, int(block.slot))
        else:
            payload_status = ExecutionStatus.IRRELEVANT
        with tracing.span("fork_choice", hist=metrics.BLOCK_FORK_CHOICE_SECONDS):
            self.fork_choice.on_block(
                current_slot=current_slot,
                block=block,
                block_root=block_root,
                state=state,
                payload_verification_status=payload_status,
                block_delay_seconds=block_delay_seconds,
            )
        # The block is fully verified: attestations to it can be produced
        # NOW, before the store write / head recompute below (reference
        # early_attester_cache.rs — the 4 s attestation deadline must not
        # wait on the database).
        self.early_attester_cache.add_head_block(
            block_root, signed_block, state, self.types, self.spec,
            blobs=blob_sidecars,
        )
        with tracing.span("store_write", hist=metrics.BLOCK_STORE_WRITE_SECONDS):
            from .. import fault_injection

            fault_injection.check("store.write")
            self._store_block(block_root, signed_block, state)
        self.observed_block_roots.add(block_root)
        self.pre_finalization_cache.block_processed(block_root)
        self._update_light_client_cache(signed_block, parent_root, parent_state)
        if blob_sidecars:
            self._blob_sidecars[block_root] = list(blob_sidecars)
            for sc in blob_sidecars:
                self.events.publish("blob_sidecar", {
                    "block_root": "0x" + block_root.hex(),
                    "index": str(int(sc.index)),
                    "slot": str(int(block.slot)),
                    "kzg_commitment": "0x" + bytes(sc.kzg_commitment).hex(),
                })

        # Feed the block's attestations to fork choice (reference
        # ``import_block`` → on_attestation(is_from_block=true)).
        for att in block.body.attestations:
            try:
                indexed = h.get_indexed_attestation(state, att, self.types, self.spec)
                # Head/target correctness vs the including chain (reference
                # validator_monitor.rs attestation scoring); None when the
                # root is not yet derivable from this state's history.
                head_hit = target_hit = None
                try:
                    head_hit = bytes(att.data.beacon_block_root) == bytes(
                        h.get_block_root_at_slot(state, int(att.data.slot), self.spec)
                    )
                except Exception:
                    pass
                try:
                    target_hit = bytes(att.data.target.root) == bytes(
                        h.get_block_root(state, int(att.data.target.epoch), self.spec)
                    )
                except Exception:
                    pass
                self.validator_monitor.on_attestation_included(
                    int(att.data.target.epoch), indexed.attesting_indices,
                    head_hit=head_hit, target_hit=target_hit,
                    inclusion_distance=int(block.slot) - int(att.data.slot),
                )
                for idx in indexed.attesting_indices:
                    self.observed.block_attesters.observe(
                        int(att.data.target.epoch), int(idx)
                    )
                self.fork_choice.on_attestation(
                    current_slot=current_slot,
                    attestation_slot=int(att.data.slot),
                    attesting_indices=list(indexed.attesting_indices),
                    beacon_block_root=bytes(att.data.beacon_block_root),
                    target_epoch=int(att.data.target.epoch),
                    target_root=bytes(att.data.target.root),
                    is_from_block=True,
                )
            except InvalidAttestation:
                continue  # attestations for unknown forks don't block import
        # Block-included slashings convict equivocators: mask their
        # fork-choice weight even when the slashing never crossed our gossip
        # path (reference import_block -> on_attester_slashing per included
        # slashing).  state.validators[i].slashed already flipped in the
        # state transition above.
        for slashing in getattr(block.body, "attester_slashings", ()):
            self.fork_choice.on_attester_slashing(
                attester_slashing_indices(slashing))
        self.validator_monitor.on_block_imported(
            int(block.slot), int(block.proposer_index)
        )
        if (self.validator_monitor.monitored
                and hasattr(block.body, "sync_aggregate")):
            try:
                committee = self._sync_committee_member_indices(state)
                bits = block.body.sync_aggregate.sync_committee_bits
                participating = {v for i, v in enumerate(committee) if bits[i]}
                # per-VALIDATOR judgment: a member repeating across
                # positions participates if ANY of its bits is set — a
                # partially-aggregated contribution is not a miss
                missing = set(committee) - participating
                self.validator_monitor.on_sync_aggregate(
                    int(block.slot), participating, missing)
            except Exception:
                pass  # monitoring must never block an import

        self.recompute_head()
        if self.head_root == block_root:
            # Score strictly against the CANONICAL chain: only the block
            # that fork choice just made head may consume simulated votes
            # (a side-fork post-state would grade them against the wrong
            # branch and destroy them).
            self.validator_monitor.score_simulated_attestations(
                state, self.spec, h
            )
        if int(block.slot) == current_slot:
            # Re-vote the simulator for this slot now its block is here:
            # the reference fires at +1/3 INTO the slot (after a timely
            # block); the slot-start vote stands only for empty slots.
            self.simulate_attestation()
        self.events.block(slot=int(block.slot), block_root=block_root)
        # Import-completion delay against the block's OWN slot start (the
        # reference's beacon_block_delay_imported figure — arrival delay
        # plus everything the pipeline added on top).
        metrics.BLOCK_IMPORTED_DELAY_SECONDS.observe(max(
            0.0,
            self.slot_clock._seconds() - self.slot_clock.start_of(int(block.slot)),
        ))
        # Reference beacon_chain.rs logs every import with slot/root/delay
        # (the notifier and Siren both read these).
        log.info(
            "block imported",
            slot=int(block.slot),
            root="0x" + block_root.hex()[:16],
            delay_s=round(float(block_delay_seconds), 3),
            import_s=round(time.perf_counter() - t_import, 3),
            attestations=len(block.body.attestations),
        )
        for hook in list(self.block_imported_hooks):
            try:
                hook(block_root)
            except Exception:
                pass  # a subscriber must never fail an import
        return block_root

    def verify_block_header_signature(self, signed_header) -> bool:
        """Proposer signature on a detached ``SignedBeaconBlockHeader`` (the
        blob-sidecar gossip rule — a forged header must not enter the DA
        cache or be re-forwarded)."""
        from ..consensus import signature_sets as sets
        from ..crypto.bls import api as bls
        from ..types.spec import DOMAIN_BEACON_PROPOSER

        header = signed_header.message
        state = self.get_state(bytes(header.parent_root)) or self.head_state
        proposer = int(header.proposer_index)
        if proposer >= len(state.validators):
            return False
        epoch = int(header.slot) // self.spec.slots_per_epoch
        # Domain from the fork AT THE HEADER'S EPOCH (not the parent state's
        # fork object) — the parent of the first post-fork block is still
        # pre-fork, but the proposer signed with the new version.
        fork_version = self.spec.fork_version_for(self.spec.fork_name_at_epoch(epoch))
        domain = h.compute_domain(
            DOMAIN_BEACON_PROPOSER, fork_version, self.genesis_validators_root
        )
        root = h.compute_signing_root(header.hash_tree_root(), domain)
        try:
            pk = sets.pubkey_cache(bytes(state.validators[proposer].pubkey))
            s = bls.SignatureSet.single_pubkey(
                bls.Signature.from_bytes(bytes(signed_header.signature)), pk, root
            )
            # through the active backend (fake/host/jax), like every other
            # chain signature check
            return bls.verify_signature_sets([s])
        except (bls.BlsError, ValueError):
            return False

    def _payload_verifier_for(self, signed_block):
        """The payload_verifier closure for one block's import.  A real
        ``ExecutionLayer`` needs the deneb extras (blob versioned hashes +
        parent beacon block root, engine_newPayloadV3); the in-proc mock's
        plain ``notify_new_payload(payload)`` is used as-is."""
        el = self.execution_engine
        if not hasattr(el, "notify_forkchoice_updated"):
            return el.notify_new_payload  # in-proc mock
        body = signed_block.message.body
        commitments = list(getattr(body, "blob_kzg_commitments", []) or [])
        if not commitments and type(signed_block.message).fork_name not in (
            "deneb", "electra",
        ):
            return el.notify_new_payload
        from ..execution_layer.engine_api import kzg_commitment_to_versioned_hash

        versioned = [kzg_commitment_to_versioned_hash(c) for c in commitments]
        parent_root = bytes(signed_block.message.parent_root)
        fork = type(signed_block.message).fork_name
        requests = getattr(body, "execution_requests", None)
        return lambda payload: el.notify_new_payload(
            payload,
            versioned_hashes=versioned,
            parent_beacon_block_root=parent_root,
            execution_requests=requests,
            fork=fork,
        )

    # ------------------------------------------------- attestation import

    def preverify_attestation(self, attestation) -> "AttestationCandidate":
        """Spec checks + committee indexing; returns a candidate carrying the
        signature set WITHOUT verifying it — the gossip batch coalescer
        verifies many candidates in one device program
        (reference ``attestation_verification.rs`` split into
        ``verify_*_for_gossip`` parts 1/2 around the batch seam)."""
        from ..consensus import signature_sets as sets
        from ..crypto.bls import api as bls

        data = attestation.data
        head_root = bytes(data.beacon_block_root)
        state = self.get_state(head_root)
        if state is None:
            raise AttestationError("attestation references unknown head block")
        base = state
        if h.compute_epoch_at_slot(int(data.slot), self.spec) > h.get_current_epoch(
            base, self.spec
        ):
            base = base.copy()
            process_slots(
                base,
                h.compute_start_slot_at_epoch(
                    h.compute_epoch_at_slot(int(data.slot), self.spec), self.spec
                ),
                self.types,
                self.spec,
            )
        try:
            indexed = h.get_indexed_attestation(base, attestation, self.types, self.spec)
        except Exception as e:
            raise AttestationError(f"cannot index attestation: {e}") from e
        try:
            sig_set = sets.indexed_attestation_signature_set(base, indexed, self.spec)
        except bls.BlsError as e:
            raise AttestationError(f"malformed attestation signature: {e}") from e
        return AttestationCandidate(attestation, indexed, sig_set, state=base)

    def preverify_aggregate(self, signed_aggregate) -> "AggregateCandidate":
        """Spec checks for a ``SignedAggregateAndProof`` (reference
        ``verify_aggregated_attestation_for_gossip``,
        ``attestation_verification.rs``): the aggregator must be a member of
        the attestation's committee AND pass the spec ``is_aggregator``
        selection gate, and THREE signature sets are built — selection proof,
        outer AggregateAndProof signature, inner indexed attestation — all
        left unverified for the batch coalescer.  Skipping any of these lets
        a peer mint wraps around public aggregates to censor honest
        aggregators (round-2 advisor finding)."""
        import hashlib

        from ..consensus import signature_sets as sets
        from ..crypto.bls import api as bls

        msg = signed_aggregate.message
        attestation = msg.aggregate
        inner = self.preverify_attestation(attestation)
        base = inner.state
        data = attestation.data
        slot = int(data.slot)
        aggregator_index = int(msg.aggregator_index)
        if aggregator_index >= len(base.validators):
            raise AttestationError("aggregator index out of range")
        if hasattr(attestation, "committee_bits"):
            committee_indices = h.get_committee_indices(attestation.committee_bits)
            if len(committee_indices) != 1:
                raise AttestationError("electra aggregate must set exactly one committee bit")
            committee_index = committee_indices[0]
        else:
            committee_index = int(data.index)
        committee = h.get_beacon_committee(base, slot, committee_index, self.spec)
        if aggregator_index not in {int(i) for i in committee}:
            raise AttestationError("aggregator is not in the attestation committee")
        modulo = max(1, len(committee) // self.spec.target_aggregators_per_committee)
        digest = hashlib.sha256(bytes(msg.selection_proof)).digest()
        if int.from_bytes(digest[:8], "little") % modulo != 0:
            raise AttestationError("validator is not a selected aggregator for this slot")
        try:
            selection_set = sets.selection_proof_signature_set(
                base, aggregator_index, slot, msg.selection_proof, self.spec
            )
            outer_set = sets.aggregate_and_proof_signature_set(
                base, signed_aggregate, self.spec
            )
        except bls.BlsError as e:
            raise AttestationError(f"malformed aggregate signature: {e}") from e
        return AggregateCandidate(
            signed_aggregate, inner, [selection_set, outer_set, inner.signature_set]
        )

    # ------------------------------------------------------- light client

    def _update_light_client_cache(self, signed_block, parent_root: bytes,
                                   parent_state) -> None:
        """Produce LC objects from an imported block (reference
        ``light_client_server_cache.rs`` recompute_and_cache_updates)."""
        from .light_client import block_to_lc_header  # noqa: F401 (cycle guard)

        parent_block = self.get_block(parent_root)
        if parent_block is None:
            if parent_root != self.genesis_block_root:
                return
            header = self.genesis_state.latest_block_header.copy()
            header.state_root = self.genesis_state.hash_tree_root()
            parent_block = header
        f_root = bytes(parent_state.finalized_checkpoint.root)
        finalized_block = self.get_block(f_root) if any(f_root) else None
        try:
            self.lc_cache.on_block_imported(
                block=signed_block,
                parent_block=parent_block,
                parent_state=parent_state,
                finalized_block=finalized_block,
            )
        except Exception:
            pass  # LC production must never break block import

    def produce_light_client_bootstrap(self, block_root: bytes):
        """Bootstrap for a (finalized) block root, built on demand."""
        block = self.get_block(block_root)
        state = self.get_state(block_root)
        if block is None or state is None:
            return None
        return self.lc_cache.produce_bootstrap(state, block)

    # ------------------------------------------------ sync committee duty

    def _sync_committee_for_slot(self, state, slot: int):
        """The committee actually signing at ``slot``: at a sync-committee
        period boundary (or when the head state lags the wall clock into the
        next period) the message's period may be the state's NEXT period —
        checking ``current_sync_committee`` unconditionally rejects valid
        messages from the new committee (reference
        ``sync_committee_verification.rs`` resolves the duty-epoch
        committee the same way)."""
        from ..consensus.helpers import compute_sync_committee_period

        # Duty period of slot+1, not slot: sync-committee messages at the
        # LAST slot of a period are signed by the NEXT committee (reference
        # ``sync_committee_at_next_slot``, beacon_chain.rs:1288).
        msg_period = compute_sync_committee_period(
            (int(slot) + 1) // self.spec.slots_per_epoch, self.spec
        )
        state_period = compute_sync_committee_period(
            int(state.slot) // self.spec.slots_per_epoch, self.spec
        )
        if msg_period == state_period + 1:
            return state.next_sync_committee
        return state.current_sync_committee

    def _expected_proposer(self, slot: int) -> Optional[int]:
        """The expected proposer at ``slot``, from an epoch-level cache
        (reference ``beacon_proposer_cache.rs``): the whole epoch's mapping
        is computed once while the head state can derive it, and survives
        the head advancing into the next epoch — so the last slots of an
        epoch stay checkable.  None when the shuffling is underivable
        (e.g. a long outage with the head frozen epochs behind); a deep
        reorg across the epoch boundary can stale one epoch's cache
        (monitoring-grade accuracy, not consensus)."""
        epoch = slot // self.spec.slots_per_epoch
        cache = getattr(self, "_proposer_epoch_cache", None)
        if cache is not None and cache[0] == epoch:
            return cache[1].get(slot)
        head_epoch = int(self.head_state.slot) // self.spec.slots_per_epoch
        if head_epoch != epoch:
            return None
        start = epoch * self.spec.slots_per_epoch
        mapping = {}
        for s in range(start, start + self.spec.slots_per_epoch):
            try:
                mapping[s] = h.get_beacon_proposer_index(
                    self.head_state, self.spec, slot=s)
            except Exception:
                continue
        self._proposer_epoch_cache = (epoch, mapping)
        return mapping.get(slot)

    def _sync_committee_member_indices(self, state) -> List[int]:
        """Validator indices of the CURRENT sync committee, position-aligned
        with its pubkeys (cached per sync period — the pubkey scan is
        O(validators) and the committee is stable for a whole period)."""
        period = (
            h.get_current_epoch(state, self.spec)
            // self.spec.preset.epochs_per_sync_committee_period
        )
        cached = getattr(self, "_sync_indices_cache", None)
        if cached is not None and cached[0] == period:
            return cached[1]
        by_pubkey = {
            bytes(v.pubkey): i for i, v in enumerate(state.validators)
        }
        indices = [
            by_pubkey.get(bytes(pk), -1)
            for pk in state.current_sync_committee.pubkeys
        ]
        self._sync_indices_cache = (period, indices)
        return indices

    def _sync_committee_positions(self, state, validator_index: int,
                                  slot: int) -> List[int]:
        committee = self._sync_committee_for_slot(state, slot)
        pk = bytes(state.validators[validator_index].pubkey)
        return [
            i for i, p in enumerate(committee.pubkeys)
            if bytes(p) == pk
        ]

    def _preverify_sync_message(self, msg, state):
        """Spec checks + signature-set construction for one message; the
        batch entry point verifies many sets in ONE backend call."""
        from ..consensus import signature_sets as sets

        current_slot = self.current_slot()
        if not (current_slot - 1 <= int(msg.slot) <= current_slot + 1):
            # spec gossip rule: the message slot must be current (±1 here for
            # clock skew); without this, validly-signed far-future messages
            # would pool forever (prune keeps future keys)
            raise AttestationError(
                f"sync message slot {msg.slot} outside the current-slot window"
            )
        vidx = int(msg.validator_index)
        if vidx >= len(state.validators):
            raise AttestationError("sync message validator index out of range")
        positions = self._sync_committee_positions(state, vidx, slot=int(msg.slot))
        if not positions:
            raise AttestationError("validator is not in the current sync committee")
        sig_set = sets.sync_committee_message_set(
            state, vidx, bytes(msg.beacon_block_root), int(msg.slot),
            msg.signature, self.spec,
        )
        return positions, sig_set

    def _pool_sync_message(self, msg, positions) -> None:
        sub_size = self.sync_contribution_pool._sub_size()
        for pos in positions:
            self.sync_contribution_pool.insert_signature(
                int(msg.slot), bytes(msg.beacon_block_root),
                pos // sub_size, pos % sub_size, bytes(msg.signature),
            )

    def process_sync_committee_message(self, msg) -> None:
        """Verify and pool one ``SyncCommitteeMessage`` (reference
        ``sync_committee_verification.rs`` gossip checks: committee
        membership + signature over the block root)."""
        from ..crypto.bls import api as bls

        positions, sig_set = self._preverify_sync_message(msg, self.head_state)
        from .. import device_pipeline

        with device_pipeline.work_context("sync_committee"):
            ok = bls.verify_signature_sets([sig_set])
        if not ok:
            raise AttestationError("bad sync committee message signature")
        self._pool_sync_message(msg, positions)

    def process_sync_committee_messages(self, messages) -> List[Optional[str]]:
        """Batch path (the POST pool/sync_committees route): all signature
        sets verify in ONE backend call — the reference coalesces sync
        messages through the processor the same way as attestations; on a
        batch failure, fall back per item.  Returns one error string or
        None per message."""
        from ..crypto.bls import api as bls

        state = self.head_state
        prepared = []
        results: List[Optional[str]] = []
        for msg in messages:
            try:
                positions, sig_set = self._preverify_sync_message(msg, state)
                prepared.append((msg, positions, sig_set))
                results.append(None)
            except AttestationError as e:
                prepared.append(None)
                results.append(str(e))
        live = [p for p in prepared if p is not None]
        if not live:
            return results
        from .. import device_pipeline

        with device_pipeline.work_context("sync_committee"):
            batch_ok = bls.verify_signature_sets([p[2] for p in live])
        for i, p in enumerate(prepared):
            if p is None:
                continue
            msg, positions, sig_set = p
            ok = batch_ok or bls.verify_signature_sets([sig_set])
            if not ok:
                results[i] = "bad sync committee message signature"
                continue
            self._pool_sync_message(msg, positions)
        return results

    def _preverify_signed_contribution(self, signed_contribution):
        """Spec checks for a ``SignedContributionAndProof`` — the full gossip
        rule set (reference ``verify_sync_committee_contribution``): the
        aggregator must be in the contribution's subcommittee AND pass the
        sync-aggregator selection gate; THREE signature sets (selection
        proof, outer signature, contribution participants) are returned
        unverified for the batch entry points."""
        import hashlib

        from ..consensus import signature_sets as sets
        from ..crypto.bls import api as bls
        from ..types.spec import DOMAIN_SYNC_COMMITTEE

        state = self.head_state
        msg = signed_contribution.message
        contribution = msg.contribution
        aggregator = int(msg.aggregator_index)
        slot = int(contribution.slot)
        current_slot = self.current_slot()
        if not (current_slot - 1 <= slot <= current_slot + 1):
            raise AttestationError(
                f"contribution slot {slot} outside the current-slot window"
            )
        sub = int(contribution.subcommittee_index)
        if sub >= self.spec.sync_committee_subnet_count:
            raise AttestationError("subcommittee index out of range")
        if aggregator >= len(state.validators):
            raise AttestationError("aggregator index out of range")
        sub_size = self.sync_contribution_pool._sub_size()
        positions = self._sync_committee_positions(state, aggregator, slot=slot)
        if not any(p // sub_size == sub for p in positions):
            raise AttestationError("aggregator is not in the contribution's subcommittee")
        modulo = max(1, sub_size // self.spec.target_aggregators_per_sync_subcommittee)
        digest = hashlib.sha256(bytes(msg.selection_proof)).digest()
        if int.from_bytes(digest[:8], "little") % modulo != 0:
            raise AttestationError("validator is not a selected sync aggregator")

        committee = self._sync_committee_for_slot(state, slot)
        participants = [
            sets.pubkey_cache(bytes(committee.pubkeys[sub * sub_size + i]))
            for i, bit in enumerate(contribution.aggregation_bits)
            if bit
        ]
        if not participants:
            raise AttestationError("empty sync contribution")
        epoch = slot // self.spec.slots_per_epoch
        domain = h.get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch, self.spec)
        signing_root = h.compute_signing_root(
            bytes(contribution.beacon_block_root), domain
        )
        try:
            sig_sets = [
                sets.sync_selection_proof_signature_set(
                    state, aggregator, slot, sub, msg.selection_proof,
                    self.types, self.spec,
                ),
                sets.contribution_and_proof_signature_set(
                    state, signed_contribution, self.spec
                ),
                bls.SignatureSet(
                    sets._sig(bytes(contribution.signature)),
                    signing_root, participants,
                ),
            ]
        except bls.BlsError as e:
            raise AttestationError(f"malformed contribution signature: {e}") from e
        return contribution, sig_sets

    def process_signed_contribution(self, signed_contribution) -> None:
        from ..crypto.bls import api as bls

        contribution, sig_sets = self._preverify_signed_contribution(signed_contribution)
        from .. import device_pipeline

        with device_pipeline.work_context("sync_committee"):
            ok = bls.verify_signature_sets(sig_sets)
        if not ok:
            raise AttestationError("bad sync contribution signature(s)")
        self.sync_contribution_pool.insert_contribution(contribution)

    # ------------------------------------------------- pool-operation gossip
    #
    # Reference gossip_methods.rs process_gossip_{voluntary_exit,
    # proposer_slashing, attester_slashing, bls_to_execution_change}:
    # dedup via the observed cache (IGNORE — return False, no forward),
    # verify signatures through the BACKEND batch seam and apply on a
    # head-state scratch (REJECT — raise ChainError, penalize), then pool.
    # One table-driven body: the dedup key, signature-set builder,
    # processor, and pool insert are the only per-kind parts — and the
    # observe-after-verify discipline (an invalid op must never censor the
    # validator's real one, observed_operations.rs) is enforced ONCE.

    def _on_gossip_op(self, kind: str, op, key, sets_fn, process_fn,
                      insert_fn, what: str, scratch=None) -> bool:
        from ..crypto.bls import api as bls

        if self.observed.operations.is_known(kind, key):
            return False
        if scratch is None:
            scratch = self.head_state.copy()
        try:
            sig_sets = sets_fn(scratch)
        except Exception as e:
            raise ChainError(f"invalid {what}: {e}") from e
        if not bls.verify_signature_sets(list(sig_sets)):
            raise ChainError(f"invalid {what}: bad signature")
        try:
            process_fn(scratch)
        except Exception as e:
            raise ChainError(f"invalid {what}: {e}") from e
        self.observed.operations.observe(kind, key)
        insert_fn()
        return True

    def on_gossip_voluntary_exit(self, exit_) -> bool:
        from ..consensus import signature_sets as sets
        from ..consensus.per_block import process_voluntary_exit
        from . import events as ev

        def insert():
            self.op_pool.insert_voluntary_exit(exit_)
            self.events.publish(ev.TOPIC_EXIT, ev.exit_event_payload(exit_))

        return self._on_gossip_op(
            "voluntary_exit", exit_, int(exit_.message.validator_index),
            lambda st: [sets.voluntary_exit_signature_set(st, exit_, self.spec)],
            lambda st: process_voluntary_exit(
                st, exit_, self.types, self.spec, verify=False),
            insert, "voluntary exit",
        )

    def on_gossip_proposer_slashing(self, slashing) -> bool:
        from ..consensus import signature_sets as sets
        from ..consensus.per_block import process_proposer_slashing

        return self._on_gossip_op(
            "proposer_slashing", slashing,
            int(slashing.signed_header_1.message.proposer_index),
            lambda st: sets.proposer_slashing_signature_sets(
                st, slashing, self.spec),
            lambda st: process_proposer_slashing(
                st, slashing, self.types, self.spec, False),
            lambda: self.op_pool.insert_proposer_slashing(slashing),
            "proposer slashing",
        )

    def on_gossip_attester_slashing(self, slashing) -> bool:
        from ..consensus import signature_sets as sets
        from ..consensus.per_block import process_attester_slashing

        def insert():
            self.op_pool.insert_attester_slashing(slashing)
            # A verified slashing is proof of equivocation: strip the
            # offenders' fork-choice weight NOW, without waiting for block
            # inclusion (reference beacon_chain.rs
            # verify_attester_slashing_for_gossip -> fc.on_attester_slashing).
            self.fork_choice.on_attester_slashing(
                attester_slashing_indices(slashing))

        return self._on_gossip_op(
            "attester_slashing", slashing, slashing.hash_tree_root(),
            lambda st: sets.attester_slashing_signature_sets(
                st, slashing, self.spec),
            lambda st: process_attester_slashing(
                st, slashing, self.types, self.spec, False),
            insert,
            "attester slashing",
        )

    def on_gossip_bls_change(self, signed_change, scratch=None) -> bool:
        """``scratch``: batch callers (the HTTP route) pass ONE shared
        scratch state so N changes cost one head-state copy, not N — and
        later items validate against the post-earlier-items state, the
        batch-application semantics."""
        from ..consensus import signature_sets as sets
        from ..consensus.per_block import process_bls_to_execution_change

        return self._on_gossip_op(
            "bls_to_execution_change", signed_change,
            int(signed_change.message.validator_index),
            lambda st: [sets.bls_to_execution_change_signature_set(
                st, signed_change, self.spec)],
            lambda st: process_bls_to_execution_change(
                st, signed_change, self.types, self.spec, False),
            lambda: self.op_pool.insert_bls_to_execution_change(signed_change),
            "bls change", scratch=scratch,
        )

    def process_signed_contributions(self, signed_contributions) -> List[Optional[str]]:
        """Batch path for POST contribution_and_proofs: every contribution's
        3 signature sets verify in ONE backend call, with the per-item
        fidelity fallback.  Returns one error string or None per item."""
        from ..crypto.bls import api as bls

        prepared = []
        results: List[Optional[str]] = []
        for signed in signed_contributions:
            try:
                prepared.append(self._preverify_signed_contribution(signed))
                results.append(None)
            except AttestationError as e:
                prepared.append(None)
                results.append(str(e))
        live = [p for p in prepared if p is not None]
        if not live:
            return results
        from .. import device_pipeline

        with device_pipeline.work_context("sync_committee"):
            batch_ok = bls.verify_signature_sets(
                [s for p in live for s in p[1]])
        for i, p in enumerate(prepared):
            if p is None:
                continue
            contribution, sig_sets = p
            ok = batch_ok or bls.verify_signature_sets(sig_sets)
            if not ok:
                results[i] = "bad sync contribution signature(s)"
                continue
            self.sync_contribution_pool.insert_contribution(contribution)
            # SSE contribution_and_proof (reference events.rs): verified
            # contributions stream to subscribers
            from . import events as ev

            self.events.publish(ev.TOPIC_CONTRIBUTION_AND_PROOF, {
                "slot": str(int(contribution.slot)),
                "beacon_block_root": "0x" + bytes(
                    contribution.beacon_block_root).hex(),
                "subcommittee_index": str(int(contribution.subcommittee_index)),
            })
        return results

    def apply_verified_aggregate(self, cand: "AggregateCandidate") -> None:
        """Apply a signature-verified aggregate candidate: fork choice + pool
        via the inner attestation, then record (aggregate root, aggregator)
        in the observed caches.  The ONE place the observe sequence lives —
        both the gossip router and the HTTP publish path call this."""
        self.apply_attestation(cand.inner)
        self.observed.aggregates.observe(
            int(cand.inner.attestation.data.slot),
            cand.inner.attestation.hash_tree_root(),
        )
        self.observed.aggregators.observe(
            int(cand.inner.attestation.data.target.epoch),
            int(cand.signed_aggregate.message.aggregator_index),
        )

    def process_aggregate(self, signed_aggregate) -> None:
        """Fully verify and apply one SignedAggregateAndProof (batch-of-one;
        the gossip router batches many candidates into one device program)."""
        from ..crypto.bls import api as bls

        cand = self.preverify_aggregate(signed_aggregate)
        from .. import device_pipeline

        with device_pipeline.work_context("gossip_aggregate"):
            ok = bls.verify_signature_sets(cand.signature_sets)
        if not ok:
            raise AttestationError("bad aggregate signature(s)")
        self.apply_verified_aggregate(cand)

    def apply_attestation(self, cand: "AttestationCandidate",
                          is_from_block: bool = False) -> None:
        """Apply an already-signature-verified candidate to fork choice and
        the aggregation pool, and record it in the observed caches."""
        data = cand.attestation.data
        if not is_from_block:
            # Slot-relative attestation delay (reference unagg/agg delay
            # histograms): how late after ITS slot's start this attestation
            # reached fork choice.  Block-carried attestations are
            # historical by construction and would only skew the figure.
            metrics.ATTESTATION_ARRIVAL_DELAY_SECONDS.observe(max(
                0.0,
                self.slot_clock._seconds()
                - self.slot_clock.start_of(int(data.slot)),
            ))
        self.fork_choice.on_attestation(
            current_slot=self.current_slot(),
            attestation_slot=int(data.slot),
            attesting_indices=list(cand.indexed.attesting_indices),
            beacon_block_root=bytes(data.beacon_block_root),
            target_epoch=int(data.target.epoch),
            target_root=bytes(data.target.root),
            is_from_block=is_from_block,
        )
        self.attestation_pool.insert(cand.attestation)
        # Observe only single-attester (unaggregated) items: recording every
        # index of an aggregate would later drop the validators' own subnet
        # attestations as "already seen" and starve downstream aggregation.
        if len(cand.indexed.attesting_indices) == 1:
            self.observed.attesters.observe(
                int(data.target.epoch), int(cand.indexed.attesting_indices[0])
            )

    def process_attestation(self, attestation, is_from_block: bool = False) -> None:
        """Verify an unaggregated/aggregated attestation (signature + spec
        checks against the target's state) and apply it to fork choice + the
        aggregation pool (reference ``attestation_verification.rs`` +
        ``beacon_chain.rs:2139``).  Batch-of-one through the active backend —
        the gossip router uses preverify/apply directly to verify whole
        drained batches in one device program."""
        from ..crypto.bls import api as bls

        cand = self.preverify_attestation(attestation)
        from .. import device_pipeline

        with device_pipeline.work_context("gossip_attestation"):
            ok = bls.verify_signature_sets([cand.signature_set])
        if not ok:
            raise AttestationError("bad attestation signature")
        self.apply_attestation(cand, is_from_block)

    # ----------------------------------------------------------- production

    def state_at_slot(self, slot: int, block_root: Optional[bytes] = None):
        """State at ``block_root`` (default: head) advanced with empty slots
        to ``slot`` — served from the pre-advanced cache when the
        state-advance timer already did the work (reference
        ``state_advance_timer.rs``: the expensive epoch-boundary advance
        happens at tail-of-slot, not on the production/attestation path)."""
        root = self.head_root if block_root is None else block_root
        cached = self._advanced
        if cached is not None and cached[0] == root and cached[1] == slot:
            self._advance_hits += 1
            # defensive copy: callers mutate production pre-states
            return cached[2].copy(), root
        state = self.get_state(root)
        if state is None:
            raise ChainError(f"unknown block root {root.hex()[:16]}")
        if int(state.slot) > slot:
            raise ChainError(f"state {state.slot} is past requested slot {slot}")
        if int(state.slot) == slot:
            return state, root
        state = state.copy()
        state = process_slots(state, slot, self.types, self.spec)
        return state, root

    def prepare_next_slot(self) -> bool:
        """Pre-advance the head state to the NEXT slot (the tail-of-slot
        job the reference's state_advance_timer runs): block production and
        attestation at the next slot then start from a cached state instead
        of paying the advance — the epoch-boundary case is the one that
        matters (full epoch processing).  Returns True when work was done."""
        next_slot = self.current_slot() + 1
        head_root = self.head_root
        cached = self._advanced
        if cached is not None and cached[0] == head_root and cached[1] == next_slot:
            return False
        state = self.get_state(head_root)
        if state is None or int(state.slot) >= next_slot:
            return False
        with tracing.span("state_advance", hist=metrics.STATE_ADVANCE_SECONDS):
            advanced = process_slots(
                state.copy(), next_slot, self.types, self.spec
            )
        self._advanced = (head_root, next_slot, advanced)
        return True

    def produce_block(
        self,
        slot: int,
        randao_reveal: bytes,
        graffiti: bytes = b"\x00" * 32,
        sync_aggregate=None,
        parent_root: Optional[bytes] = None,
        pre_state=None,
        blob_kzg_commitments: Optional[List[bytes]] = None,
        payload_header=None,
        execution_requests=None,
    ):
        """Assemble an unsigned block on the current head (or on
        ``parent_root`` — how tests build forks); reference
        ``produce_block_with_verification:4137`` → ``produce_block_on_state:4720``.
        ``pre_state``: the already-slot-advanced state for (parent_root, slot)
        if the caller has it (avoids re-advancing); it will be mutated.
        Returns ``(block, post_state_root)``; caller signs."""
        types, spec = self.types, self.spec
        # Graffiti precedence (graffiti_calculator.rs): VC-provided wins;
        # otherwise operator flag, then the calculated EL+CL version string.
        graffiti = self.graffiti_calculator.get_graffiti(graffiti)
        if pre_state is not None:
            if parent_root is None:
                raise ChainError("pre_state requires an explicit parent_root")
            state = pre_state
            if int(state.slot) != slot:
                raise ChainError(f"pre_state at slot {state.slot}, expected {slot}")
        else:
            if parent_root is None:
                parent_root = self._maybe_re_org_parent(slot)
            state, parent_root = self.state_at_slot(slot, parent_root)
        if state is self._states.get(parent_root):
            state = state.copy()
        fork = type(state).fork_name
        proposer = h.get_beacon_proposer_index(state, spec)

        # Mature naive-pool aggregates into the op pool, then max-cover pack
        # (reference: produce_block_on_state → op_pool.get_attestations).
        for att in self.attestation_pool.get_for_block(state, spec, 10_000):
            self.op_pool.insert_attestation(att)
        max_atts = (
            spec.preset.max_attestations_electra
            if fork == "electra"
            else spec.preset.max_attestations
        )
        attestations = self.op_pool.get_attestations(state, types, spec, max_atts)
        proposer_slashings, attester_slashings = self.op_pool.get_slashings(
            state, spec, types
        )

        # MEV path: a builder payload HEADER yields a blinded block
        # (reference produce_block's BlindedPayload variant).
        blinded = payload_header is not None
        # Eth1 vote + required deposits (reference eth1_chain.rs): without a
        # follower, repeat the state's current eth1_data and carry none.
        eth1_data = state.eth1_data.copy()
        deposits = []
        if self.eth1_service is not None:
            try:
                eth1_data = self.eth1_service.eth1_vote(state)
                # will THIS vote flip state.eth1_data? (process_eth1_data
                # runs before process_operations in the transition)
                period_slots = (spec.preset.epochs_per_eth1_voting_period
                                * spec.slots_per_epoch)
                same = sum(1 for v in state.eth1_data_votes if v == eth1_data) + 1
                effective = eth1_data if same * 2 > period_slots else state.eth1_data
                deposits = self.eth1_service.deposits_for_block(state, effective)
            except Exception:
                eth1_data = state.eth1_data.copy()
                deposits = []

        body_cls = types.blinded_block_body[fork] if blinded else types.block_body[fork]
        body_kwargs = dict(
            randao_reveal=randao_reveal,
            eth1_data=eth1_data,
            graffiti=graffiti,
            proposer_slashings=proposer_slashings,
            attester_slashings=attester_slashings,
            attestations=attestations,
            deposits=deposits,
            voluntary_exits=self.op_pool.get_voluntary_exits(state, types, spec),
        )
        if hasattr(body_cls, "fields") and "sync_aggregate" in body_cls.fields:
            if sync_aggregate is None:
                # The pool's contributions for the PREVIOUS slot over the
                # parent root are exactly what a block at ``slot`` carries
                # (produce_block_on_state → op_pool sync contributions).
                pooled = self.sync_contribution_pool.best_sync_aggregate(
                    max(slot, 1) - 1, parent_root
                )
                if any(pooled.sync_committee_bits):
                    sync_aggregate = pooled
            if sync_aggregate is None:
                from ..crypto.bls import api as bls

                sync_aggregate = types.SyncAggregate(
                    sync_committee_bits=[False] * spec.preset.sync_committee_size,
                    sync_committee_signature=bls.INFINITY_SIGNATURE,
                )
            body_kwargs["sync_aggregate"] = sync_aggregate
        if "execution_payload_header" in body_cls.fields:
            body_kwargs["execution_payload_header"] = payload_header
        if "execution_payload" in body_cls.fields:
            fee_recipient = self.proposer_preparations.get(proposer)
            if fork == "electra" and hasattr(
                self.execution_engine, "produce_payload_and_requests"
            ):
                payload, requests = self.execution_engine.produce_payload_and_requests(
                    state, types, spec, suggested_fee_recipient=fee_recipient
                )
                body_kwargs["execution_payload"] = payload
                body_kwargs["execution_requests"] = requests
            else:
                # the prepared recipient rides the payload attributes (the
                # EL's block hash commits to it)
                body_kwargs["execution_payload"] = self.execution_engine.produce_payload(
                    state, types, spec, suggested_fee_recipient=fee_recipient
                )
        if "bls_to_execution_changes" in body_cls.fields:
            body_kwargs["bls_to_execution_changes"] = (
                self.op_pool.get_bls_to_execution_changes(state, spec)
            )
        if "blob_kzg_commitments" in body_cls.fields:
            body_kwargs["blob_kzg_commitments"] = list(blob_kzg_commitments or [])
        if "execution_requests" in body_cls.fields and "execution_requests" not in body_kwargs:
            if execution_requests is not None:
                # blinded electra production: requests come from the bid
                body_kwargs["execution_requests"] = execution_requests
            else:
                # mock-EL path: no EL-triggered requests
                body_kwargs["execution_requests"] = types.ExecutionRequests(
                    deposits=[], withdrawals=[], consolidations=[]
                )

        block_cls = types.blinded_block[fork] if blinded else types.block[fork]
        block = block_cls(
            slot=slot,
            proposer_index=proposer,
            parent_root=parent_root,
            state_root=b"\x00" * 32,
            body=body_cls(**body_kwargs),
        )

        # Dry-run the block on the state to compute the post-state root
        # (reference: per_block_processing(VerifyRandao) dry run; signatures
        # are the caller's and randao is verified at import).
        signed_cls = (types.signed_blinded_block[fork] if blinded
                      else types.signed_block[fork])
        wrapper = signed_cls(message=block, signature=b"\x00" * 96)
        from ..consensus.per_block import per_block_processing

        per_block_processing(
            state,
            wrapper,
            types,
            spec,
            strategy=BlockSignatureStrategy.NO_VERIFICATION,
            verify_block_root=False,
            payload_verifier=None,
        )
        block.state_root = state.hash_tree_root()
        return block, bytes(block.state_root)

    # ------------------------------------------------------- MEV / builder

    def produce_blinded_block(self, slot: int, randao_reveal: bytes,
                              graffiti: bytes = b"\x00" * 32):
        """Builder-path production (reference ``produce_block`` blinded
        variant): fetch a bid from the configured relay, verify the bid
        signature and header consistency, build a BLINDED block around the
        header.  Raises ``ChainError`` when no usable bid exists — the
        caller (HTTP route / VC) falls back to local production."""
        from ..consensus.per_block import is_merge_transition_complete
        from ..crypto.bls import api as bls
        from ..execution_layer.builder_client import (
            BuilderError,
            builder_signing_root,
        )

        if self.builder is None:
            raise ChainError("no builder configured")
        state, parent_root = self.state_at_slot(slot)
        if not hasattr(state, "latest_execution_payload_header") or (
            not is_merge_transition_complete(state)
        ):
            raise ChainError("builder path requires post-merge execution")
        parent_hash = bytes(state.latest_execution_payload_header.block_hash)
        proposer = h.get_beacon_proposer_index(state, self.spec)
        pubkey = bytes(state.validators[proposer].pubkey)
        try:
            fork, signed_bid = self.builder.get_header(slot, parent_hash, pubkey,
                                                       self.types)
        except BuilderError as e:
            raise ChainError(f"builder get_header failed: {e}") from e
        if signed_bid is None:
            raise ChainError("builder returned no bid")
        if fork != type(state).fork_name:
            # a wrong-fork header would poison the state header field and
            # surface later as a non-ChainError, defeating the fallback
            raise ChainError(
                f"builder bid fork {fork!r} != state fork {type(state).fork_name!r}"
            )
        bid = signed_bid.message
        if int(bid.value) == 0:
            raise ChainError("builder bid has zero value")
        if bytes(bid.header.parent_hash) != parent_hash:
            raise ChainError("builder bid builds on the wrong parent")
        if self.builder_pubkey is not None and (
            bytes(bid.pubkey) != bytes(self.builder_pubkey)
        ):
            # Without a pinned identity the signature below only proves
            # internal consistency (bid.pubkey is attacker-chosen over plain
            # http); pinning is how the operator makes it an AUTH check.
            raise ChainError("builder bid signed by an unexpected relay key")
        sig_set = bls.SignatureSet.single_pubkey(
            bls.Signature.from_bytes(bytes(signed_bid.signature)),
            bls.PublicKey.from_bytes(bytes(bid.pubkey)),
            builder_signing_root(bid.hash_tree_root(), self.spec),
        )
        if not bls.verify_signature_sets([sig_set]):
            raise ChainError("builder bid signature invalid")
        blob_commitments = list(getattr(bid, "blob_kzg_commitments", []) or [])
        # electra bids carry the EL-triggered requests the blinded body must
        # embed (builder_bid.rs:14-35 + builder-specs electra).
        bid_requests = getattr(bid, "execution_requests", None)
        return self.produce_block(
            slot, randao_reveal, graffiti=graffiti,
            parent_root=parent_root, pre_state=state,
            payload_header=bid.header.copy(),
            blob_kzg_commitments=blob_commitments or None,
            execution_requests=bid_requests.copy() if bid_requests is not None else None,
        )

    def unblind_and_import(self, signed_blinded_block):
        """POST /eth/v1/beacon/blinded_blocks: reveal the payload at the
        relay, reconstruct the full block (same root — the header summarizes
        the payload), import it.  Returns (block_root, signed_full_block)."""
        from ..consensus.per_block import execution_payload_to_header
        from ..execution_layer.builder_client import BuilderError

        if self.builder is None:
            raise ChainError("no builder configured")
        fork = type(signed_blinded_block.message).fork_name
        try:
            payload = self.builder.submit_blinded_block(
                signed_blinded_block, self.types
            )
        except BuilderError as e:
            raise BlockError(f"builder failed to reveal payload: {e}") from e
        header = signed_blinded_block.message.body.execution_payload_header
        rebuilt = execution_payload_to_header(payload, self.types, fork)
        if rebuilt.hash_tree_root() != header.hash_tree_root():
            raise BlockError("revealed payload does not match the signed header")
        blinded = signed_blinded_block.message
        body_kwargs = {}
        for name in blinded.body.fields:
            if name == "execution_payload_header":
                body_kwargs["execution_payload"] = payload
            else:
                body_kwargs[name] = getattr(blinded.body, name)
        full = self.types.block[fork](
            slot=blinded.slot,
            proposer_index=blinded.proposer_index,
            parent_root=blinded.parent_root,
            state_root=blinded.state_root,
            body=self.types.block_body[fork](**body_kwargs),
        )
        signed_full = self.types.signed_block[fork](
            message=full, signature=signed_blinded_block.signature
        )
        root = self.process_block(signed_full)
        return root, signed_full

    def _maybe_re_org_parent(self, slot: int) -> Optional[bytes]:
        """Proposer late-block re-org decision (reference
        ``beacon_chain.rs:4250`` ``get_state_for_re_org``): when the head is
        a weakly-attested late block, propose on its PARENT and orphan it.
        Returns the parent root to build on, or None for the canonical head.
        Only attempted early in the slot (within 1/re_org_cutoff_denominator
        of slot time — a re-org block proposed late loses the race it is
        trying to win)."""
        from ..fork_choice.fork_choice import DoNotReOrg

        if self.re_org_head_threshold is None:
            return None
        into_slot = self.slot_clock.seconds_from_current_slot_start()
        if into_slot is not None and into_slot > (
            self.spec.seconds_per_slot / self.re_org_cutoff_denominator
        ):
            return None
        # head_late gate (beacon_chain.rs:4289-4290): only a head that
        # arrived AFTER the attestation deadline (seconds_per_slot/3) may be
        # orphaned — a timely block that is merely weakly attested (slow
        # attestation propagation, low participation) must be left alone.
        head_delay = self._block_delays.get(self.head_root)
        if head_delay is None or head_delay <= self.spec.seconds_per_slot / 3:
            return None
        try:
            parent = self.fork_choice.get_proposer_head(
                int(slot), self.head_root,
                re_org_head_threshold=self.re_org_head_threshold,
                re_org_parent_threshold=self.re_org_parent_threshold,
                max_epochs_since_finalization=(
                    self.re_org_max_epochs_since_finalization),
                disallowed_offsets=self.re_org_disallowed_offsets,
            )
        except DoNotReOrg as e:
            log.debug("not re-orging: %s", e)
            return None
        log.info("attempting late-block re-org: building on parent %s",
                 parent.hex()[:12])
        return parent

    def produce_attestation_data(self, slot: int, committee_index: int):
        """Reference ``produce_unaggregated_attestation:1759`` — the data all
        committee members at (slot, index) sign.  The early-attester cache is
        consulted first: for the newest verified block it answers without
        touching (or advancing) the head state."""
        types, spec = self.types, self.spec
        early = self.early_attester_cache.try_attest(
            int(slot), int(committee_index), types, spec
        )
        if early is not None:
            # The newest verified block is the right attestation target even
            # before the head recompute lands (the reference returns here
            # unconditionally; the cache is cleared if a re-org ever picks a
            # different branch).
            return early
        state = self.head_state
        head_root = self.head_root
        if int(state.slot) < slot:
            state, _ = self.state_at_slot(slot)
        epoch = h.compute_epoch_at_slot(slot, spec)
        epoch_start = h.compute_start_slot_at_epoch(epoch, spec)
        if self._blocks_slot(head_root) <= epoch_start:
            target_root = head_root  # head at/before the boundary is the target
        else:
            target_root = h.get_block_root(state, epoch, spec)
        # EIP-7549: post-electra the data's index is always 0 — the committee
        # is conveyed by the attestation's committee_bits instead.
        data_index = (
            0 if spec.fork_name_at_slot(slot) == "electra" else committee_index
        )
        return types.AttestationData(
            slot=slot,
            index=data_index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint.copy(),
            target=types.Checkpoint(epoch=epoch, root=target_root),
        )

    def _blocks_slot(self, block_root: bytes) -> int:
        if block_root == self.genesis_block_root:
            return int(self.genesis_state.slot)
        # Raw stored form only: a blinded block's slot is right there in the
        # header, and this lookup must work while the EL is down (payload
        # reconstruction would raise exactly then).
        block = self._raw_block(block_root)
        if block is None:
            raise ChainError(f"unknown block {block_root.hex()[:16]}")
        return int(block.message.slot)

    def is_pre_finalization_block(self, block_root: bytes) -> bool:
        """Is an (unknown-to-fork-choice) root a pre-finalization block?
        True -> attestations to it are rejected outright; False -> a
        single-block lookup is warranted (reference
        pre_finalization_cache.rs ``is_pre_finalization_block``)."""
        return self.pre_finalization_cache.check(block_root, self)

    def reset_fork_choice_to_finalization(self) -> None:
        """Swap in a fork choice rebuilt from the finalized checkpoint by
        canonical replay (reference fork_revert.rs
        ``reset_fork_choice_to_finalization``) — the recovery path for a
        corrupt or unsound persisted fork choice.  Destructive: every
        non-canonical branch is forgotten."""
        from .fork_revert import reset_fork_choice_to_finalization

        self.fork_choice = reset_fork_choice_to_finalization(self)
        self.recompute_head()

    # ----------------------------------------------------------------- head

    def recompute_head(self) -> bytes:
        """Reference ``canonical_head.rs:496`` ``recompute_head_at_slot``."""
        with tracing.span("head_recompute", hist=metrics.HEAD_RECOMPUTE_SECONDS):
            return self._recompute_head_inner()

    def _recompute_head_inner(self) -> bytes:
        old_head = self.head_root
        head = self.fork_choice.get_head(self.current_slot())
        self.head_root = head
        # A head that re-orged away from the early-attester item makes the
        # cached attestation data wrong — drop it (reference clears the
        # cache on re-org in canonical_head.rs).  Atomic under the cache
        # lock: a concurrent add_head_block for this very head must not be
        # wiped by a stale compare-then-clear.
        self.early_attester_cache.clear_unless(head)
        if head != old_head:
            # Head swap vs re-org: a re-org abandons the old head's branch
            # (reference canonical_head.rs logs these distinctly).
            if self.fork_choice.is_descendant(old_head, head):
                log.info("new head", slot=self._blocks_slot(head),
                         root="0x" + head.hex()[:16])
            else:
                log.warning(
                    "head re-org",
                    old_root="0x" + old_head.hex()[:16],
                    old_slot=self._blocks_slot(old_head),
                    new_root="0x" + head.hex()[:16],
                    new_slot=self._blocks_slot(head),
                )
        st = self.get_state(head) if head != old_head else None
        if st is not None:
            old_epoch = self._blocks_slot(old_head) // self.spec.slots_per_epoch
            new_epoch = self._blocks_slot(head) // self.spec.slots_per_epoch
            self.events.head(
                slot=self._blocks_slot(head),
                block_root=head,
                state_root=bytes(self._blocks[head].message.state_root)
                if head in self._blocks
                else st.hash_tree_root(),
                epoch_transition=new_epoch > old_epoch,
            )
        # Real ELs track our head (engine_forkchoiceUpdated on head change);
        # the in-proc mock has no such method and is skipped.
        if head != old_head and hasattr(self.execution_engine, "notify_forkchoice_updated"):
            st2 = self.get_state(head)
            if st2 is not None and hasattr(st2, "latest_execution_payload_header"):
                f_root_now = self.fork_choice.finalized_checkpoint[1]
                f_state = self._states.get(f_root_now)
                f_hash = (
                    bytes(f_state.latest_execution_payload_header.block_hash)
                    if f_state is not None
                    and hasattr(f_state, "latest_execution_payload_header")
                    else b"\x00" * 32
                )
                try:
                    self.execution_engine.notify_forkchoice_updated(
                        head_block_hash=bytes(
                            st2.latest_execution_payload_header.block_hash
                        ),
                        finalized_block_hash=f_hash,
                        fork=type(st2).fork_name,
                    )
                except Exception:
                    pass  # EL hiccups must never block head updates
        f_epoch, f_root = self.fork_choice.finalized_checkpoint
        if f_epoch > self._last_finalized_epoch:
            self._last_finalized_epoch = f_epoch
            log.info("finalized checkpoint advanced", epoch=f_epoch,
                     root="0x" + f_root.hex()[:16])
            f_state = self._states.get(f_root)
            self.events.finalized(
                epoch=f_epoch,
                block_root=f_root,
                state_root=bytes(self._blocks[f_root].message.state_root)
                if f_root in self._blocks
                else (f_state.hash_tree_root() if f_state is not None else b"\x00" * 32),
            )
        self._maybe_migrate()
        return head

    def _maybe_migrate(self) -> None:
        """Freeze newly-finalized history and drop abandoned forks from the
        object caches (reference: background ``migrate.rs`` — synchronous
        here; the networked node runs it off the hot path)."""
        f_epoch, f_root = self.fork_choice.finalized_checkpoint
        f_slot = f_epoch * self.spec.slots_per_epoch
        if f_slot <= self._migrated_slot or f_root not in self._states:
            return
        fork_choice = self.fork_choice

        def canonical_root_at_slot(slot: int):
            # locked per-walk: prune() may rebuild the node array between
            # migration steps (holding the lock across the WHOLE migration
            # would park imports behind state I/O)
            return fork_choice.ancestor_at_slot(f_root, slot)

        def state_for_root(block_root: bytes):
            return self._states.get(block_root)

        # Forks not descending from the finalized root are dead.
        abandoned = [
            root
            for root in self._states
            if root != f_root
            and self._blocks_slot(root) <= f_slot
            and fork_choice.ancestor_at_slot(f_root, self._blocks_slot(root)) != root
        ]
        self.db.migrate(
            finalized_slot=f_slot,
            finalized_state=self._states[f_root],
            canonical_root_at_slot=canonical_root_at_slot,
            state_for_root=state_for_root,
            abandoned_state_roots=[
                bytes(self._blocks[r].message.state_root)
                for r in abandoned
                if r in self._blocks
            ],
        )
        log.info("hot->cold migration", finalized_slot=f_slot,
                 abandoned_forks=len(abandoned))
        # Prune object caches: keep finalized root and everything after it.
        for root in abandoned:
            self._states.pop(root, None)
            self._blocks.pop(root, None)
        for root in list(self._states):
            if root != f_root and self._blocks_slot(root) < f_slot:
                self._states.pop(root, None)
        self.fork_choice.prune()
        self._migrated_slot = f_slot

    def simulate_attestation(self) -> None:
        """Produce (but never publish) one committee-0 attestation for the
        current slot and hand it to the validator monitor for later scoring
        (reference ``attestation_simulator.rs``): a free per-slot measure of
        what OUR view would have voted, scored against the canonical chain
        once the truth for the slot is knowable.  Skipped while syncing
        (head > 2 epochs behind — old-state committees are a burden)."""
        slot = self.current_slot()
        tolerance = 2 * self.spec.slots_per_epoch
        if self._blocks_slot(self.head_root) + tolerance < slot:
            return
        try:
            data = self.produce_attestation_data(slot, 0)
        except Exception:
            return
        self.validator_monitor.set_unaggregated_attestation(slot, data)

    def per_slot_task(self) -> None:
        """Per-slot tick (reference ``timer`` → ``per_slot_task``)."""
        slot = self.current_slot()
        self.fork_choice.update_time(slot)
        self.recompute_head()
        self.simulate_attestation()
        from .otb_verification import verify_otbs

        try:
            verify_otbs(self)
        except Exception as e:  # an OTB sweep must never starve pruning
            log.warning("otb verification sweep failed", error=str(e)[:80])
        self.attestation_pool.prune(slot)
        self.sync_contribution_pool.prune(slot)
        self.op_pool.prune(self.head_state, self.spec, current_slot=slot)
        self.observed.prune(self.fork_choice.finalized_checkpoint[0],
                            self.spec.slots_per_epoch)
        self.validator_monitor.prune(slot // self.spec.slots_per_epoch)
        # Missed-block tracking (validator_monitor.rs): once a slot has
        # closed, a monitored expected proposer with no canonical block is
        # a missed proposal.  Judged at a FULL slot's lag — a block
        # routinely lands seconds into the next slot, and the once-per-slot
        # guard would make that false miss permanent.  The epoch-level
        # proposer cache keeps the last two slots of each epoch checkable
        # after the head advances into the next one.
        prev = slot - 2
        if self.validator_monitor.monitored and prev > 0:
            try:
                expected = self._expected_proposer(prev)
                if expected is not None:
                    canonical = self.block_root_at_slot(prev)
                    block_seen = (
                        canonical is not None
                        and self._blocks_slot(canonical) == prev
                    )
                    self.validator_monitor.on_proposal_outcome(
                        prev, expected, block_seen)
            except Exception:
                pass  # monitoring must never break the tick
        f_slot = self.fork_choice.finalized_checkpoint[0] * self.spec.slots_per_epoch
        self.da_checker.prune(f_slot)
        # Blob retention horizon (spec MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS):
        # drop sidecars for pruned forks immediately and canonical blobs once
        # they age out — otherwise 128KiB-per-blob storage grows forever.
        horizon_slot = slot - (
            self.spec.min_epochs_for_blob_sidecars_requests * self.spec.slots_per_epoch
        )
        for root in list(self._blob_sidecars):
            if root not in self._blocks:
                self._blob_sidecars.pop(root, None)
            elif int(self._blocks[root].message.slot) < horizon_slot:
                self._blob_sidecars.pop(root, None)
        # store-side retention (backfilled sidecars live in the DB only)
        try:
            self.db.prune_blobs(horizon_slot)
        except Exception:
            pass  # retention pruning must never break the slot tick

    # ------------------------------------------------------------- queries

    def finalized_checkpoint(self) -> Tuple[int, bytes]:
        return self.fork_choice.finalized_checkpoint

    def justified_checkpoint(self) -> Tuple[int, bytes]:
        return self.fork_choice.justified_checkpoint

    def block_root_at_slot(self, slot: int) -> Optional[bytes]:
        """Canonical chain block root at ``slot`` (walks from head)."""
        return self.fork_choice.ancestor_at_slot(self.head_root, slot)
