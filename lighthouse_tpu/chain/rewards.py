"""Reward calculators for the rewards HTTP APIs and the validator monitor.

Equivalent of the reference's ``beacon_chain/src/attestation_rewards.rs``,
``beacon_block_reward.rs`` and ``sync_committee_rewards.rs`` (the sources of
the ``/eth/v1/beacon/rewards/*`` endpoints), computed from the same dense
arrays the epoch processor uses.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..consensus import helpers as h
from ..consensus import per_epoch
from ..types.spec import (
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    ChainSpec,
)

_FLAG_NAMES = {
    TIMELY_SOURCE_FLAG_INDEX: "source",
    TIMELY_TARGET_FLAG_INDEX: "target",
    TIMELY_HEAD_FLAG_INDEX: "head",
}


def attestation_rewards(state, spec: ChainSpec,
                        validator_ids: Optional[Sequence[int]] = None) -> dict:
    """Per-validator attestation rewards for the state's PREVIOUS epoch
    (reference ``attestation_rewards.rs`` / the
    ``/eth/v1/beacon/rewards/attestations/{epoch}`` payload): the state must
    be in epoch E+1 for rewards of epoch E."""
    arrays = per_epoch.EpochArrays(state, spec)
    n = len(state.validators)
    previous_epoch = h.get_previous_epoch(state, spec)
    prev_part = per_epoch._participation_array(state.previous_epoch_participation, n)
    eligible = arrays.eligible_mask(previous_epoch)
    in_leak = per_epoch.is_in_inactivity_leak(state, spec)

    increment = spec.effective_balance_increment
    total_active_balance = h.get_total_active_balance(state, spec)
    base_reward_per_increment = (
        increment * spec.base_reward_factor // spec.integer_squareroot(total_active_balance)
    )
    base_reward = (arrays.effective_balance // increment) * base_reward_per_increment
    active_increments = total_active_balance // increment

    per_flag: Dict[str, np.ndarray] = {}
    ideal_per_flag: Dict[str, np.ndarray] = {}
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        participating = per_epoch._unslashed_participating_mask(
            arrays, prev_part, flag_index, previous_epoch
        )
        participating_increments = int(
            arrays.effective_balance[participating].sum()
        ) // increment
        if in_leak:
            ideal = np.zeros(n, dtype=np.int64)
        else:
            ideal = (
                base_reward * weight * participating_increments
                // (active_increments * WEIGHT_DENOMINATOR)
            )
        name = _FLAG_NAMES[flag_index]
        ideal_per_flag[name] = ideal
        got = np.where(eligible & participating, ideal, 0)
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            got = got - np.where(
                eligible & ~participating,
                base_reward * weight // WEIGHT_DENOMINATOR, 0,
            )
        per_flag[name] = got

    # inactivity penalties against non-target-participants
    fork = type(state).fork_name
    quotient = (
        spec.inactivity_penalty_quotient_altair
        if fork == "altair"
        else spec.inactivity_penalty_quotient_bellatrix
    )
    target_participating = per_epoch._unslashed_participating_mask(
        arrays, prev_part, TIMELY_TARGET_FLAG_INDEX, previous_epoch
    )
    scores = np.asarray([int(x) for x in state.inactivity_scores], dtype=np.int64)
    inactivity = -np.where(
        eligible & ~target_participating,
        arrays.effective_balance * scores
        // (spec.inactivity_score_bias * quotient),
        0,
    )

    if validator_ids is None:
        indices = list(range(n))
    else:
        indices = [int(i) for i in validator_ids]
        bad = [i for i in indices if not (0 <= i < n)]
        if bad:
            raise ValueError(f"unknown validator indices {bad}")
    total_rewards = [
        {
            "validator_index": str(i),
            "head": str(int(per_flag["head"][i])),
            "target": str(int(per_flag["target"][i])),
            "source": str(int(per_flag["source"][i])),
            "inactivity": str(int(inactivity[i])),
        }
        for i in indices
    ]
    # ideal rewards keyed by effective balance (the API's shape)
    ideal_rewards = []
    for eb in sorted({int(arrays.effective_balance[i]) for i in indices}):
        rep = next(i for i in indices if int(arrays.effective_balance[i]) == eb)
        ideal_rewards.append({
            "effective_balance": str(eb),
            "head": str(int(ideal_per_flag["head"][rep])),
            "target": str(int(ideal_per_flag["target"][rep])),
            "source": str(int(ideal_per_flag["source"][rep])),
        })
    return {"ideal_rewards": ideal_rewards, "total_rewards": total_rewards}


def sync_committee_rewards(state, block, spec: ChainSpec,
                           validator_ids: Optional[Sequence[int]] = None) -> List[dict]:
    """Per-participant sync rewards for ``block`` on its PRE-state
    (reference ``sync_committee_rewards.rs``): positive for set bits,
    negative for missed slots."""
    from ..consensus.per_block import sync_participant_reward

    aggregate = getattr(block.message.body, "sync_aggregate", None)
    if aggregate is None:
        return []
    participant_reward = sync_participant_reward(state, spec)
    pk_to_idx = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
    wanted = None if validator_ids is None else {int(i) for i in validator_ids}
    out: Dict[int, int] = {}
    for i, bit in enumerate(aggregate.sync_committee_bits):
        vidx = pk_to_idx[bytes(state.current_sync_committee.pubkeys[i])]
        if wanted is not None and vidx not in wanted:
            continue
        out[vidx] = out.get(vidx, 0) + (
            participant_reward if bit else -participant_reward
        )
    return [
        {"validator_index": str(i), "reward": str(r)} for i, r in sorted(out.items())
    ]


def block_rewards(chain, block_root: bytes) -> Optional[dict]:
    """Proposer reward breakdown for an imported block (reference
    ``beacon_block_reward.rs``): total from the proposer's balance delta
    across the transition; the sync-aggregate share from its closed-form
    formula; attestations as the remainder (slashing inclusion rewards fold
    into it — the reference separates them, noted in the payload)."""
    block = chain.get_block(block_root)
    if block is None:
        return None
    parent_state = chain.get_state(bytes(block.message.parent_root))
    post_state = chain.get_state(block_root)
    if parent_state is None or post_state is None:
        return None
    spec = chain.spec
    proposer = int(block.message.proposer_index)

    from ..consensus.per_slot import process_slots

    pre = parent_state.copy()
    if int(pre.slot) < int(block.message.slot):
        pre = process_slots(pre, int(block.message.slot), chain.types, spec)
    pre_balance = int(pre.balances[proposer])
    post_balance = int(post_state.balances[proposer])
    total = post_balance - pre_balance

    from ..consensus.per_block import sync_proposer_reward_per_bit

    sync_share = 0
    aggregate = getattr(block.message.body, "sync_aggregate", None)
    if aggregate is not None:
        sync_share = sync_proposer_reward_per_bit(pre, spec) * sum(
            aggregate.sync_committee_bits
        )

    return {
        "proposer_index": str(proposer),
        "total": str(total),
        "attestations": str(total - sync_share),
        "sync_aggregate": str(sync_share),
        "proposer_slashings": str(0),
        "attester_slashings": str(0),
    }
