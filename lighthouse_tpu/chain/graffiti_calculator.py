"""Graffiti source precedence for block production.

Equivalent of the reference's
``beacon_node/beacon_chain/src/graffiti_calculator.rs``: the graffiti that
lands in a produced block is chosen, in order, from

1. the validator client's per-request graffiti,
2. the operator's beacon-node flag (``--graffiti``),
3. a CALCULATED string carrying the EL client's name/version (via
   ``engine_getClientVersionV1``) next to our own version,
4. the bare client version as the last resort.
"""

from __future__ import annotations

from typing import Optional

from .. import __version__ as _CL_VERSION

GRAFFITI_BYTES_LEN = 32


def _to_graffiti_bytes(text: str) -> bytes:
    raw = text.encode()[:GRAFFITI_BYTES_LEN]
    return raw + b"\x00" * (GRAFFITI_BYTES_LEN - len(raw))


class GraffitiOrigin:
    USER_SPECIFIED = "user_specified"
    CALCULATED = "calculated"

    def __init__(self, graffiti: bytes, origin: str):
        self.graffiti = graffiti
        self.origin = origin

    @classmethod
    def user(cls, graffiti: bytes) -> "GraffitiOrigin":
        return cls(bytes(graffiti[:GRAFFITI_BYTES_LEN]).ljust(
            GRAFFITI_BYTES_LEN, b"\x00"), cls.USER_SPECIFIED)

    @classmethod
    def default(cls) -> "GraffitiOrigin":
        return cls(_to_graffiti_bytes(f"lighthouse-tpu/{_CL_VERSION}"),
                   cls.CALCULATED)


class GraffitiCalculator:
    # Retry a failed EL identity probe no sooner than this (the reference
    # refreshes on an epoch cadence in the background; block production
    # must never stall re-asking a flaky EL for a graffiti string).
    FAILURE_RETRY_SECONDS = 384.0

    def __init__(self, beacon_graffiti: Optional[GraffitiOrigin] = None,
                 execution_engine=None):
        self.beacon_graffiti = beacon_graffiti or GraffitiOrigin.default()
        self.execution_engine = execution_engine
        self._el_version_cache: Optional[str] = None
        self._el_failed_at: Optional[float] = None

    def _el_client_string(self) -> Optional[str]:
        import time

        engine = self.execution_engine
        if engine is None or not hasattr(engine, "get_client_version"):
            return None
        if self._el_version_cache is not None:
            return self._el_version_cache
        # Negative cache: while the EL is slow/flaky, one failure parks the
        # probe for FAILURE_RETRY_SECONDS instead of paying an RPC timeout
        # on every production attempt.
        if (self._el_failed_at is not None
                and time.monotonic() - self._el_failed_at
                < self.FAILURE_RETRY_SECONDS):
            return None
        try:
            info = engine.get_client_version()
        except Exception:
            info = None
        if not info:
            self._el_failed_at = time.monotonic()
            return None
        self._el_version_cache = (
            f"{info.get('code', info.get('name', '??'))}"
            f"{str(info.get('commit', ''))[:4]}"
        )
        return self._el_version_cache

    def get_graffiti(self, validator_graffiti: Optional[bytes] = None) -> bytes:
        # 1. the VC's wish always wins
        if validator_graffiti is not None and any(validator_graffiti):
            return bytes(validator_graffiti[:GRAFFITI_BYTES_LEN]).ljust(
                GRAFFITI_BYTES_LEN, b"\x00")
        # 2. an operator-pinned graffiti is next
        if self.beacon_graffiti.origin == GraffitiOrigin.USER_SPECIFIED:
            return self.beacon_graffiti.graffiti
        # 3. EL version + CL version, when the EL can tell us who it is
        el = self._el_client_string()
        if el:
            return _to_graffiti_bytes(f"{el}LH{_CL_VERSION[:8]}")
        # 4. plain CL version
        return self.beacon_graffiti.graffiti
