"""Light-client server: bootstrap / update / finality-update / optimistic-
update production from import-time caching.

Equivalent of the reference's ``beacon_chain/src/light_client_server_cache.rs``
(+ the LC types in ``consensus/types/src/light_client_*.rs``): every imported
block whose sync aggregate has participants yields

- an **optimistic update** (attested header = the parent the committee
  signed, best-participation-wins per slot),
- a **finality update** (plus the attested state's finalized header and its
  Merkle branch), and
- a per-sync-committee-period **best update** carrying the next sync
  committee and its branch (the altair sync-protocol object light clients
  replay period by period).

Bootstraps are built on demand from any stored finalized block/state.

Branch depths follow the state's field count: ≤32 fields (altair..deneb) is
a depth-5 tree with the finalized root one level deeper; electra's 37-field
state is depth 6/7 and is served with the electra LC container variants
(the fork-era registry ``types.light_client``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..types import ssz as ssz_mod

SYNC_COMMITTEE_BRANCH_DEPTH = 5
FINALITY_BRANCH_DEPTH = 6


def state_depth(state) -> int:
    """Merkle depth of the state container's field tree (5 through deneb,
    6 for electra's 37 fields)."""
    return max(0, (len(state.ssz_type.field_types) - 1).bit_length())


def era_for_slot(spec, slot: int) -> str:
    """LC container era for objects at ``slot``: tracks the header format
    (capella introduces the execution header, light_client_header.rs:40-59)
    and the electra branch deepening.  altair/bellatrix share the
    beacon-only header."""
    fork = spec.fork_name_at_slot(int(slot))
    if fork in ("capella", "deneb", "electra"):
        return fork
    return "altair"


def lc_era(state, spec=None) -> str:
    """Which LC container era a state's objects must use."""
    if spec is not None:
        return era_for_slot(spec, int(state.slot))
    # Fallback (legacy callers): depth-only discrimination.
    return "electra" if state_depth(state) > SYNC_COMMITTEE_BRANCH_DEPTH else "altair"


def state_field_roots(state) -> List[bytes]:
    """Per-field hash roots of a state container (the leaves the LC branches
    prove against) — served by the incremental tree-hash cache when the
    state carries one (every hashed state does), so building branches costs
    O(cached) instead of a full re-merkleization."""
    cache = getattr(state, "_thc", None)
    if cache is not None:
        return cache.field_roots(state)
    t = state.ssz_type
    return [ft.hash_tree_root(getattr(state, name)) for name, ft in t.field_types.items()]


def _field_branch(state, field_name: str, roots: Optional[List[bytes]] = None):
    t = state.ssz_type
    names = list(t.field_types)
    if field_name not in t.field_types:
        return None  # pre-altair state: no sync committees
    depth = state_depth(state)
    if roots is None:
        roots = state_field_roots(state)
    return ssz_mod.merkle_branch(roots, 1 << depth, names.index(field_name))


def sync_committee_branch(state, field_name: str,
                          roots: Optional[List[bytes]] = None):
    return _field_branch(state, field_name, roots)


def finality_branch(state, roots: Optional[List[bytes]] = None):
    """Branch proving ``finalized_checkpoint.root`` under the state root:
    the checkpoint's own epoch-sibling leaf + the state-level branch."""
    t = state.ssz_type
    names = list(t.field_types)
    cp = state.finalized_checkpoint
    epoch_leaf = ssz_mod.uint64.hash_tree_root(int(cp.epoch))
    if roots is None:
        roots = state_field_roots(state)
    state_level = ssz_mod.merkle_branch(
        roots, 1 << state_depth(state), names.index("finalized_checkpoint")
    )
    # Checkpoint = (epoch, root): root is leaf index 1, sibling = epoch leaf.
    return [epoch_leaf] + state_level


def _payload_to_lc_exec_header(types, payload, era: str):
    """Payload -> the ERA's execution payload header, zero-extending fields
    the payload's own fork predates (the spec's upgrade_lc_header_to_*
    functions default new fields — e.g. a capella finalized block inside a
    deneb update gets blob_gas_used = excess_blob_gas = 0)."""
    hdr_cls = types.payload_header["deneb" if era == "electra" else era]
    kwargs = {}
    for name in hdr_cls.fields:
        if name == "transactions_root":
            kwargs[name] = payload.fields["transactions"].hash_tree_root(
                payload.transactions)
        elif name == "withdrawals_root":
            kwargs[name] = payload.fields["withdrawals"].hash_tree_root(
                payload.withdrawals)
        elif hasattr(payload, name):
            kwargs[name] = getattr(payload, name)
    return hdr_cls(**kwargs)


def block_to_lc_header(types, block_or_header, spec=None, era: str = None):
    """Per-era LC header for a block (light_client_header.rs:40-59).

    ``era`` is the CONTAINER era (defaults to the block slot's own era; an
    update spanning a fork boundary passes its attested era so both headers
    share one container type).  The execution part is present iff the block
    itself is capella+ (spec ``block_to_light_client_header``): the payload
    header plus the 4-deep Merkle branch proving it under the body root —
    built from one body field-root pass (which also yields the body root,
    so the beacon header costs nothing extra).  A bare ``BeaconBlockHeader``
    input (no body available — the genesis-anchor corner) degrades to a
    zeroed execution header."""
    msg = getattr(block_or_header, "message", block_or_header)
    if era is None:
        era = (era_for_slot(spec, int(msg.slot))
               if spec is not None else "altair")
    hdr_cls = types.light_client[era]["header"]

    if hasattr(msg, "body_root"):  # bare header: no body to prove against
        return hdr_cls(beacon=msg.copy())

    body = msg.body
    bt = body.ssz_type
    froots = [ft.hash_tree_root(getattr(body, n))
              for n, ft in bt.field_types.items()]
    beacon = types.BeaconBlockHeader(
        slot=msg.slot,
        proposer_index=msg.proposer_index,
        parent_root=msg.parent_root,
        state_root=msg.state_root,
        body_root=ssz_mod.merkleize(froots),
    )
    block_fork = spec.fork_name_at_slot(int(msg.slot)) if spec is not None else None
    if (era == "altair"
            or block_fork not in ("capella", "deneb", "electra")
            or not hasattr(body, "execution_payload")):
        # Pre-capella block (or altair-era container): beacon-only /
        # zeroed execution per the spec's default header.
        return hdr_cls(beacon=beacon)

    names = list(bt.field_types)
    idx = names.index("execution_payload")
    return hdr_cls(
        beacon=beacon,
        execution=_payload_to_lc_exec_header(types, body.execution_payload, era),
        execution_branch=ssz_mod.merkle_branch(froots, 16, idx),
    )


class LightClientServerCache:
    """Import-time LC object production (reference
    ``light_client_server_cache.rs``)."""

    def __init__(self, types, spec):
        self.types = types
        self.spec = spec
        self.latest_finality_update = None
        self.latest_optimistic_update = None
        # sync-committee period -> best LightClientUpdate
        self.best_updates: Dict[int, object] = {}
        self._new_finality = None  # gossip-publish queue (router drains)
        self._new_optimistic = None

    def _period(self, slot: int) -> int:
        return (slot // self.spec.slots_per_epoch) // self.spec.preset.epochs_per_sync_committee_period

    def on_block_imported(self, *, block, parent_block, parent_state,
                          finalized_block) -> None:
        """Called after import: ``block`` carries a sync aggregate signing
        ``parent_block`` (header) as attested, over ``parent_state`` (the
        attested state the branches come from).  ``finalized_block`` is the
        block at ``parent_state.finalized_checkpoint.root`` (may be None
        early in the chain)."""
        sync_aggregate = getattr(block.message.body, "sync_aggregate", None)
        if sync_aggregate is None or not any(sync_aggregate.sync_committee_bits):
            return
        if not hasattr(parent_state, "current_sync_committee"):
            return
        participation = sum(sync_aggregate.sync_committee_bits)
        signature_slot = int(block.message.slot)
        era = lc_era(parent_state, self.spec)
        lc = self.types.light_client[era]
        attested_header = block_to_lc_header(
            self.types, parent_block, self.spec, era=era)
        # One field-root pass serves both branches below (the cache makes it
        # incremental; recomputing per branch would double the cost).
        roots = state_field_roots(parent_state)

        optimistic = lc["optimistic_update"](
            attested_header=attested_header,
            sync_aggregate=sync_aggregate.copy(),
            signature_slot=signature_slot,
        )
        cur = self.latest_optimistic_update
        if cur is None or int(cur.signature_slot) < signature_slot or (
            int(cur.signature_slot) == signature_slot
            and sum(cur.sync_aggregate.sync_committee_bits) < participation
        ):
            self.latest_optimistic_update = optimistic
            self._new_optimistic = optimistic

        fin_branch = finality_branch(parent_state, roots)
        finalized_header = (
            block_to_lc_header(self.types, finalized_block, self.spec, era=era)
            if fin_branch is not None and finalized_block is not None else None
        )
        if finalized_header is not None:
            finality = lc["finality_update"](
                attested_header=attested_header,
                finalized_header=finalized_header,
                finality_branch=fin_branch,
                sync_aggregate=sync_aggregate.copy(),
                signature_slot=signature_slot,
            )
            curf = self.latest_finality_update
            if curf is None or int(curf.signature_slot) < signature_slot or (
                int(curf.signature_slot) == signature_slot
                and sum(curf.sync_aggregate.sync_committee_bits) < participation
            ):
                self.latest_finality_update = finality
                self._new_finality = finality

        # Period update: carries next_sync_committee (proven on the attested
        # state) so clients can advance committee periods.  Finality is
        # OPTIONAL (spec: zeroed finalized header/branch when the chain
        # hasn't finalized within reach yet) — without this, the periods
        # before first finality would have no updates and light clients
        # could never rotate past them.
        nsc_branch = sync_committee_branch(parent_state, "next_sync_committee", roots)
        if nsc_branch is not None:
            if finalized_header is not None:
                fin_header = finalized_header
                fin_br = fin_branch
                has_finality = True
            else:
                fin_header = lc["header"]()
                fin_br = [b"\x00" * 32] * (state_depth(parent_state) + 1)
                has_finality = False
            period = self._period(int(parent_block.message.slot)
                                  if hasattr(parent_block, "message")
                                  else int(parent_block.slot))
            update = lc["update"](
                attested_header=attested_header,
                next_sync_committee=parent_state.next_sync_committee.copy(),
                next_sync_committee_branch=nsc_branch,
                finalized_header=fin_header,
                finality_branch=fin_br,
                sync_aggregate=sync_aggregate.copy(),
                signature_slot=signature_slot,
            )
            best = self.best_updates.get(period)
            # Finality-carrying updates outrank finality-less ones; then
            # higher participation wins (the reference's is_better_update).
            def rank(u):
                return (any(any(b) for b in u.finality_branch),
                        sum(u.sync_aggregate.sync_committee_bits))

            if best is None or rank(best) < rank(update):
                self.best_updates[period] = update

    def produce_bootstrap(self, state, block):
        """``LightClientBootstrap`` for a finalized block/state pair; None
        for pre-altair states (no sync committees to prove)."""
        if not hasattr(state, "current_sync_committee"):
            return None
        branch = sync_committee_branch(state, "current_sync_committee")
        if branch is None:
            return None
        era = lc_era(state, self.spec)
        return self.types.light_client[era]["bootstrap"](
            header=block_to_lc_header(self.types, block, self.spec),
            current_sync_committee=state.current_sync_committee.copy(),
            current_sync_committee_branch=branch,
        )

    def get_updates(self, start_period: int, count: int) -> List[object]:
        out = []
        for p in range(start_period, start_period + min(count, 128)):
            u = self.best_updates.get(p)
            if u is not None:
                out.append(u)
        return out

    def take_new_updates(self) -> Tuple[Optional[object], Optional[object]]:
        """(finality_update, optimistic_update) produced since the last call
        — the router publishes these on the LC gossip topics."""
        f, o = self._new_finality, self._new_optimistic
        self._new_finality = self._new_optimistic = None
        return f, o

    def prune(self, current_period: int, keep_periods: int = 128) -> None:
        for p in [p for p in self.best_updates if p + keep_periods < current_period]:
            del self.best_updates[p]
