"""Fork-readiness watchers.

Equivalent of the reference's
``beacon_node/beacon_chain/src/{capella,deneb,electra}_readiness.rs`` as
surfaced by ``client/src/notifier.rs``: in the run-up to a scheduled fork,
each tick reports whether this node is READY — the EL is reachable and
speaks the fork's engine methods, and (for deneb+) the blob machinery has a
KZG trusted setup loaded — so operators learn about a missing upgrade
BEFORE the fork activates, not at the first missed block.
"""

from __future__ import annotations

from typing import Optional

from ..logs import get_logger

log = get_logger("chain.readiness")

# Start warning this many epochs ahead (reference readiness window).
READINESS_WINDOW_EPOCHS = 2

# Engine methods each fork's payload flow needs (reference *_readiness.rs
# capability checks).
_REQUIRED_ENGINE_METHODS = {
    "bellatrix": ("engine_newPayloadV1", "engine_forkchoiceUpdatedV1",
                  "engine_getPayloadV1"),
    "capella": ("engine_newPayloadV2", "engine_forkchoiceUpdatedV2",
                "engine_getPayloadV2"),
    "deneb": ("engine_newPayloadV3", "engine_forkchoiceUpdatedV3",
              "engine_getPayloadV3"),
    "electra": ("engine_newPayloadV4", "engine_getPayloadV4"),
}

_FORK_EPOCH_ATTR = {
    "altair": "altair_fork_epoch",
    "bellatrix": "bellatrix_fork_epoch",
    "capella": "capella_fork_epoch",
    "deneb": "deneb_fork_epoch",
    "electra": "electra_fork_epoch",
}

_FORK_ORDER = ("phase0", "altair", "bellatrix", "capella", "deneb", "electra")


def next_scheduled_fork(spec, current_epoch: int) -> Optional[tuple]:
    """(fork_name, fork_epoch) of the nearest fork still ahead, or None."""
    best = None
    for name in _FORK_ORDER[1:]:
        epoch = getattr(spec, _FORK_EPOCH_ATTR[name])
        if epoch is not None and epoch > current_epoch:
            if best is None or epoch < best[1]:
                best = (name, epoch)
    return best


def fork_readiness(chain) -> Optional[dict]:
    """Readiness report for the next fork inside the warning window, or
    None when no fork is near.  Shape mirrors the notifier's log fields."""
    spec = chain.spec
    current_epoch = chain.current_slot() // spec.slots_per_epoch
    upcoming = next_scheduled_fork(spec, current_epoch)
    if upcoming is None:
        return None
    fork, fork_epoch = upcoming
    if fork_epoch - current_epoch > READINESS_WINDOW_EPOCHS:
        return None

    problems = []
    engine = chain.execution_engine
    if fork in _REQUIRED_ENGINE_METHODS:
        if engine is None:
            problems.append("no execution engine configured")
        elif hasattr(engine, "engine"):  # real ExecutionLayer facade
            try:
                caps = engine.engine.capabilities or []
            except Exception:
                caps = []
            if not caps:
                problems.append("execution engine unreachable")
            else:
                missing = [m for m in _REQUIRED_ENGINE_METHODS[fork]
                           if m not in caps]
                if missing:
                    problems.append(f"engine missing {','.join(missing)}")
        # in-proc mock engine: structurally fork-complete, nothing to check
    if fork in ("deneb", "electra") and chain.kzg is None:
        problems.append("no KZG trusted setup loaded (blob verification)")

    report = {
        "fork": fork,
        "fork_epoch": int(fork_epoch),
        "current_epoch": int(current_epoch),
        "ready": not problems,
        "problems": problems,
    }
    if problems:
        log.warning("NOT ready for fork", **report)
    else:
        log.info("ready for fork", fork=fork, fork_epoch=int(fork_epoch))
    return report
