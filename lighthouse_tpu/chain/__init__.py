"""Chain core: BeaconChain, harness, clocks, mock execution engine
(reference: ``beacon_node/beacon_chain`` + ``common/slot_clock`` +
``execution_layer/test_utils``)."""

from .beacon_chain import (
    AttestationError,
    BeaconChain,
    BlockError,
    ChainError,
    NaiveAggregationPool,
    genesis_block_root_of,
)
from .harness import BeaconChainHarness
from .mock_el import MockExecutionEngine
from .slot_clock import ManualSlotClock, SlotClock, SystemTimeSlotClock

__all__ = [
    "AttestationError",
    "BeaconChain",
    "BeaconChainHarness",
    "BlockError",
    "ChainError",
    "ManualSlotClock",
    "MockExecutionEngine",
    "NaiveAggregationPool",
    "SlotClock",
    "SystemTimeSlotClock",
    "genesis_block_root_of",
]
