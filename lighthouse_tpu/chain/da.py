"""Deneb data availability: blob sidecar verification + the availability
checker gating block import.

Equivalent of the reference's
``beacon_node/beacon_chain/src/blob_verification.rs`` (gossip sidecar
checks: index bound, header/block consistency, commitment inclusion proof,
KZG proof) and ``data_availability_checker.rs`` (514 LoC — blocks whose
commitments aren't yet backed by verified blobs wait in the checker; import
proceeds only on full availability).

KZG verification runs through the ``Kzg`` engine the chain owns — with
``device=True`` that is the fused TPU MSM+pairing program
(``ops/kzg_device.py``), the BASELINE.md Deneb target.
"""

from __future__ import annotations

import threading

from ..timeout_lock import TimeoutLock
from typing import Dict, List, Optional, Tuple

from ..types import ssz as ssz_mod


class BlobError(Exception):
    pass


# -------------------------------------------------------- inclusion proofs


def _commitments_field_position(body_cls) -> int:
    return list(body_cls.fields).index("blob_kzg_commitments")


def compute_blob_inclusion_proof(body, index: int) -> List[bytes]:
    """Merkle branch proving ``body.blob_kzg_commitments[index]`` against
    ``hash_tree_root(body)`` (reference ``blob_sidecar.rs`` proof builder):
    list subtree siblings, the length mix-in, then the body field siblings."""
    list_type = body.fields["blob_kzg_commitments"]
    commitments = list(body.blob_kzg_commitments)
    if index >= len(commitments):
        raise BlobError(f"blob index {index} >= {len(commitments)} commitments")
    chunks = [list_type.elem.hash_tree_root(c) for c in commitments]
    proof = ssz_mod.merkle_branch(chunks, list_type.limit, index)
    proof.append(len(commitments).to_bytes(32, "little"))  # length mix-in
    field_roots = [
        ftype.hash_tree_root(getattr(body, name))
        for name, ftype in body.fields.items()
    ]
    field_pos = _commitments_field_position(type(body))
    limit = 1 << max(0, (len(field_roots) - 1).bit_length())
    proof.extend(ssz_mod.merkle_branch(field_roots, limit, field_pos))
    return proof


def verify_blob_inclusion_proof(sidecar, body_cls, max_commitments: int) -> bool:
    """Check the sidecar's commitment really is in the signed header's body
    (is_valid_merkle_branch against header.body_root)."""
    from ..consensus.per_block import is_valid_merkle_branch

    header = sidecar.signed_block_header.message
    depth_list = max(0, (max_commitments - 1).bit_length())
    n_fields = len(body_cls.fields)
    depth_body = max(0, (n_fields - 1).bit_length())
    depth = depth_list + 1 + depth_body
    field_pos = _commitments_field_position(body_cls)
    # generalized position: field subtree -> left (list body) -> leaf index
    gindex = (field_pos << (depth_list + 1)) + int(sidecar.index)
    leaf = ssz_mod.bytes48.hash_tree_root(bytes(sidecar.kzg_commitment))
    return is_valid_merkle_branch(
        leaf,
        [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof],
        depth,
        gindex,
        bytes(header.body_root),
    )


# ----------------------------------------------------------- gossip checks


def verify_blob_sidecar(sidecar, *, spec, types, kzg=None,
                        verify_kzg: bool = True,
                        header_verifier=None,
                        current_slot: Optional[int] = None) -> bytes:
    """Gossip-rule verification for one sidecar; returns the block root it
    attests to (blob_verification.rs ``GossipVerifiedBlob``).

    ``header_verifier(signed_block_header) -> bool`` authenticates the
    proposer signature (the chain provides it on the gossip path — a forged
    header must never be stored or re-forwarded); ``current_slot`` bounds
    far-future slots out of the cache."""
    header = sidecar.signed_block_header.message
    if int(sidecar.index) >= spec.preset.max_blob_commitments_per_block:
        raise BlobError(f"blob index {sidecar.index} out of range")
    if current_slot is not None and int(header.slot) > current_slot + 1:
        raise BlobError(f"sidecar slot {header.slot} is in the future")
    fork = spec.fork_name_at_slot(int(header.slot))
    body_cls = types.block_body.get(fork) or types.block_body["deneb"]
    if not verify_blob_inclusion_proof(
        sidecar, body_cls, spec.preset.max_blob_commitments_per_block
    ):
        raise BlobError("commitment inclusion proof invalid")
    if header_verifier is not None:
        if not header_verifier(sidecar.signed_block_header):
            raise BlobError("header proposer signature invalid")
    if verify_kzg:
        if kzg is None:
            raise BlobError("no KZG engine configured")
        if not kzg.verify_blob_kzg_proof(
            bytes(sidecar.blob), bytes(sidecar.kzg_commitment),
            bytes(sidecar.kzg_proof),
        ):
            raise BlobError("KZG proof invalid")
    return header.hash_tree_root()


# ------------------------------------------------------------- the checker


class DataAvailabilityChecker:
    """Blocks wait here until all their committed blobs arrive verified
    (data_availability_checker.rs).  Thread-safe; pruned by slot; both stores
    are hard-capped so unauthenticated input can't grow them without bound."""

    MAX_PENDING_BLOCKS = 64
    MAX_BLOB_ROOTS = 512

    def __init__(self, *, spec, types, kzg=None, header_verifier=None,
                 slot_provider=None):
        self.spec = spec
        self.types = types
        self.kzg = kzg
        # chain-provided proposer-signature check + clock (gossip path)
        self.header_verifier = header_verifier
        self.slot_provider = slot_provider
        self._lock = TimeoutLock("da_checker")
        # block_root -> {index: sidecar} (KZG-verified)
        self._blobs: Dict[bytes, Dict[int, object]] = {}
        # block_root -> signed block awaiting availability
        self._pending_blocks: Dict[bytes, object] = {}

    # ------------------------------------------------------------- blobs

    def put_blob(self, sidecar, verified: bool = False) -> bytes:
        """Verify (unless already ``verified``) + store one sidecar; returns
        its block root."""
        if verified:
            block_root = sidecar.signed_block_header.message.hash_tree_root()
        else:
            block_root = verify_blob_sidecar(
                sidecar, spec=self.spec, types=self.types, kzg=self.kzg,
                header_verifier=self.header_verifier,
                current_slot=self.slot_provider() if self.slot_provider else None,
            )
        with self._lock:
            if (
                block_root not in self._blobs
                and len(self._blobs) >= self.MAX_BLOB_ROOTS
            ):
                # evict the oldest-slot entry (bounded-cache discipline)
                oldest = min(
                    self._blobs,
                    key=lambda r: int(
                        next(iter(self._blobs[r].values())).signed_block_header.message.slot
                    ),
                )
                del self._blobs[oldest]
            self._blobs.setdefault(block_root, {})[int(sidecar.index)] = sidecar
        return block_root

    def blobs_for(self, block_root: bytes) -> Dict[int, object]:
        with self._lock:
            return dict(self._blobs.get(block_root, {}))

    # ------------------------------------------------------------ checking

    def check_availability(self, signed_block,
                           sidecars: Optional[List] = None) -> Tuple[str, List]:
        """('available', sidecars-in-order) when every commitment is backed
        by a verified blob; ('pending', missing-indices) otherwise.  Extra
        ``sidecars`` supplied by the caller (RPC, API) are verified+absorbed.
        Batch-verifies the supplied sidecars' KZG proofs in ONE engine call
        (kzg_utils.rs:23-36)."""
        block = signed_block.message
        commitments = [bytes(c) for c in getattr(block.body, "blob_kzg_commitments", [])]
        if not commitments:
            return "available", []
        block_root = block.hash_tree_root()
        if sidecars:
            self._absorb_batch(block_root, block, sidecars)
        have = self.blobs_for(block_root)
        missing = [i for i in range(len(commitments)) if i not in have]
        if missing:
            return "pending", missing
        ordered = []
        for i, commitment in enumerate(commitments):
            sc = have[i]
            if bytes(sc.kzg_commitment) != commitment:
                raise BlobError(f"blob {i} commitment mismatch with block")
            ordered.append(sc)
        return "available", ordered

    def _absorb_batch(self, block_root: bytes, block, sidecars: List) -> None:
        """Verify caller-supplied sidecars as one KZG batch + per-sidecar
        structural checks, then store them."""
        fresh = []
        have = self.blobs_for(block_root)
        for sc in sidecars:
            if int(sc.index) in have:
                continue
            header = sc.signed_block_header.message
            if header.hash_tree_root() != block_root:
                raise BlobError("sidecar header does not match block")
            fork = self.spec.fork_name_at_slot(int(header.slot))
            body_cls = self.types.block_body.get(fork) or self.types.block_body["deneb"]
            if not verify_blob_inclusion_proof(
                sc, body_cls, self.spec.preset.max_blob_commitments_per_block
            ):
                raise BlobError(f"blob {sc.index} inclusion proof invalid")
            fresh.append(sc)
        if not fresh:
            return
        if self.kzg is not None:
            ok = self.kzg.verify_blob_kzg_proof_batch(
                [bytes(sc.blob) for sc in fresh],
                [bytes(sc.kzg_commitment) for sc in fresh],
                [bytes(sc.kzg_proof) for sc in fresh],
            )
            if not ok:
                raise BlobError("blob KZG batch verification failed")
        with self._lock:
            slot_map = self._blobs.setdefault(block_root, {})
            for sc in fresh:
                slot_map[int(sc.index)] = sc

    # ------------------------------------------------------ pending blocks

    def put_pending_block(self, signed_block) -> None:
        with self._lock:
            if len(self._pending_blocks) >= self.MAX_PENDING_BLOCKS:
                oldest = min(
                    self._pending_blocks,
                    key=lambda r: int(self._pending_blocks[r].message.slot),
                )
                del self._pending_blocks[oldest]
            self._pending_blocks[signed_block.message.hash_tree_root()] = signed_block

    def take_ready_block(self, block_root: bytes):
        """Pop the pending block at ``block_root`` if its blobs are now all
        present; None otherwise."""
        with self._lock:
            block = self._pending_blocks.get(block_root)
        if block is None:
            return None
        status, _ = self.check_availability(block)
        if status != "available":
            return None
        with self._lock:
            return self._pending_blocks.pop(block_root, None)

    # ------------------------------------------------------------- pruning

    def prune(self, finalized_slot: int) -> None:
        with self._lock:
            for root in [
                r for r, m in self._blobs.items()
                if m and int(next(iter(m.values())).signed_block_header.message.slot)
                < finalized_slot
            ]:
                del self._blobs[root]
            for root in [
                r for r, b in self._pending_blocks.items()
                if int(b.message.slot) < finalized_slot
            ]:
                del self._pending_blocks[root]
