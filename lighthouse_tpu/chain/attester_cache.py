"""Early-attester cache: attest to the newest block before it hits the store.

Equivalent of the reference's single-item
``beacon_node/beacon_chain/src/early_attester_cache.rs``: when a block
finishes verification, enough of its post-state is captured (source/target
checkpoints, committee count) to produce attestations for the block's epoch
WITHOUT touching ``chain.head_state`` — on the 4-second attestation deadline,
waiting for the database write and head recompute is a latency cliff.  The
cached block/blobs also serve RPC requests for a block peers can already see
on gossip but which is not yet queryable from the store.
"""

from __future__ import annotations

from typing import List, Optional

from ..timeout_lock import TimeoutLock

from .. import metrics

EARLY_CACHE_HITS = metrics.counter(
    "beacon_early_attester_cache_hits",
    "attestation data served from the early-attester cache",
)


class EarlyAttesterCache:
    """Single-item cache (the newest verified head candidate)."""

    def __init__(self) -> None:
        self._item: Optional[dict] = None
        self._lock = TimeoutLock("early_attester_cache")

    def clear(self) -> None:
        with self._lock:
            self._item = None

    def clear_unless(self, block_root: bytes) -> None:
        """Atomically drop the item unless it is for ``block_root``.

        Head-recompute path: a compare-then-``clear()`` outside the lock
        races a concurrent ``add_head_block`` — the fresh item of the block
        that just became head could be wiped between the check and the
        clear, dropping a valid early-attestation target."""
        with self._lock:
            if self._item is not None and self._item["block_root"] != bytes(block_root):
                self._item = None

    def add_head_block(self, block_root: bytes, signed_block, state,
                       types, spec, blobs: Optional[list] = None) -> None:
        """Capture attestation-production state for the verified block
        (reference ``add_head_block``): the post-state's justified source,
        the epoch target (the block itself when it sits at/before the epoch
        start), and the committee count for index bounds."""
        from ..consensus import helpers as h

        epoch = int(state.slot) // spec.slots_per_epoch
        target_slot = epoch * spec.slots_per_epoch
        if int(state.slot) <= target_slot:
            target_root = bytes(block_root)
        else:
            target_root = bytes(h.get_block_root(state, epoch, spec))
        item = {
            "epoch": epoch,
            "block_slot": int(signed_block.message.slot),
            "block_root": bytes(block_root),
            "source": state.current_justified_checkpoint.copy(),
            "target_root": target_root,
            "committee_count": h.get_committee_count_per_slot(state, epoch, spec),
            "block": signed_block,
            "blobs": list(blobs) if blobs else None,
        }
        with self._lock:
            self._item = item

    def try_attest(self, request_slot: int, request_index: int, types, spec):
        """``AttestationData`` for (slot, index) from the cache, or None when
        the item is absent / a different epoch / the index is out of bounds
        (reference ``try_attest`` conditions)."""
        with self._lock:
            item = self._item
        if item is None:
            return None
        if request_slot // spec.slots_per_epoch != item["epoch"]:
            return None
        if request_slot < item["block_slot"]:
            return None
        if request_index >= item["committee_count"]:
            return None
        data_index = (
            0 if spec.fork_name_at_slot(request_slot) == "electra"
            else request_index
        )
        EARLY_CACHE_HITS.inc()
        return types.AttestationData(
            slot=request_slot,
            index=data_index,
            beacon_block_root=item["block_root"],
            source=item["source"].copy(),
            target=types.Checkpoint(epoch=item["epoch"],
                                    root=item["target_root"]),
        )

    def get_block(self, block_root: bytes):
        """The cached signed block, for serving RPC before the store has it."""
        with self._lock:
            item = self._item
        if item is not None and item["block_root"] == bytes(block_root):
            return item["block"]
        return None

    def get_blobs(self, block_root: bytes) -> Optional[List]:
        with self._lock:
            item = self._item
        if item is not None and item["block_root"] == bytes(block_root):
            return item["blobs"]
        return None
