"""Slot clocks (reference: ``common/slot_clock`` — ``SystemTimeSlotClock`` for
production, ``ManualSlotClock`` for deterministic tests)."""

from __future__ import annotations

import time
from typing import Optional


class SlotClock:
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def _seconds(self) -> float:
        raise NotImplementedError

    def now(self) -> Optional[int]:
        """Current slot, or None before genesis."""
        s = self._seconds()
        if s < self.genesis_time:
            return None
        return int(s - self.genesis_time) // self.seconds_per_slot

    def start_of(self, slot: int) -> int:
        return self.genesis_time + slot * self.seconds_per_slot

    def seconds_from_current_slot_start(self) -> Optional[float]:
        now_slot = self.now()
        if now_slot is None:
            return None
        return self._seconds() - self.start_of(now_slot)

    def duration_to_next_slot(self) -> Optional[float]:
        now_slot = self.now()
        if now_slot is None:
            return None
        return self.start_of(now_slot + 1) - self._seconds()


class SystemTimeSlotClock(SlotClock):
    def _seconds(self) -> float:
        return time.time()


class ManualSlotClock(SlotClock):
    """Test clock advanced explicitly (reference ``manual_slot_clock.rs``)."""

    def __init__(self, genesis_time: int, seconds_per_slot: int):
        super().__init__(genesis_time, seconds_per_slot)
        self._now: float = float(genesis_time)

    def _seconds(self) -> float:
        return self._now

    def set_slot(self, slot: int, offset_seconds: float = 0.0) -> None:
        self._now = self.start_of(slot) + offset_seconds

    def advance_slot(self) -> None:
        current = self.now()
        self.set_slot((current if current is not None else -1) + 1)

    def advance_seconds(self, seconds: float) -> None:
        self._now += seconds
