"""Cache for rejecting attestations to pre-finalization blocks.

Equivalent of the reference's
``beacon_node/beacon_chain/src/pre_finalization_cache.rs``: an attestation
whose head block is unknown to fork choice is either (a) pointing at an
already-finalized-past block — reject outright, it can never become a head —
or (b) pointing at a block we have not imported yet — hand it to sync's
single-block lookup.  Without this cache, an attacker replaying ancient
attestations forces a disk lookup per packet; with it, known-ancient roots
are refused from memory, and in-flight lookups are de-duplicated.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

from ..timeout_lock import TimeoutLock

BLOCK_ROOT_CACHE_LIMIT = 512
LOOKUP_LIMIT = 8


class _Lru:
    def __init__(self, cap: int) -> None:
        self.cap = cap
        self._d: "OrderedDict[bytes, None]" = OrderedDict()

    def __contains__(self, key: bytes) -> bool:
        if key in self._d:
            self._d.move_to_end(key)
            return True
        return False

    def put(self, key: bytes) -> None:
        self._d[key] = None
        self._d.move_to_end(key)
        while len(self._d) > self.cap:
            self._d.popitem(last=False)

    def pop(self, key: bytes) -> None:
        self._d.pop(key, None)

    def __len__(self) -> int:
        return len(self._d)


class PreFinalizationBlockCache:
    def __init__(self) -> None:
        self._lock = TimeoutLock("pre_finalization_cache")
        self._block_roots = _Lru(BLOCK_ROOT_CACHE_LIMIT)
        self._in_progress = _Lru(LOOKUP_LIMIT)
        # head-history snapshot: frozenset of the head state's block-roots
        # vector, rebuilt only when the head moves (the per-packet scan of
        # SLOTS_PER_HISTORICAL_ROOT entries is exactly the DoS cost this
        # cache exists to avoid).
        self._history_key: Optional[bytes] = None
        self._history: frozenset = frozenset()

    def _head_history(self, chain) -> frozenset:
        head = chain.head_root
        with self._lock:
            if self._history_key == head:
                return self._history
        snap = frozenset(bytes(r) for r in chain.head_state.block_roots)
        with self._lock:
            self._history_key = head
            self._history = snap
        return snap

    # -------------------------------------------------------------- queries

    def check(self, block_root: bytes, chain) -> bool:
        """True = the root is known pre-finalization: reject the attestation
        outright.  False = unknown; the caller should fall through to a
        single-block lookup (already-de-duplicated here)."""
        block_root = bytes(block_root)
        with self._lock:
            if block_root in self._block_roots:
                return True
            if block_root in self._in_progress:
                return False
        # 1. Recent history: the head state's block-roots vector covers the
        #    last SLOTS_PER_HISTORICAL_ROOT slots without touching disk
        #    (O(1) against the per-head frozenset snapshot).
        # 2. Disk: a stored block that fork choice does NOT know is on a
        #    pruned (pre-finalization) branch.
        if (block_root in self._head_history(chain)
                or chain.db.get_block(block_root) is not None):
            # Re-check fork choice AFTER the store read: a concurrent import
            # may have landed between the caller's fork-choice miss and now —
            # a freshly-imported head must not be classified ancient (and
            # its attester penalized).
            if chain.fork_choice.contains_block(block_root):
                return False
            with self._lock:
                self._block_roots.put(block_root)
            return True
        # 3. Unknown everywhere: let sync look it up (bounded, de-duplicated).
        with self._lock:
            self._in_progress.put(block_root)
        return False

    # -------------------------------------------------------------- feeding

    def block_processed(self, block_root: bytes) -> None:
        """An import landed: fork choice knows the root now."""
        with self._lock:
            self._in_progress.pop(bytes(block_root))

    def block_rejected(self, block_root: bytes) -> None:
        """A looked-up block failed import as pre-finalization: remember."""
        with self._lock:
            root = bytes(block_root)
            self._in_progress.pop(root)
            self._block_roots.put(root)

    def contains(self, block_root: bytes) -> bool:
        with self._lock:
            return bytes(block_root) in self._block_roots

    def metrics(self) -> Optional[Tuple[int, int]]:
        with self._lock:
            return len(self._block_roots), len(self._in_progress)
