"""In-process chain harness.

Equivalent of the reference's ``BeaconChainHarness``
(`beacon_node/beacon_chain/src/test_utils.rs`, 2.6k LoC): deterministic
interop keypairs + ``MemoryStore`` + ``ManualSlotClock`` + mock EL, able to
extend chains block-by-block with configurable attestation participation,
build forks, and drive the full L0–L4 stack with no networking — the topology
every integration test (and the north-star bench) runs on.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..consensus import helpers as h
from ..consensus.genesis import interop_genesis_state, interop_secret_key
from ..crypto.bls import api as bls
from ..types.containers import build_types
from ..types.spec import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    ChainSpec,
)
from ..types.ssz import UintType
from .beacon_chain import BeaconChain
from .mock_el import MockExecutionEngine
from .slot_clock import ManualSlotClock


class BeaconChainHarness:
    def __init__(
        self,
        *,
        validator_count: int = 16,
        spec: Optional[ChainSpec] = None,
        genesis_time: int = 1_600_000_000,
        fake_crypto: bool = False,
        kzg=None,
    ):
        """``fake_crypto=True`` switches the BLS backend to the always-valid
        impl and signs with a canned G2 point — the reference's
        ``fake_crypto`` feature (``crypto/bls/src/impls/fake_crypto.rs``),
        which lets multi-epoch logic tests run in seconds.  Structural checks
        (non-empty keys) still apply."""
        from ..types.spec import minimal_spec

        self.spec = spec if spec is not None else minimal_spec(
            altair_fork_epoch=0, bellatrix_fork_epoch=0, capella_fork_epoch=0,
            deneb_fork_epoch=None,
        )
        self.fake_crypto = fake_crypto
        if fake_crypto:
            from ..crypto.bls.backends import set_backend

            set_backend("fake")
            from ..crypto.bls import curve, serde

            self._canned_sig = serde.g2_compress(curve.G2)
        self.types = build_types(self.spec.preset)
        self.validator_count = validator_count
        self.keys = [interop_secret_key(i) for i in range(validator_count)]
        genesis_state = interop_genesis_state(
            validator_count, self.types, self.spec, genesis_time=genesis_time
        )
        self.chain = BeaconChain(
            genesis_state=genesis_state,
            types=self.types,
            spec=self.spec,
            slot_clock=ManualSlotClock(genesis_time, self.spec.seconds_per_slot),
            execution_engine=MockExecutionEngine(),
            kzg=kzg,
        )

    # ------------------------------------------------------------- signing

    def _domain_at(self, state, domain_type: bytes, epoch: int) -> bytes:
        return h.get_domain(state, domain_type, epoch, self.spec)

    def _sign(self, validator_index: int, root: bytes) -> bls.Signature:
        if self.fake_crypto:
            return bls.Signature.from_bytes(self._canned_sig)
        return self.keys[validator_index].sign(root)

    def sign_block(self, block, state) -> object:
        signed_cls = self.types.signed_block[type(block).fork_name]
        proposer = int(block.proposer_index)
        epoch = h.compute_epoch_at_slot(int(block.slot), self.spec)
        domain = self._domain_at(state, DOMAIN_BEACON_PROPOSER, epoch)
        root = h.compute_signing_root(block.hash_tree_root(), domain)
        sig = self._sign(proposer, root)
        return signed_cls(message=block, signature=sig.to_bytes())

    def randao_reveal(self, state, slot: int, proposer: int) -> bytes:
        epoch = h.compute_epoch_at_slot(slot, self.spec)
        domain = self._domain_at(state, DOMAIN_RANDAO, epoch)
        root = h.compute_signing_root(UintType(8).hash_tree_root(epoch), domain)
        return self._sign(proposer, root).to_bytes()

    def sign_attestation_data(self, state, data, validator_index: int) -> bls.Signature:
        domain = self._domain_at(state, DOMAIN_BEACON_ATTESTER, int(data.target.epoch))
        root = h.compute_signing_root(data.hash_tree_root(), domain)
        return self._sign(validator_index, root)

    def make_sync_aggregate(self, state, block_root: bytes, slot: int):
        """Full-participation sync aggregate over ``block_root`` for a block
        at ``slot`` (members sign the previous block root)."""
        spec, types = self.spec, self.types
        committee = state.current_sync_committee
        previous_slot = max(slot, 1) - 1
        domain = self._domain_at(
            state, DOMAIN_SYNC_COMMITTEE, h.compute_epoch_at_slot(previous_slot, spec)
        )
        root = h.compute_signing_root(bytes(block_root), domain)
        if self.fake_crypto:
            return types.SyncAggregate(
                sync_committee_bits=[True] * spec.preset.sync_committee_size,
                sync_committee_signature=self._canned_sig,
            )
        agg = bls.AggregateSignature.infinity()
        pk_to_index = {}
        for i, v in enumerate(state.validators):
            pk_to_index.setdefault(bytes(v.pubkey), i)
        for pk in committee.pubkeys:
            idx = pk_to_index[bytes(pk)]
            agg.add_assign(self.keys[idx].sign(root))
        return types.SyncAggregate(
            sync_committee_bits=[True] * spec.preset.sync_committee_size,
            sync_committee_signature=agg.to_bytes(),
        )

    # ----------------------------------------------------------- lifecycle

    def advance_slot(self) -> int:
        self.chain.slot_clock.advance_slot()
        self.chain.per_slot_task()
        return self.chain.current_slot()

    def produce_signed_block(
        self,
        slot: Optional[int] = None,
        sync_participation: bool = True,
        parent_root: Optional[bytes] = None,
        graffiti: bytes = b"\x00" * 32,
    ):
        chain = self.chain
        slot = chain.current_slot() if slot is None else slot
        pre_state, parent_root = chain.state_at_slot(slot, parent_root)
        proposer = h.get_beacon_proposer_index(pre_state, self.spec)
        reveal = self.randao_reveal(pre_state, slot, proposer)
        sync_aggregate = None
        if sync_participation and hasattr(pre_state, "current_sync_committee"):
            sync_aggregate = self.make_sync_aggregate(pre_state, parent_root, slot)
        block, _ = chain.produce_block(
            slot, reveal, graffiti=graffiti, sync_aggregate=sync_aggregate,
            parent_root=parent_root, pre_state=pre_state.copy(),
        )
        return self.sign_block(block, pre_state)

    def produce_signed_block_with_blobs(
        self,
        blobs: Sequence[bytes],
        slot: Optional[int] = None,
        sync_participation: bool = True,
    ):
        """Produce + sign a deneb block carrying ``blobs``, returning
        ``(signed_block, sidecars)`` with inclusion proofs + KZG proofs from
        the chain's KZG engine (the fake-EL analog of the blobsBundle flow)."""
        from .da import compute_blob_inclusion_proof

        chain, types = self.chain, self.types
        kzg = chain.kzg
        assert kzg is not None, "harness needs a Kzg engine for blob production"
        slot = chain.current_slot() if slot is None else slot
        pre_state, parent_root = chain.state_at_slot(slot)
        proposer = h.get_beacon_proposer_index(pre_state, self.spec)
        reveal = self.randao_reveal(pre_state, slot, proposer)
        sync_aggregate = None
        if sync_participation and hasattr(pre_state, "current_sync_committee"):
            sync_aggregate = self.make_sync_aggregate(pre_state, parent_root, slot)
        commitments = [kzg.blob_to_kzg_commitment(b) for b in blobs]
        proofs = [kzg.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, commitments)]
        block, _ = chain.produce_block(
            slot, reveal, sync_aggregate=sync_aggregate,
            parent_root=parent_root, pre_state=pre_state.copy(),
            blob_kzg_commitments=commitments,
        )
        signed = self.sign_block(block, pre_state)
        header = types.SignedBeaconBlockHeader(
            message=types.BeaconBlockHeader(
                slot=block.slot,
                proposer_index=block.proposer_index,
                parent_root=block.parent_root,
                state_root=block.state_root,
                body_root=block.body.hash_tree_root(),
            ),
            signature=signed.signature,
        )
        sidecars = [
            types.BlobSidecar(
                index=i,
                blob=blob,
                kzg_commitment=commitments[i],
                kzg_proof=proofs[i],
                signed_block_header=header,
                kzg_commitment_inclusion_proof=compute_blob_inclusion_proof(
                    block.body, i
                ),
            )
            for i, blob in enumerate(blobs)
        ]
        return signed, sidecars

    def attest_to_head(
        self, slot: Optional[int] = None, validators: Optional[Sequence[int]] = None
    ) -> int:
        """All (or the given) validators attest to the current head at
        ``slot``; attestations go through the chain's verification pipeline
        into fork choice + the aggregation pool.  Returns #attestations."""
        chain, spec, types = self.chain, self.spec, self.types
        slot = chain.current_slot() if slot is None else slot
        state, _ = chain.state_at_slot(slot) if int(chain.head_state.slot) < slot else (
            chain.head_state,
            chain.head_root,
        )
        included = 0
        committees = h.get_committee_count_per_slot(state, h.compute_epoch_at_slot(slot, spec), spec)
        allowed = set(validators) if validators is not None else None
        electra = spec.fork_name_at_slot(slot) == "electra"
        for index in range(committees):
            committee = h.get_beacon_committee(state, slot, index, spec)
            data = chain.produce_attestation_data(slot, index)
            for pos, vidx in enumerate(committee):
                if allowed is not None and int(vidx) not in allowed:
                    continue
                bits = [False] * len(committee)
                bits[pos] = True
                sig = self.sign_attestation_data(state, data, int(vidx)).to_bytes()
                if electra:
                    committee_bits = [False] * spec.preset.max_committees_per_slot
                    committee_bits[index] = True
                    att = types.AttestationElectra(
                        aggregation_bits=bits,
                        data=data,
                        signature=sig,
                        committee_bits=committee_bits,
                    )
                else:
                    att = types.Attestation(
                        aggregation_bits=bits, data=data, signature=sig
                    )
                chain.process_attestation(att)
                included += 1
        return included

    def extend_chain(
        self,
        num_blocks: int,
        attest: bool = True,
        participation: Optional[Sequence[int]] = None,
        sync_participation: bool = True,
    ) -> List[bytes]:
        """Advance one slot per block: produce → sign → import → attest
        (reference ``BeaconChainHarness::extend_chain``).  Returns the new
        block roots."""
        roots = []
        for _ in range(num_blocks):
            self.advance_slot()
            signed = self.produce_signed_block(sync_participation=sync_participation)
            root = self.chain.process_block(signed, block_delay_seconds=1.0)
            roots.append(root)
            if attest:
                self.attest_to_head(validators=participation)
        return roots

    # ------------------------------------------------------------- queries

    @property
    def head_root(self) -> bytes:
        return self.chain.head_root

    @property
    def head_state(self):
        return self.chain.head_state

    def finalized_epoch(self) -> int:
        return self.chain.finalized_checkpoint()[0]

    def justified_epoch(self) -> int:
        return self.chain.justified_checkpoint()[0]
