"""Gossip dedup caches: the chain's first line of DoS defense.

Equivalent of the reference's ``beacon_node/beacon_chain/src/observed_*``
family (``observed_attesters.rs``, ``observed_aggregates.rs``,
``observed_block_producers.rs``): before any signature work, gossip
verification consults these caches so the same attestation/aggregate/block
can never be re-verified arbitrarily often under replay — the spec's p2p
validation rules made O(1).

Membership is checked during gossip pre-verification and inserted only after
successful signature verification (the reference's observe-after-verify
order), so an attacker cannot poison the cache with invalid items.
"""

from __future__ import annotations

from typing import Dict, Set, Tuple

from ..timeout_lock import TimeoutLock


class ObservedAttesters:
    """One unaggregated attestation per (validator, target epoch) — the
    beacon_attestation_{subnet} gossip rule (observed_attesters.rs)."""

    def __init__(self) -> None:
        self._seen: Dict[int, Set[int]] = {}  # target_epoch -> {validator_index}
        self._lock = TimeoutLock("observed")

    def is_known(self, target_epoch: int, validator_index: int) -> bool:
        with self._lock:
            return validator_index in self._seen.get(target_epoch, ())

    def observe(self, target_epoch: int, validator_index: int) -> bool:
        """Record; returns False if it was already known."""
        with self._lock:
            s = self._seen.setdefault(target_epoch, set())
            if validator_index in s:
                return False
            s.add(validator_index)
            return True

    def prune(self, finalized_epoch: int) -> None:
        with self._lock:
            for e in [e for e in self._seen if e < finalized_epoch]:
                del self._seen[e]


class ObservedAggregators(ObservedAttesters):
    """One aggregate per (aggregator, target epoch) — the
    beacon_aggregate_and_proof gossip rule (observed_attesters.rs
    ``ObservedAggregators``)."""


class ObservedAggregates:
    """Seen aggregate attestation roots per slot, for exact-duplicate drops
    (observed_aggregates.rs ``ObservedAttestations``)."""

    def __init__(self) -> None:
        self._seen: Dict[int, Set[bytes]] = {}  # slot -> {attestation htr}
        self._lock = TimeoutLock("observed")

    def is_known(self, slot: int, attestation_root: bytes) -> bool:
        with self._lock:
            return attestation_root in self._seen.get(slot, ())

    def observe(self, slot: int, attestation_root: bytes) -> bool:
        with self._lock:
            s = self._seen.setdefault(slot, set())
            if attestation_root in s:
                return False
            s.add(attestation_root)
            return True

    def prune(self, finalized_slot: int) -> None:
        with self._lock:
            for s in [s for s in self._seen if s < finalized_slot]:
                del self._seen[s]


class ObservedBlockProducers:
    """One block per (proposer, slot); a second distinct root is an
    equivocation (observed_block_producers.rs)."""

    def __init__(self) -> None:
        self._seen: Dict[Tuple[int, int], bytes] = {}  # (slot, proposer) -> root
        self._lock = TimeoutLock("observed")

    def status(self, slot: int, proposer: int, block_root: bytes) -> str:
        """Read-only check: 'new', 'duplicate' (same root) or 'equivocation'.
        Used BEFORE import; ``observe`` records only after the block passes
        verification (observe-after-verify — an invalid block must not be
        able to brand the honest proposer an equivocator)."""
        with self._lock:
            prev = self._seen.get((slot, proposer))
            if prev is None:
                return "new"
            return "duplicate" if prev == block_root else "equivocation"

    def observe(self, slot: int, proposer: int, block_root: bytes) -> None:
        with self._lock:
            self._seen.setdefault((slot, proposer), block_root)

    def prune(self, finalized_slot: int) -> None:
        with self._lock:
            for k in [k for k in self._seen if k[0] < finalized_slot]:
                del self._seen[k]

    def proposer_seen_in_epoch(self, epoch: int, proposer: int,
                               slots_per_epoch: int) -> bool:
        """Did ``proposer`` produce any observed block in ``epoch``?  Liveness
        query (reference ``validator_seen_at_epoch``, beacon_chain.rs:6615)."""
        lo = epoch * slots_per_epoch
        hi = lo + slots_per_epoch
        with self._lock:
            return any(
                lo <= slot < hi and prod == proposer
                for (slot, prod) in self._seen
            )


class ObservedOperations:
    """Gossip dedup for pool operations (reference observed_operations.rs):
    one exit per validator, one slashing per offending proposer, one BLS
    change per validator — re-broadcasts are IGNOREd, not re-verified.
    Attester slashings dedup on their ssz root (index-set supersets are the
    pool's concern, not gossip's)."""

    KINDS = ("voluntary_exit", "proposer_slashing", "attester_slashing",
             "bls_to_execution_change")

    def __init__(self) -> None:
        self._seen = {kind: set() for kind in self.KINDS}

    def is_known(self, kind: str, key) -> bool:
        """Check WITHOUT marking — only verified ops get recorded (an
        invalid op must never censor the validator's real one)."""
        return key in self._seen[kind]

    def observe(self, kind: str, key) -> None:
        self._seen[kind].add(key)

    def prune(self) -> None:
        # exits/changes are one-shot per validator for the chain's life;
        # only the unbounded slashing-root set needs a cap
        seen = self._seen["attester_slashing"]
        while len(seen) > 4096:
            seen.pop()


class ObservedCaches:
    """The bundle a chain owns, pruned together each finalization."""

    def __init__(self) -> None:
        self.attesters = ObservedAttesters()
        self.aggregators = ObservedAggregators()
        self.aggregates = ObservedAggregates()
        self.block_producers = ObservedBlockProducers()
        self.sync_contributors = ObservedAttesters()  # (slot-as-epoch, validator)
        # Attester indices seen inside imported blocks, by target epoch.  A
        # node subscribed to few subnets sees most attestations only in
        # aggregates/blocks, so doppelganger liveness MUST consult this too
        # (reference observed_attesters.rs ``ObservedBlockAttesters``).
        self.block_attesters = ObservedAttesters()
        self.operations = ObservedOperations()

    def prune(self, finalized_epoch: int, slots_per_epoch: int) -> None:
        finalized_slot = finalized_epoch * slots_per_epoch
        self.attesters.prune(finalized_epoch)
        self.aggregators.prune(finalized_epoch)
        self.aggregates.prune(finalized_slot)
        self.block_producers.prune(finalized_slot)
        self.block_attesters.prune(finalized_epoch)
        self.operations.prune()

    def validator_seen_at_epoch(self, epoch: int, index: int,
                                slots_per_epoch: int) -> bool:
        """OR over every cache that can prove a validator was active in
        ``epoch``: gossip attestations, attestations inside imported blocks,
        aggregation duties, and block proposals (the reference's four-cache
        check, beacon_chain.rs:6615 ``validator_seen_at_epoch``)."""
        return (
            self.attesters.is_known(epoch, index)
            or self.block_attesters.is_known(epoch, index)
            or self.aggregators.is_known(epoch, index)
            or self.block_producers.proposer_seen_in_epoch(
                epoch, index, slots_per_epoch)
        )
