"""Destructive head-revert utilities for disaster recovery.

Equivalent of the reference's ``beacon_node/beacon_chain/src/fork_revert.rs``:

* ``revert_to_fork_boundary`` — after a hard fork activates and the head
  chain turns out to be invalid under the new rules (e.g. the node was
  offline during the fork and followed a pre-fork-only branch), walk the
  head's ancestry back to the last block BEFORE the fork boundary and adopt
  it as the new head.  Reverted blocks lie dormant in the database forever.
* ``reset_fork_choice_to_finalization`` — rebuild fork choice from the head
  state's finalized checkpoint by replaying the canonical blocks up to the
  head (the safe way to recover from a corrupt/unsound persisted fork
  choice; consensus-specs issue 2566 explains why replay beats patching).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..fork_choice import ExecutionStatus, ForkChoice


class ForkRevertError(Exception):
    pass


_FORK_EPOCH_ATTR = {
    "altair": "altair_fork_epoch",
    "bellatrix": "bellatrix_fork_epoch",
    "capella": "capella_fork_epoch",
    "deneb": "deneb_fork_epoch",
    "electra": "electra_fork_epoch",
}


def revert_to_fork_boundary(chain, current_slot: int) -> Tuple[bytes, object]:
    """(new_head_root, signed_block) for the last head-ancestor from before
    the currently-active fork.  Raises when already on phase0 or when no
    pre-fork ancestor exists (a corrupt database)."""
    spec = chain.spec
    fork = spec.fork_name_at_slot(int(current_slot))
    attr = _FORK_EPOCH_ATTR.get(fork)
    if attr is None:
        raise ForkRevertError("cannot revert to before the phase0 hard fork; "
                              "the database may be corrupt")
    fork_epoch = getattr(spec, attr)
    if fork_epoch is None:
        raise ForkRevertError(f"current fork {fork!r} never activates")
    boundary_slot = fork_epoch * spec.slots_per_epoch

    root = chain.head_root
    while True:
        block = chain.get_block(root)
        if block is None:
            if root == chain.genesis_block_root and boundary_slot > 0:
                return root, None  # genesis itself predates the fork
            raise ForkRevertError(
                "no pre-fork blocks found walking the head ancestry; "
                "the database may be corrupt"
            )
        if int(block.message.slot) < boundary_slot:
            return root, block
        root = bytes(block.message.parent_root)


def reset_fork_choice_to_finalization(
    chain, current_slot: Optional[int] = None
) -> ForkChoice:
    """A fresh ForkChoice anchored at the head state's finalized checkpoint
    with the canonical chain to the head replayed into it.

    Replayed blocks get ``ExecutionStatus.OPTIMISTIC`` (their payloads cannot
    be retroactively re-verified — the reference makes the same choice) and a
    zero block delay (reinforcing the canonical chain with proposer boost is
    intended).  All other branches are permanently forgotten.
    """
    spec = chain.spec
    head_root = chain.head_root
    head_state = chain.head_state
    f_epoch = int(head_state.finalized_checkpoint.epoch)
    f_root = bytes(head_state.finalized_checkpoint.root)
    if not any(f_root):
        f_root = chain.genesis_block_root  # nothing finalized yet
    f_state = chain.get_state(f_root)
    if f_state is None:
        raise ForkRevertError(
            f"finalized state missing for revert: {f_root.hex()[:16]}"
        )
    finalized_slot = f_epoch * spec.slots_per_epoch
    if int(f_state.slot) < finalized_slot:
        # advance across skipped slots to the checkpoint epoch start
        from ..consensus.per_slot import process_slots

        f_state = process_slots(f_state.copy(), finalized_slot, chain.types, spec)

    fc = ForkChoice(
        spec=spec,
        genesis_block_root=f_root,
        genesis_state=f_state,
        anchor_slot=finalized_slot,
    )
    fc.set_justified_state_provider(chain.get_state)

    # Canonical ancestry head -> finalized anchor, then replay oldest-first.
    replay = []
    root = head_root
    while root != f_root and root != chain.genesis_block_root:
        block = chain.get_block(root)
        if block is None:
            raise ForkRevertError(
                f"missing block {root.hex()[:16]} replaying to finalization"
            )
        replay.append((root, block))
        root = bytes(block.message.parent_root)
    if current_slot is None:
        current_slot = chain.current_slot()
    for block_root, block in reversed(replay):
        state = chain.get_state(block_root)
        if state is None:
            raise ForkRevertError(
                f"missing post-state {block_root.hex()[:16]} replaying to finalization"
            )
        status = (
            ExecutionStatus.OPTIMISTIC
            if hasattr(block.message.body, "execution_payload")
            else ExecutionStatus.IRRELEVANT
        )
        fc.on_block(
            current_slot=int(current_slot),
            block=block.message,
            block_root=block_root,
            state=state,
            payload_verification_status=status,
            block_delay_seconds=0.0,
        )
    fc.update_time(int(current_slot))
    return fc
