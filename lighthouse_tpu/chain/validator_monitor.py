"""Per-validator performance monitor.

Equivalent of the reference's ``beacon_chain/src/validator_monitor.rs``
(2.1k LoC): operators register the indices they care about; the monitor
watches on-chain inclusion (did my validator's attestation land in a block?
did my proposal land?), keeps per-epoch hit/miss state, and surfaces a
summary, cumulative per-validator metrics (the
``POST /lighthouse/ui/validator_metrics`` shape — reference
``http_api/src/ui.rs:152-258``), and Prometheus series.
"""

from __future__ import annotations

from ..timeout_lock import TimeoutLock
from typing import Dict, Iterable, Optional, Set

from .. import metrics

MONITOR_HISTORY_EPOCHS = 16

MONITORED_ATTESTATION_HITS = metrics.counter(
    "validator_monitor_attestation_included_total",
    "on-chain attestation inclusions for monitored validators",
)
MONITORED_BLOCKS = metrics.counter(
    "validator_monitor_blocks_proposed_total",
    "on-chain proposals by monitored validators",
)
MONITORED_COUNT = metrics.gauge(
    "validator_monitor_validators", "number of monitored validators",
)
MONITORED_SYNC_HITS = metrics.counter(
    "validator_monitor_sync_committee_hits_total",
    "sync-aggregate inclusions for monitored sync-committee members",
)
MONITORED_SYNC_MISSES = metrics.counter(
    "validator_monitor_sync_committee_misses_total",
    "sync-aggregate misses for monitored sync-committee members",
)
MONITORED_PROPOSAL_MISSES = metrics.counter(
    "validator_monitor_missed_blocks_total",
    "slots where a monitored validator was proposer but no block landed",
)
SIMULATOR_HEAD_HITS = metrics.counter(
    "validator_monitor_attestation_simulator_head_attester_hits_total",
    "simulated attestations whose head vote matched the canonical chain",
)
SIMULATOR_HEAD_MISSES = metrics.counter(
    "validator_monitor_attestation_simulator_head_attester_misses_total",
    "simulated attestations whose head vote missed",
)
SIMULATOR_TARGET_HITS = metrics.counter(
    "validator_monitor_attestation_simulator_target_attester_hits_total",
    "simulated attestations whose target vote matched",
)
SIMULATOR_TARGET_MISSES = metrics.counter(
    "validator_monitor_attestation_simulator_target_attester_misses_total",
    "simulated attestations whose target vote missed",
)

MAX_UNAGGREGATED_ATTESTATIONS = 64


def _pct(hits: int, misses: int) -> float:
    # Floor division on purpose: the reference computes
    # `(100 * hits / total) as f64` over u64s (ui.rs:219-232), which
    # truncates — wire parity beats precision here.
    total = hits + misses
    return 0.0 if total == 0 else float(100 * hits // total)


class ValidatorMonitor:
    def __init__(self, spec):
        self.spec = spec
        self.monitored: Set[int] = set()
        self._lock = TimeoutLock("validator_monitor")
        # target epoch -> monitored validators whose attestation was included
        self._included: Dict[int, Set[int]] = {}
        # target epoch -> vidx -> {"head": bool|None, "target": bool|None}
        self._flags: Dict[int, Dict[int, dict]] = {}
        # slot -> monitored proposer
        self._proposed: Dict[int, int] = {}
        # cumulative per-validator counters, advanced as epochs close
        self._counters: Dict[int, dict] = {}
        self._registered_epoch: Dict[int, int] = {}
        self._last_closed_epoch: int = -1
        # slot -> simulated AttestationData (attestation_simulator.rs feed)
        self._simulated: Dict[int, object] = {}
        self.simulator_stats = {"head_hits": 0, "head_misses": 0,
                                "target_hits": 0, "target_misses": 0}
        self._last_proposal_slot_checked: int = -1

    def register(self, indices: Iterable[int], current_epoch: int = 0) -> None:
        with self._lock:
            for i in indices:
                i = int(i)
                if i not in self.monitored:
                    self.monitored.add(i)
                    self._registered_epoch[i] = int(current_epoch)
                    self._counters.setdefault(i, {
                        "attestation_hits": 0, "attestation_misses": 0,
                        "attestation_head_hits": 0, "attestation_head_misses": 0,
                        "attestation_target_hits": 0, "attestation_target_misses": 0,
                        "latest_attestation_inclusion_distance": 0,
                        "sync_committee_hits": 0, "sync_committee_misses": 0,
                        "proposal_hits": 0, "proposal_misses": 0,
                    })
            MONITORED_COUNT.set(len(self.monitored))

    # ------------------------------------------------------------- feeding

    def on_attestation_included(
        self,
        target_epoch: int,
        attesting_indices: Iterable[int],
        head_hit: Optional[bool] = None,
        target_hit: Optional[bool] = None,
        inclusion_distance: Optional[int] = None,
    ) -> None:
        """Called per attestation in an imported block.  head_hit/target_hit
        say whether the attested head/target match the including chain
        (None = undeterminable, not counted either way)."""
        if not self.monitored:
            return
        hits = self.monitored.intersection(int(i) for i in attesting_indices)
        if not hits:
            return
        with self._lock:
            seen = self._included.setdefault(int(target_epoch), set())
            new = hits - seen
            seen.update(new)
            flags = self._flags.setdefault(int(target_epoch), {})
            for v in new:
                flags[v] = {"head": head_hit, "target": target_hit}
                if inclusion_distance is not None and v in self._counters:
                    self._counters[v]["latest_attestation_inclusion_distance"] = int(
                        inclusion_distance
                    )
        if new:
            MONITORED_ATTESTATION_HITS.inc(len(new))

    def on_block_imported(self, slot: int, proposer_index: int) -> None:
        if int(proposer_index) in self.monitored:
            with self._lock:
                self._proposed[int(slot)] = int(proposer_index)
                c = self._counters.get(int(proposer_index))
                if c is not None:
                    c["proposal_hits"] += 1
            MONITORED_BLOCKS.inc()

    def on_sync_aggregate(self, slot: int, participating: Iterable[int],
                          missing: Iterable[int]) -> None:
        """Per imported post-altair block: which monitored sync-committee
        members' bits were set / unset in its sync aggregate (reference
        validator_monitor.rs register_sync_aggregate_in_block)."""
        if not self.monitored:
            return
        hits = self.monitored.intersection(int(i) for i in participating)
        misses = self.monitored.intersection(int(i) for i in missing)
        if not hits and not misses:
            return
        with self._lock:
            for v in hits:
                c = self._counters.get(v)
                if c is not None:
                    c["sync_committee_hits"] += 1
            for v in misses:
                c = self._counters.get(v)
                if c is not None:
                    c["sync_committee_misses"] += 1
        if hits:
            MONITORED_SYNC_HITS.inc(len(hits))
        if misses:
            MONITORED_SYNC_MISSES.inc(len(misses))

    def on_proposal_outcome(self, slot: int, proposer_index: int,
                            block_seen: bool) -> None:
        """Called once per CLOSED slot with the slot's expected proposer:
        a monitored proposer with no canonical block is a missed block
        (reference validator_monitor.rs missed-block tracking).  Proposal
        HITS are counted at import (on_block_imported)."""
        v = int(proposer_index)
        with self._lock:
            # idempotent per slot: the tick can fire more than once per slot
            if int(slot) <= self._last_proposal_slot_checked:
                return
            self._last_proposal_slot_checked = int(slot)
            if block_seen or v not in self.monitored:
                return
            c = self._counters.get(v)
            if c is not None:
                c["proposal_misses"] += 1
        MONITORED_PROPOSAL_MISSES.inc()

    def _close_epochs(self, current_epoch: int) -> None:
        """Tally cumulative hit/miss counters for every epoch that can no
        longer gain inclusions (inclusion lags at most one full epoch, so
        epoch e closes once current_epoch >= e + 2).  Lock held by caller."""
        start = self._last_closed_epoch + 1
        for e in range(start, int(current_epoch) - 1):
            included = self._included.get(e, set())
            flags = self._flags.get(e, {})
            for v in self.monitored:
                if self._registered_epoch.get(v, 0) > e:
                    continue
                c = self._counters.get(v)
                if c is None:
                    continue
                if v in included:
                    c["attestation_hits"] += 1
                    f = flags.get(v, {})
                    if f.get("head") is True:
                        c["attestation_head_hits"] += 1
                    elif f.get("head") is False:
                        c["attestation_head_misses"] += 1
                    if f.get("target") is True:
                        c["attestation_target_hits"] += 1
                    elif f.get("target") is False:
                        c["attestation_target_misses"] += 1
                else:
                    c["attestation_misses"] += 1
            self._last_closed_epoch = e

    def set_unaggregated_attestation(self, slot: int, data) -> None:
        """Store one simulated per-slot attestation (the attestation
        simulator's feed, reference validator_monitor.rs
        ``set_unaggregated_attestation``); bounded like the reference."""
        with self._lock:
            if len(self._simulated) >= MAX_UNAGGREGATED_ATTESTATIONS:
                self._simulated.pop(min(self._simulated), None)
            self._simulated[int(slot)] = data

    def score_simulated_attestations(self, state, spec, helpers) -> None:
        """Compare stored simulated attestations against the now-canonical
        chain (called at block import, once the truth for their slots is
        knowable) and count head/target hit/miss metrics."""
        with self._lock:
            due = [(s, d) for s, d in self._simulated.items()
                   if s < int(state.slot)]
            for s, _ in due:
                del self._simulated[s]
        tally = {"head_hits": 0, "head_misses": 0,
                 "target_hits": 0, "target_misses": 0}
        for slot, data in due:
            try:
                head_hit = bytes(data.beacon_block_root) == bytes(
                    helpers.get_block_root_at_slot(state, slot, spec)
                )
            except Exception:
                continue
            try:
                target_hit = bytes(data.target.root) == bytes(
                    helpers.get_block_root(state, int(data.target.epoch), spec)
                )
            except Exception:
                target_hit = None
            if head_hit:
                SIMULATOR_HEAD_HITS.inc()
                tally["head_hits"] += 1
            else:
                SIMULATOR_HEAD_MISSES.inc()
                tally["head_misses"] += 1
            if target_hit is True:
                SIMULATOR_TARGET_HITS.inc()
                tally["target_hits"] += 1
            elif target_hit is False:
                SIMULATOR_TARGET_MISSES.inc()
                tally["target_misses"] += 1
        if any(tally.values()):
            with self._lock:  # shared stats follow the class's lock rule
                for k, v in tally.items():
                    self.simulator_stats[k] += v

    # ------------------------------------------------------------- queries

    def summary(self, epoch: int) -> dict:
        """Hit/miss summary for ``epoch`` (meaningful once epoch+1 ends —
        inclusion can lag a full epoch)."""
        with self._lock:
            included = sorted(self._included.get(int(epoch), set()))
            missed = sorted(self.monitored.difference(included))
            proposals = sorted(
                s for s, p in self._proposed.items()
                if s // self.spec.slots_per_epoch == int(epoch)
            )
        return {
            "epoch": int(epoch),
            "monitored": len(self.monitored),
            "attestation_included": included,
            "attestation_missed": missed,
            "proposal_slots": proposals,
        }

    def validator_metrics(self, indices: Iterable[int]) -> dict:
        """Reference ``post_validator_monitor_metrics``: cumulative counters
        for the intersection of the requested and monitored sets."""
        out = {}
        with self._lock:
            for raw in indices:
                v = int(raw)
                c = self._counters.get(v)
                if v not in self.monitored or c is None:
                    continue
                out[str(v)] = {
                    "attestation_hits": c["attestation_hits"],
                    "attestation_misses": c["attestation_misses"],
                    "attestation_hit_percentage": _pct(
                        c["attestation_hits"], c["attestation_misses"]),
                    "attestation_head_hits": c["attestation_head_hits"],
                    "attestation_head_misses": c["attestation_head_misses"],
                    "attestation_head_hit_percentage": _pct(
                        c["attestation_head_hits"], c["attestation_head_misses"]),
                    "attestation_target_hits": c["attestation_target_hits"],
                    "attestation_target_misses": c["attestation_target_misses"],
                    "attestation_target_hit_percentage": _pct(
                        c["attestation_target_hits"], c["attestation_target_misses"]),
                    "latest_attestation_inclusion_distance":
                        c["latest_attestation_inclusion_distance"],
                    "sync_committee_hits": c.get("sync_committee_hits", 0),
                    "sync_committee_misses": c.get("sync_committee_misses", 0),
                    "proposal_hits": c.get("proposal_hits", 0),
                    "proposal_misses": c.get("proposal_misses", 0),
                }
        return {"validators": out}

    def prune(self, current_epoch: int) -> None:
        cutoff = int(current_epoch) - MONITOR_HISTORY_EPOCHS
        with self._lock:
            self._close_epochs(int(current_epoch))
            for e in [e for e in self._included if e < cutoff]:
                del self._included[e]
                self._flags.pop(e, None)
            slot_cutoff = cutoff * self.spec.slots_per_epoch
            for s in [s for s in self._proposed if s < slot_cutoff]:
                del self._proposed[s]
