"""Per-validator performance monitor.

Equivalent of the reference's ``beacon_chain/src/validator_monitor.rs``
(2.1k LoC): operators register the indices they care about; the monitor
watches on-chain inclusion (did my validator's attestation land in a block?
did my proposal land?), keeps per-epoch hit/miss state, and surfaces both a
summary (the notifier line / ``/lighthouse/ui/validator_metrics`` analog)
and Prometheus series.
"""

from __future__ import annotations

import threading

from ..timeout_lock import TimeoutLock
from typing import Dict, Iterable, List, Set

from .. import metrics

MONITOR_HISTORY_EPOCHS = 16

MONITORED_ATTESTATION_HITS = metrics.counter(
    "validator_monitor_attestation_included_total",
    "on-chain attestation inclusions for monitored validators",
)
MONITORED_BLOCKS = metrics.counter(
    "validator_monitor_blocks_proposed_total",
    "on-chain proposals by monitored validators",
)
MONITORED_COUNT = metrics.gauge(
    "validator_monitor_validators", "number of monitored validators",
)


class ValidatorMonitor:
    def __init__(self, spec):
        self.spec = spec
        self.monitored: Set[int] = set()
        self._lock = TimeoutLock("validator_monitor")
        # target epoch -> monitored validators whose attestation was included
        self._included: Dict[int, Set[int]] = {}
        # slot -> monitored proposer
        self._proposed: Dict[int, int] = {}

    def register(self, indices: Iterable[int]) -> None:
        with self._lock:
            self.monitored.update(int(i) for i in indices)
            MONITORED_COUNT.set(len(self.monitored))

    # ------------------------------------------------------------- feeding

    def on_attestation_included(self, target_epoch: int,
                                attesting_indices: Iterable[int]) -> None:
        """Called per attestation in an imported block."""
        if not self.monitored:
            return
        hits = self.monitored.intersection(int(i) for i in attesting_indices)
        if not hits:
            return
        with self._lock:
            seen = self._included.setdefault(int(target_epoch), set())
            new = hits - seen
            seen.update(new)
        if new:
            MONITORED_ATTESTATION_HITS.inc(len(new))

    def on_block_imported(self, slot: int, proposer_index: int) -> None:
        if int(proposer_index) in self.monitored:
            with self._lock:
                self._proposed[int(slot)] = int(proposer_index)
            MONITORED_BLOCKS.inc()

    # ------------------------------------------------------------- queries

    def summary(self, epoch: int) -> dict:
        """Hit/miss summary for ``epoch`` (meaningful once epoch+1 ends —
        inclusion can lag a full epoch)."""
        with self._lock:
            included = sorted(self._included.get(int(epoch), set()))
            missed = sorted(self.monitored.difference(included))
            proposals = sorted(
                s for s, p in self._proposed.items()
                if s // self.spec.slots_per_epoch == int(epoch)
            )
        return {
            "epoch": int(epoch),
            "monitored": len(self.monitored),
            "attestation_included": included,
            "attestation_missed": missed,
            "proposal_slots": proposals,
        }

    def prune(self, current_epoch: int) -> None:
        cutoff = int(current_epoch) - MONITOR_HISTORY_EPOCHS
        with self._lock:
            for e in [e for e in self._included if e < cutoff]:
                del self._included[e]
            slot_cutoff = cutoff * self.spec.slots_per_epoch
            for s in [s for s in self._proposed if s < slot_cutoff]:
                del self._proposed[s]
