"""Server-sent-event bus for the beacon API ``/eth/v1/events`` stream.

Equivalent of the reference's ``beacon_chain/src/events.rs`` (``ServerSentEventHandler``
— per-topic broadcast channels the HTTP API subscribes to).  Subscribers get a
bounded queue; slow consumers drop events rather than stall the chain.
"""

from __future__ import annotations

import queue
import threading

from .. import metrics
from ..timeout_lock import TimeoutLock
from typing import Callable, Dict, List, Optional, Tuple

TOPIC_HEAD = "head"
TOPIC_BLOCK = "block"
TOPIC_ATTESTATION = "attestation"
TOPIC_FINALIZED = "finalized_checkpoint"
TOPIC_EXIT = "voluntary_exit"
TOPIC_BLOB_SIDECAR = "blob_sidecar"
TOPIC_CHAIN_REORG = "chain_reorg"
TOPIC_PAYLOAD_ATTRIBUTES = "payload_attributes"
TOPIC_CONTRIBUTION_AND_PROOF = "contribution_and_proof"
# Non-spec operator topic: device circuit-breaker transitions
# (device_supervisor.py) — a subscriber watching this sees the device
# degrade to the host path and recover, live.
TOPIC_DEVICE_BREAKER = "device_breaker"

ALL_TOPICS = (
    TOPIC_HEAD,
    TOPIC_BLOCK,
    TOPIC_ATTESTATION,
    TOPIC_PAYLOAD_ATTRIBUTES,
    TOPIC_CONTRIBUTION_AND_PROOF,
    TOPIC_FINALIZED,
    TOPIC_EXIT,
    TOPIC_BLOB_SIDECAR,
    TOPIC_CHAIN_REORG,
    TOPIC_DEVICE_BREAKER,
)


class EventSubscription:
    def __init__(self, topics: List[str], maxsize: int = 256):
        self.topics = set(topics)
        self.q: "queue.Queue[Tuple[str, dict]]" = queue.Queue(maxsize=maxsize)
        self.dropped = 0
        self.dropped_by_topic: Dict[str, int] = {}
        self.sent = 0  # bumped by the SSE writer on each delivered event

    def poll(self, timeout: Optional[float] = None) -> Optional[Tuple[str, dict]]:
        try:
            return self.q.get(timeout=timeout)
        except queue.Empty:
            return None


class EventBus:
    def __init__(self) -> None:
        self._subs: List[EventSubscription] = []
        # Synchronous in-process listeners (fn(topic, data)) — the HTTP
        # response cache's invalidation feed.  Unlike subscriptions these
        # run inline on the publishing (chain) thread, so they must be
        # cheap and must never raise into the chain.
        self._listeners: List[Callable[[str, dict], None]] = []
        self._lock = TimeoutLock("event_bus")

    def add_listener(self, fn: Callable[[str, dict], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[str, dict], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def subscribe(self, topics: List[str]) -> EventSubscription:
        bad = [t for t in topics if t not in ALL_TOPICS]
        if bad:
            raise ValueError(f"unknown event topics: {bad}")
        sub = EventSubscription(topics)
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: EventSubscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def publish(self, topic: str, data: dict) -> None:
        with self._lock:
            subs = list(self._subs)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(topic, data)
            except Exception:
                # A broken listener (cache invalidation hook) must never
                # break head recompute / block import.
                pass
        for sub in subs:
            if topic in sub.topics:
                try:
                    sub.q.put_nowait((topic, data))
                except queue.Full:
                    # Slow consumer: drop rather than stall the chain — but
                    # never silently (per-subscriber tallies + a per-topic
                    # counter, so a lossy /eth/v1/events stream is visible
                    # on /metrics before a user reports missing heads).
                    sub.dropped += 1
                    sub.dropped_by_topic[topic] = (
                        sub.dropped_by_topic.get(topic, 0) + 1
                    )
                    metrics.SSE_EVENTS_DROPPED.inc(topic=topic)

    def summary(self) -> List[dict]:
        """Per-subscriber state for the operator surface
        (``GET /lighthouse/events/subscribers``)."""
        with self._lock:
            subs = list(self._subs)
        return [
            {
                "topics": sorted(sub.topics),
                "queue_depth": sub.q.qsize(),
                "queue_capacity": sub.q.maxsize,
                "sent": sub.sent,
                "dropped": sub.dropped,
                "dropped_by_topic": dict(sub.dropped_by_topic),
            }
            for sub in subs
        ]

    # Convenience emitters mirroring the reference's EventKind variants.

    def head(self, *, slot: int, block_root: bytes, state_root: bytes,
             epoch_transition: bool) -> None:
        self.publish(TOPIC_HEAD, {
            "slot": str(slot),
            "block": "0x" + block_root.hex(),
            "state": "0x" + state_root.hex(),
            "epoch_transition": epoch_transition,
            "execution_optimistic": False,
        })

    def block(self, *, slot: int, block_root: bytes) -> None:
        self.publish(TOPIC_BLOCK, {
            "slot": str(slot),
            "block": "0x" + block_root.hex(),
            "execution_optimistic": False,
        })

    def finalized(self, *, epoch: int, block_root: bytes, state_root: bytes) -> None:
        self.publish(TOPIC_FINALIZED, {
            "epoch": str(epoch),
            "block": "0x" + block_root.hex(),
            "state": "0x" + state_root.hex(),
            "execution_optimistic": False,
        })

    def device_breaker(self, *, op: str, **fields) -> None:
        """Device circuit-breaker transition (called by the supervisor on
        every state change: op, from, to, reason, timestamp_ms)."""
        self.publish(TOPIC_DEVICE_BREAKER, {"op": op, **fields})


def exit_event_payload(exit_) -> dict:
    """SSE payload for a pooled voluntary exit (the chain layer builds
    event dicts itself — no dependency on the HTTP serializer)."""
    return {
        "message": {
            "epoch": str(int(exit_.message.epoch)),
            "validator_index": str(int(exit_.message.validator_index)),
        },
        "signature": "0x" + bytes(exit_.signature).hex(),
    }
