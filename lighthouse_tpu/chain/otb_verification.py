"""Optimistic transition block (OTB) verification.

Equivalent of the reference's
``beacon_node/beacon_chain/src/otb_verification_service.rs``: a node that
imports the MERGE TRANSITION block optimistically (its EL was offline or
syncing) has accepted, unverified, the single block whose PoW parent must
meet the terminal total difficulty.  The root+slot is persisted; once the
EL can answer, the stored block's payload parent is checked against TTD —
valid removes the record, invalid invalidates the block in fork choice
(``INVALID_BLOCK_HASH``-equivalent).  Pre- and post-transition optimistic
blocks don't need this: their validity flows from forkchoiceUpdated.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from ..logs import get_logger
from ..store.kv import DBColumn

log = get_logger("chain.otb")

_OTB_PREFIX = b"otb:"


class OtbStore:
    """Persisted registry of optimistically-imported transition blocks."""

    def __init__(self, db) -> None:
        self.db = db

    def register(self, block_root: bytes, slot: int) -> None:
        self.db.hot.put(
            DBColumn.BEACON_META, _OTB_PREFIX + bytes(block_root),
            struct.pack(">Q", int(slot)),
        )
        log.info("optimistic transition block registered",
                 root="0x" + bytes(block_root).hex()[:16], slot=int(slot))

    def remove(self, block_root: bytes) -> None:
        self.db.hot.delete(DBColumn.BEACON_META, _OTB_PREFIX + bytes(block_root))

    def all(self) -> List[Tuple[bytes, int]]:
        out = []
        for key, raw in self.db.hot.iter_column(DBColumn.BEACON_META):
            if key.startswith(_OTB_PREFIX):
                out.append((key[len(_OTB_PREFIX):], struct.unpack(">Q", raw)[0]))
        return out


def validate_merge_transition_block(chain, signed_block) -> Optional[bool]:
    """True = the transition is valid (PoW parent meets TTD), False =
    provably invalid, None = the EL cannot answer yet.  Accepts a full OR
    blinded block — the check needs only the payload's parent_hash, which
    the blinded header carries."""
    body = signed_block.message.body
    payload = getattr(body, "execution_payload",
                      getattr(body, "execution_payload_header", None))
    engine = chain.execution_engine
    if engine is None or not hasattr(engine, "get_pow_block"):
        return None
    try:
        pow_block = engine.get_pow_block(bytes(payload.parent_hash))
    except Exception:
        return None
    if pow_block is None:
        # Not-found is UNDECIDABLE, not invalid (reference
        # TerminalPoWBlockNotFound retries — the EL may still be syncing
        # or has pruned pre-merge history); only a found-and-failing
        # parent proves the transition invalid.
        return None
    ttd = chain.spec.terminal_total_difficulty
    try:
        total_td = int(pow_block["total_difficulty"])
        parent_td = int(pow_block["parent_total_difficulty"])
    except (KeyError, TypeError, ValueError):
        return None  # partial EL response: decide nothing on missing data
    return total_td >= ttd and parent_td < ttd


def verify_otbs(chain) -> int:
    """One verification sweep (the reference's background service loop body):
    resolves every stored OTB the EL can now answer for.  Returns the
    number of records resolved."""
    store: OtbStore = chain.otb_store
    resolved = 0
    for root, slot in store.all():
        # The BLINDED form suffices (parent_hash lives in the header) and
        # never round-trips the EL — get_block's payload reconstruction
        # would raise in exactly the EL-down state where OTBs exist.
        block = chain.get_blinded_block(root)
        if block is None:
            store.remove(root)  # pruned away: nothing left to verify
            resolved += 1
            continue
        verdict = validate_merge_transition_block(chain, block)
        if verdict is None:
            continue  # EL still can't answer; retry next sweep
        if verdict:
            log.info("optimistic transition block verified",
                     root="0x" + root.hex()[:16])
        else:
            log.warning("INVALID optimistic transition block",
                        root="0x" + root.hex()[:16], slot=slot)
            try:
                chain.fork_choice.on_invalid_execution_payload(
                    root, latest_valid_hash=None
                )
                chain.recompute_head()
            except Exception as e:
                log.error("failed to invalidate transition block",
                          root="0x" + root.hex()[:16], error=str(e)[:80])
                continue
        store.remove(root)
        resolved += 1
    return resolved
