"""Blinded-block payload reconstruction (beacon block streamer).

Equivalent of the reference's
``beacon_node/beacon_chain/src/beacon_block_streamer.rs`` (1,008 LoC): the
store may hold POST-MERGE blocks in blinded form (execution payload replaced
by its header — how the reference persists every block); anything that must
serve a FULL block (``/eth/v2/beacon/blocks/{id}``, BlocksByRange/Root RPC)
reconstructs the payload from the execution layer via
``engine_getPayloadBodiesByHash`` (batched — one EL round trip per request,
not per block), rebuilds the block, and verifies the rebuilt payload
summarizes to the stored header before handing it out.

Pre-merge blocks (no payload) pass through untouched.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..consensus.per_block import execution_payload_to_header


class ReconstructionError(Exception):
    pass


def is_blinded(signed_block) -> bool:
    return hasattr(signed_block.message.body, "execution_payload_header")


def blind_signed_block(signed_block, types):
    """Full -> blinded: replace the execution payload with its header
    (inverse of ``BeaconChain.unblind_and_import``'s rebuild loop)."""
    block = signed_block.message
    fork = type(block).fork_name
    body_kwargs = {}
    for name in block.body.fields:
        if name == "execution_payload":
            body_kwargs["execution_payload_header"] = execution_payload_to_header(
                block.body.execution_payload, types, fork
            )
        else:
            body_kwargs[name] = getattr(block.body, name)
    blinded = types.blinded_block[fork](
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body=types.blinded_block_body[fork](**body_kwargs),
    )
    return types.signed_blinded_block[fork](
        message=blinded, signature=signed_block.signature
    )


class BeaconBlockStreamer:
    """Batched full-block reconstruction over the chain's execution engine."""

    def __init__(self, chain) -> None:
        self.chain = chain

    # ------------------------------------------------------------ plumbing

    def _payload_cls(self, fork: str):
        types = self.chain.types
        return {
            "bellatrix": types.ExecutionPayloadBellatrix,
            "capella": types.ExecutionPayloadCapella,
            "deneb": types.ExecutionPayloadDeneb,
            "electra": types.ExecutionPayloadDeneb,  # structurally deneb's
        }[fork]

    def _withdrawal(self, w):
        """Accept a Withdrawal container (mock EL) or engine-API JSON."""
        if not isinstance(w, dict):
            return w
        return self.chain.types.Withdrawal(
            index=int(w["index"], 16),
            validator_index=int(w["validatorIndex"], 16),
            address=bytes.fromhex(w["address"][2:]),
            amount=int(w["amount"], 16),
        )

    def _rebuild_payload(self, header, fork: str, body: dict):
        """Header + EL payload body -> full ExecutionPayload, verified."""
        cls = self._payload_cls(fork)
        kwargs = {}
        for name in cls.fields:
            if name == "transactions":
                kwargs[name] = [bytes(t) for t in body.get("transactions", [])]
            elif name == "withdrawals":
                kwargs[name] = [
                    self._withdrawal(w) for w in (body.get("withdrawals") or [])
                ]
            else:
                kwargs[name] = getattr(header, name)
        payload = cls(**kwargs)
        rebuilt = execution_payload_to_header(payload, self.chain.types, fork)
        if rebuilt.hash_tree_root() != header.hash_tree_root():
            raise ReconstructionError(
                "EL payload body does not summarize to the stored header "
                f"(block_hash {bytes(header.block_hash).hex()[:16]})"
            )
        return payload

    def _unblind(self, signed_blinded, body: Optional[dict]):
        if body is None:
            raise ReconstructionError(
                "execution layer has no payload body for block_hash "
                + bytes(
                    signed_blinded.message.body.execution_payload_header.block_hash
                ).hex()[:16]
            )
        types = self.chain.types
        blinded = signed_blinded.message
        fork = type(blinded).fork_name
        header = blinded.body.execution_payload_header
        payload = self._rebuild_payload(header, fork, body)
        body_kwargs = {}
        for name in blinded.body.fields:
            if name == "execution_payload_header":
                body_kwargs["execution_payload"] = payload
            else:
                body_kwargs[name] = getattr(blinded.body, name)
        full = types.block[fork](
            slot=blinded.slot,
            proposer_index=blinded.proposer_index,
            parent_root=blinded.parent_root,
            state_root=blinded.state_root,
            body=types.block_body[fork](**body_kwargs),
        )
        return types.signed_block[fork](
            message=full, signature=signed_blinded.signature
        )

    # ------------------------------------------------------------- public

    def reconstruct(self, signed_blocks: Sequence) -> List:
        """Full blocks for a mixed full/blinded sequence: ONE batched
        ``engine_getPayloadBodiesByHash`` round trip covers every blinded
        entry (the reference streams ranges the same way)."""
        hashes: List[bytes] = []
        for sb in signed_blocks:
            if sb is not None and is_blinded(sb):
                hashes.append(bytes(
                    sb.message.body.execution_payload_header.block_hash
                ))
        bodies: Dict[bytes, Optional[dict]] = {}
        if hashes:
            engine = self.chain.execution_engine
            if engine is None or not hasattr(engine, "get_payload_bodies_by_hash"):
                raise ReconstructionError(
                    "no execution engine able to serve payload bodies"
                )
            for hsh, body in zip(hashes, engine.get_payload_bodies_by_hash(hashes)):
                bodies[hsh] = body
        out = []
        for sb in signed_blocks:
            if sb is None or not is_blinded(sb):
                out.append(sb)
                continue
            hsh = bytes(sb.message.body.execution_payload_header.block_hash)
            out.append(self._unblind(sb, bodies.get(hsh)))
        return out

    def reconstruct_one(self, signed_block):
        return self.reconstruct([signed_block])[0]
