"""Mock execution engine.

Equivalent of the reference's ``MockServer``/``MockExecutionLayer``
(`beacon_node/execution_layer/src/test_utils/`) — the fake EL that every
harness/simulator test runs against.  Builds payloads that satisfy
``process_execution_payload``'s checks (parent hash chain, prev_randao,
timestamp) and answers ``notify_new_payload`` with a configurable verdict so
tests can inject INVALID payloads (the reference's ``payload_invalidation.rs``
fault-injection pattern).
"""

from __future__ import annotations

from hashlib import sha256
from typing import Optional, Set

from ..consensus import helpers as h
from ..consensus.per_block import compute_timestamp_at_slot, is_merge_transition_complete
from ..types.spec import ChainSpec


class MockExecutionEngine:
    on_payload_attributes = None  # SSE hook, set by the chain

    def __init__(self) -> None:
        self.invalid_hashes: Set[bytes] = set()
        self.offline = False
        self.payloads_seen = 0
        # block_hash -> payload body, for engine_getPayloadBodiesByHash/Range
        # (reference MockServer keeps every payload it has seen).
        self._bodies: dict = {}
        # PoW chain stub for transition-block TTD checks (tests seed this).
        self.pow_blocks: dict = {}

    def _record_body(self, payload) -> None:
        self._bodies[bytes(payload.block_hash)] = {
            "block_number": int(payload.block_number),
            "transactions": [bytes(t) for t in payload.transactions],
            "withdrawals": [w.copy() for w in getattr(payload, "withdrawals", [])],
        }

    # ------------------------------------------------------------- produce

    def produce_payload(self, state, types, spec: ChainSpec,
                        suggested_fee_recipient=None):
        """Build the payload for a block on ``state`` (already advanced to the
        block's slot).  The analog of engine_getPayload against the mock EL."""
        fork = type(state).fork_name
        cls = {
            "bellatrix": types.ExecutionPayloadBellatrix,
            "capella": types.ExecutionPayloadCapella,
            "deneb": types.ExecutionPayloadDeneb,
            "electra": types.ExecutionPayloadDeneb,  # structurally identical
        }[fork]
        parent_hash = bytes(state.latest_execution_payload_header.block_hash)
        if not is_merge_transition_complete(state):
            parent_hash = b"\x00" * 32
        timestamp = compute_timestamp_at_slot(state, state.slot, spec)
        prev_randao = h.get_randao_mix(state, h.get_current_epoch(state, spec), spec)
        if self.on_payload_attributes is not None:
            # mirror the real EL's SSE hook (same attribute shape) so
            # harness runs emit structurally identical events
            try:
                self.on_payload_attributes(fork, state, {
                    "timestamp": hex(timestamp),
                    "prevRandao": "0x" + bytes(prev_randao).hex(),
                    "suggestedFeeRecipient": "0x" + bytes(
                        suggested_fee_recipient or b"\x00" * 20).hex(),
                })
            except Exception:
                pass
        block_hash = sha256(
            b"mock-el" + parent_hash + int(state.slot).to_bytes(8, "little")
        ).digest()
        kwargs = dict(
            parent_hash=parent_hash,
            fee_recipient=bytes(suggested_fee_recipient or b"\x00" * 20),
            state_root=b"\x00" * 32,
            receipts_root=b"\x00" * 32,
            logs_bloom=b"\x00" * 256,
            prev_randao=prev_randao,
            block_number=int(state.slot),
            gas_limit=30_000_000,
            gas_used=0,
            timestamp=timestamp,
            extra_data=b"",
            base_fee_per_gas=7,
            block_hash=block_hash,
            transactions=[],
        )
        if fork in ("capella", "deneb", "electra"):
            kwargs["withdrawals"] = h.get_expected_withdrawals(state, types, spec)
        if fork in ("deneb", "electra"):
            kwargs["blob_gas_used"] = 0
            kwargs["excess_blob_gas"] = 0
        payload = cls(**kwargs)
        self._record_body(payload)
        return payload

    # -------------------------------------------------------------- verify

    def notify_new_payload(self, payload) -> bool:
        """engine_newPayload: VALID unless the hash was marked invalid."""
        if self.offline:
            raise ConnectionError("mock execution engine offline")
        self.payloads_seen += 1
        self._record_body(payload)
        return bytes(payload.block_hash) not in self.invalid_hashes

    def get_pow_block(self, block_hash: bytes):
        """PoW-chain lookup for transition-block TTD validation
        (otb_verification; reference MockServer's PoW block store).
        Returns {"total_difficulty", "parent_total_difficulty"} or None."""
        if self.offline:
            raise ConnectionError("mock execution engine offline")
        return self.pow_blocks.get(bytes(block_hash))

    def get_client_version(self) -> dict:
        """engine_getClientVersionV1 (graffiti_calculator's EL identity)."""
        if self.offline:
            raise ConnectionError("mock execution engine offline")
        return {"code": "MK", "name": "mock-el", "version": "0.1.0",
                "commit": "deadbeef"}

    # ------------------------------------------------------- payload bodies

    def get_payload_bodies_by_hash(self, hashes):
        """engine_getPayloadBodiesByHashV1 (body dict or None per hash)."""
        if self.offline:
            raise ConnectionError("mock execution engine offline")
        return [self._bodies.get(bytes(h)) for h in hashes]

    def get_payload_bodies_by_range(self, start: int, count: int):
        """engine_getPayloadBodiesByRangeV1 by block_number."""
        if self.offline:
            raise ConnectionError("mock execution engine offline")
        by_number = {b["block_number"]: b for b in self._bodies.values()}
        return [by_number.get(n) for n in range(start, start + count)]
