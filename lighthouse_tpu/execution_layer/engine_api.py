"""Typed engine-API JSON-RPC client.

Equivalent of the reference's ``execution_layer/src/engine_api/http.rs``
(``HttpJsonRpc`` — newPayload/forkchoiceUpdated/getPayload V1-V3, capability
exchange), with the payload JSON (de)serialization the engine spec defines:
camelCase keys, 0x-hex QUANTITY/DATA encodings.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from . import auth

STATUS_VALID = "VALID"
STATUS_INVALID = "INVALID"
STATUS_SYNCING = "SYNCING"
STATUS_ACCEPTED = "ACCEPTED"
STATUS_INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"

SUPPORTED_METHODS = [
    "engine_exchangeCapabilities",
    "engine_newPayloadV1",
    "engine_newPayloadV2",
    "engine_newPayloadV3",
    "engine_newPayloadV4",
    "engine_forkchoiceUpdatedV1",
    "engine_forkchoiceUpdatedV2",
    "engine_forkchoiceUpdatedV3",
    "engine_getPayloadV1",
    "engine_getPayloadV2",
    "engine_getPayloadV3",
    "engine_getPayloadV4",
    "engine_getPayloadBodiesByHashV1",
    "engine_getPayloadBodiesByRangeV1",
    "engine_getClientVersionV1",
]


class EngineApiError(Exception):
    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


class EngineOffline(EngineApiError):
    pass


# --------------------------------------------------------- payload serde


def _q(v: int) -> str:  # QUANTITY
    return hex(int(v))


def _d(b: bytes) -> str:  # DATA
    return "0x" + bytes(b).hex()


def withdrawal_to_json(w) -> Dict[str, str]:
    """Engine-API WithdrawalV1 encoding — shared by payload serde and
    PayloadAttributes construction."""
    return {
        "index": _q(w.index),
        "validatorIndex": _q(w.validator_index),
        "address": _d(w.address),
        "amount": _q(w.amount),
    }


_REQUEST_FIELDS = (("deposits", 0), ("withdrawals", 1), ("consolidations", 2))


def execution_requests_to_json(er) -> List[str]:
    """ExecutionRequests container -> Prague engine encoding: one DATA item
    per non-empty request type, ``type_byte || ssz(list)``."""
    out = []
    for field, type_byte in _REQUEST_FIELDS:
        items = list(getattr(er, field))
        if items:
            blob = er.fields[field].serialize(items)
            out.append("0x%02x" % type_byte + blob.hex())
    return out


def execution_requests_from_json(lst, types):
    """Inverse of :func:`execution_requests_to_json`."""
    by_type = {t: f for f, t in _REQUEST_FIELDS}
    kwargs = {f: [] for f, _ in _REQUEST_FIELDS}
    cls = types.ExecutionRequests
    for item in lst or []:
        raw = bytes.fromhex(item[2:] if item.startswith("0x") else item)
        if not raw:
            continue
        field = by_type.get(raw[0])
        if field is None:
            raise EngineApiError(f"unknown execution request type {raw[0]}")
        kwargs[field] = cls.fields[field].deserialize(raw[1:])
    return cls(**kwargs)


def _body_from_json(obj) -> Optional[Dict[str, Any]]:
    """ExecutionPayloadBodyV1 JSON -> normalized dict (or None)."""
    if obj is None:
        return None
    return {
        "transactions": [bytes.fromhex(t[2:]) for t in obj.get("transactions", [])],
        "withdrawals": list(obj.get("withdrawals") or []),
    }


def kzg_commitment_to_versioned_hash(commitment: bytes) -> bytes:
    """EIP-4844 versioned hash: 0x01 || sha256(commitment)[1:]."""
    from hashlib import sha256

    return b"\x01" + sha256(bytes(commitment)).digest()[1:]


def payload_to_json(payload) -> Dict[str, Any]:
    """ExecutionPayload container -> engine-API ExecutionPayloadV1/2/3 JSON."""
    out = {
        "parentHash": _d(payload.parent_hash),
        "feeRecipient": _d(payload.fee_recipient),
        "stateRoot": _d(payload.state_root),
        "receiptsRoot": _d(payload.receipts_root),
        "logsBloom": _d(payload.logs_bloom),
        "prevRandao": _d(payload.prev_randao),
        "blockNumber": _q(payload.block_number),
        "gasLimit": _q(payload.gas_limit),
        "gasUsed": _q(payload.gas_used),
        "timestamp": _q(payload.timestamp),
        "extraData": _d(payload.extra_data),
        "baseFeePerGas": _q(payload.base_fee_per_gas),
        "blockHash": _d(payload.block_hash),
        "transactions": [_d(tx) for tx in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):
        out["withdrawals"] = [withdrawal_to_json(w) for w in payload.withdrawals]
    if hasattr(payload, "blob_gas_used"):
        out["blobGasUsed"] = _q(payload.blob_gas_used)
        out["excessBlobGas"] = _q(payload.excess_blob_gas)
    return out


def payload_from_json(obj: Dict[str, Any], types, fork: str):
    """Engine-API JSON -> the fork's ExecutionPayload container."""
    cls = {
        "bellatrix": types.ExecutionPayloadBellatrix,
        "capella": types.ExecutionPayloadCapella,
        "deneb": types.ExecutionPayloadDeneb,
        "electra": types.ExecutionPayloadDeneb,  # structurally identical
    }[fork]
    kwargs = dict(
        parent_hash=bytes.fromhex(obj["parentHash"][2:]),
        fee_recipient=bytes.fromhex(obj["feeRecipient"][2:]),
        state_root=bytes.fromhex(obj["stateRoot"][2:]),
        receipts_root=bytes.fromhex(obj["receiptsRoot"][2:]),
        logs_bloom=bytes.fromhex(obj["logsBloom"][2:]),
        prev_randao=bytes.fromhex(obj["prevRandao"][2:]),
        block_number=int(obj["blockNumber"], 16),
        gas_limit=int(obj["gasLimit"], 16),
        gas_used=int(obj["gasUsed"], 16),
        timestamp=int(obj["timestamp"], 16),
        extra_data=bytes.fromhex(obj["extraData"][2:]),
        base_fee_per_gas=int(obj["baseFeePerGas"], 16),
        block_hash=bytes.fromhex(obj["blockHash"][2:]),
        transactions=[bytes.fromhex(tx[2:]) for tx in obj["transactions"]],
    )
    if fork in ("capella", "deneb", "electra"):
        kwargs["withdrawals"] = [
            types.Withdrawal(
                index=int(w["index"], 16),
                validator_index=int(w["validatorIndex"], 16),
                address=bytes.fromhex(w["address"][2:]),
                amount=int(w["amount"], 16),
            )
            for w in obj.get("withdrawals", [])
        ]
    if fork in ("deneb", "electra"):
        kwargs["blob_gas_used"] = int(obj.get("blobGasUsed", "0x0"), 16)
        kwargs["excess_blob_gas"] = int(obj.get("excessBlobGas", "0x0"), 16)
    return cls(**kwargs)


# ----------------------------------------------------------------- client


class EngineApiClient:
    def __init__(self, url: str, jwt_secret: bytes, timeout: float = 8.0):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self._id = 0

    def rpc(self, method: str, params: List[Any]) -> Any:
        self._id += 1
        body = json.dumps({
            "jsonrpc": "2.0", "id": self._id, "method": method, "params": params,
        }).encode()
        req = urllib.request.Request(
            self.url,
            data=body,
            method="POST",
            headers={
                "Content-Type": "application/json",
                "Authorization": "Bearer " + auth.generate_token(self.jwt_secret),
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # An HTTP status from the engine is NOT "offline": 401 is an auth
            # failure the operator must see (engines.rs State::AuthFailed).
            detail = e.read().decode(errors="replace")[:200]
            if e.code == 401:
                raise EngineApiError(f"auth failed (401): {detail}", e.code) from None
            raise EngineApiError(f"engine HTTP {e.code}: {detail}", e.code) from None
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise EngineOffline(f"engine unreachable: {e}") from None
        if "error" in payload and payload["error"]:
            err = payload["error"]
            raise EngineApiError(err.get("message", "rpc error"), err.get("code"))
        return payload.get("result")

    # ------------------------------------------------------------- methods

    def exchange_capabilities(self) -> List[str]:
        return self.rpc("engine_exchangeCapabilities", [SUPPORTED_METHODS])

    def new_payload(self, payload, fork: str,
                    versioned_hashes: Optional[List[bytes]] = None,
                    parent_beacon_block_root: Optional[bytes] = None,
                    execution_requests: Optional[List[str]] = None) -> Dict[str, Any]:
        """engine_newPayloadV1-V4 by fork; returns the PayloadStatus.
        ``execution_requests``: Prague's encoded request list (V4)."""
        pj = payload_to_json(payload)
        if fork == "electra":
            return self.rpc("engine_newPayloadV4", [
                pj,
                [_d(h) for h in (versioned_hashes or [])],
                _d(parent_beacon_block_root or b"\x00" * 32),
                execution_requests or [],
            ])
        if fork == "deneb":
            return self.rpc("engine_newPayloadV3", [
                pj,
                [_d(h) for h in (versioned_hashes or [])],
                _d(parent_beacon_block_root or b"\x00" * 32),
            ])
        version = "engine_newPayloadV2" if fork == "capella" else "engine_newPayloadV1"
        return self.rpc(version, [pj])

    def forkchoice_updated(self, *, head_block_hash: bytes,
                           safe_block_hash: bytes,
                           finalized_block_hash: bytes,
                           fork: str,
                           payload_attributes: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        state = {
            "headBlockHash": _d(head_block_hash),
            "safeBlockHash": _d(safe_block_hash),
            "finalizedBlockHash": _d(finalized_block_hash),
        }
        version = {
            "bellatrix": "engine_forkchoiceUpdatedV1",
            "capella": "engine_forkchoiceUpdatedV2",
            "deneb": "engine_forkchoiceUpdatedV3",
        }.get(fork, "engine_forkchoiceUpdatedV3")
        return self.rpc(version, [state, payload_attributes])

    def get_payload(self, payload_id: str, fork: str) -> Dict[str, Any]:
        version = {
            "bellatrix": "engine_getPayloadV1",
            "capella": "engine_getPayloadV2",
            "deneb": "engine_getPayloadV3",
            "electra": "engine_getPayloadV4",
        }.get(fork, "engine_getPayloadV3")
        return self.rpc(version, [payload_id])

    def get_payload_bodies_by_hash(self, hashes) -> list:
        """engine_getPayloadBodiesByHashV1: normalized body dicts
        ({transactions: [bytes], withdrawals: [json]}) or None per hash."""
        res = self.rpc(
            "engine_getPayloadBodiesByHashV1",
            [["0x" + bytes(h).hex() for h in hashes]],
        )
        return [_body_from_json(b) for b in (res or [])]

    def get_payload_bodies_by_range(self, start: int, count: int) -> list:
        res = self.rpc("engine_getPayloadBodiesByRangeV1", [_q(start), _q(count)])
        return [_body_from_json(b) for b in (res or [])]

    def get_client_version(self) -> Optional[Dict[str, str]]:
        """engine_getClientVersionV1: the EL identifies itself (we identify
        ourselves in the request, per the spec's mutual exchange)."""
        from .. import __version__

        res = self.rpc("engine_getClientVersionV1", [{
            "code": "LH", "name": "lighthouse-tpu",
            "version": __version__, "commit": "00000000",
        }])
        return res[0] if res else None
