"""The execution layer: engine-API client + state machine + the chain-facing
facade.

Equivalent of the reference's ``beacon_node/execution_layer`` crate: JWT
HS256 auth (``engine_api/auth.rs``), the JSON-RPC engine client
(``engine_api/http.rs``), the offline→online engine state machine
(``engines.rs``), and the ``ExecutionLayer`` facade the beacon chain drives
(``lib.rs`` — notify_new_payload / notify_forkchoice_updated /
get_payload).

``ExecutionLayer`` is a drop-in for the harness's ``MockExecutionEngine``
slot on ``BeaconChain``: it implements the same two chain-facing methods
(``produce_payload``, ``notify_new_payload``) but speaks real engine-API
JSON-RPC over a socket, so a node can swap between the in-proc mock and a
real EL by construction argument alone.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..consensus import helpers as h
from ..consensus.per_block import compute_timestamp_at_slot, is_merge_transition_complete
from .auth import JwtError, generate_token, strip_prefix, validate_token
from .engine_api import (
    STATUS_ACCEPTED,
    STATUS_INVALID,
    STATUS_SYNCING,
    STATUS_VALID,
    EngineApiClient,
    EngineApiError,
    EngineOffline,
    payload_from_json,
    payload_to_json,
)
from .engines import STATE_OFFLINE, STATE_ONLINE, Engine

__all__ = [
    "Engine",
    "EngineApiClient",
    "EngineApiError",
    "EngineOffline",
    "ExecutionLayer",
    "JwtError",
    "generate_token",
    "payload_from_json",
    "payload_to_json",
    "strip_prefix",
    "validate_token",
]


class ExecutionLayer:
    """Chain-facing facade over one engine (the reference supports one EL
    post-Capella too, ``engines.rs:1-12``)."""

    def __init__(self, *, url: str, jwt_secret: bytes,
                 fee_recipient: bytes = b"\x00" * 20, timeout: float = 8.0):
        self.engine = Engine(EngineApiClient(url, jwt_secret, timeout=timeout))
        self.fee_recipient = fee_recipient
        # Optimistic bookkeeping: payload hashes the EL reported SYNCING for.
        # The chain reads this after notify_new_payload to mark the block
        # ExecutionStatus.OPTIMISTIC in fork choice (not VALID).
        self.optimistic_hashes: set = set()
        # Last finalized payload hash the chain told us about — reused as the
        # finalized/safe hash in production fcU calls so we never tell the EL
        # an unfinalized block is final.
        self.latest_finalized_hash: bytes = b"\x00" * 32
        self._last_get_payload_response: Dict = {}
        # set by the chain: called with (fork, state, attributes) whenever
        # production sends forkchoiceUpdated WITH payload attributes
        self.on_payload_attributes = None

    # -------------------------------------------------- chain integration

    def notify_new_payload(self, payload, *, versioned_hashes=None,
                           parent_beacon_block_root=None,
                           execution_requests=None, fork=None) -> bool:
        """True=VALID, False=INVALID; SYNCING/ACCEPTED are treated
        optimistically (recorded, allowed through) — the reference's
        optimistic-sync behavior (``PayloadVerificationStatus::Optimistic``).
        ``execution_requests``: the block body's ExecutionRequests container
        (electra — encoded for engine_newPayloadV4); ``fork`` overrides the
        structural guess (deneb/electra payloads are identical)."""
        from .engine_api import execution_requests_to_json

        fork = fork or _payload_fork(payload)
        encoded_requests = (
            execution_requests_to_json(execution_requests)
            if execution_requests is not None
            else None
        )
        status = self.engine.request(
            lambda api: api.new_payload(
                payload, fork,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=parent_beacon_block_root,
                execution_requests=encoded_requests,
            )
        )
        s = status.get("status")
        if s == STATUS_VALID:
            self.optimistic_hashes.discard(bytes(payload.block_hash))
            return True
        if s in (STATUS_SYNCING, STATUS_ACCEPTED):
            self.optimistic_hashes.add(bytes(payload.block_hash))
            return True
        return False

    def notify_forkchoice_updated(self, *, head_block_hash: bytes,
                                  finalized_block_hash: bytes,
                                  fork: str,
                                  payload_attributes: Optional[Dict] = None) -> Dict:
        self.latest_finalized_hash = bytes(finalized_block_hash)
        return self.engine.request(
            lambda api: api.forkchoice_updated(
                head_block_hash=head_block_hash,
                safe_block_hash=finalized_block_hash,
                finalized_block_hash=finalized_block_hash,
                fork=fork,
                payload_attributes=payload_attributes,
            )
        )

    def get_payload_bodies_by_hash(self, hashes) -> list:
        """Batched payload-body fetch for blinded-block reconstruction
        (beacon_block_streamer analog — chain/block_streamer.py)."""
        return self.engine.request(
            lambda api: api.get_payload_bodies_by_hash(hashes)
        )

    def get_payload_bodies_by_range(self, start: int, count: int) -> list:
        return self.engine.request(
            lambda api: api.get_payload_bodies_by_range(start, count)
        )

    def get_client_version(self) -> Optional[Dict]:
        """The EL's identity (graffiti_calculator + fork-readiness logs)."""
        return self.engine.request(lambda api: api.get_client_version())

    def produce_payload(self, state, types, spec,
                        suggested_fee_recipient=None):
        """The real getPayload flow: forkchoiceUpdated(head, attributes) →
        payloadId → getPayload (``lib.rs`` get_payload; the mock engine slot
        implements the same method signature in-proc).
        ``suggested_fee_recipient``: the prepared per-proposer recipient
        (prepare_beacon_proposer) — it must ride the payload ATTRIBUTES (the
        EL's block hash commits to it; rewriting after the fact would brick
        the payload)."""
        fork = type(state).fork_name
        parent_hash = bytes(state.latest_execution_payload_header.block_hash)
        if not is_merge_transition_complete(state):
            parent_hash = b"\x00" * 32
        recipient = suggested_fee_recipient or self.fee_recipient
        attributes = {
            "timestamp": hex(compute_timestamp_at_slot(state, state.slot, spec)),
            "prevRandao": "0x" + h.get_randao_mix(
                state, h.get_current_epoch(state, spec), spec
            ).hex(),
            "suggestedFeeRecipient": "0x" + bytes(recipient).hex(),
        }
        if fork in ("capella", "deneb", "electra"):
            from .engine_api import withdrawal_to_json

            attributes["withdrawals"] = [
                withdrawal_to_json(w)
                for w in h.get_expected_withdrawals(state, types, spec)
            ]
        if fork in ("deneb", "electra"):
            # EIP-4788: the PARENT beacon block's root = hash_tree_root of
            # the state's latest header (state_root already backfilled by
            # process_slots), NOT header.parent_root (the grandparent).
            attributes["parentBeaconBlockRoot"] = (
                "0x" + state.latest_block_header.hash_tree_root().hex()
            )
        if self.on_payload_attributes is not None:
            # SSE payload_attributes (reference events.rs): external
            # builders watch exactly what rides forkchoiceUpdated
            try:
                self.on_payload_attributes(fork, state, attributes)
            except Exception:
                pass  # an SSE consumer must never break production
        result = self.notify_forkchoice_updated(
            head_block_hash=parent_hash,
            # Never report an unfinalized block as final to the EL — use the
            # last finalized hash the chain gave us (zeros before finality).
            finalized_block_hash=self.latest_finalized_hash,
            fork=fork,
            payload_attributes=attributes,
        )
        payload_id = result.get("payloadId")
        if payload_id is None:
            raise EngineApiError("engine returned no payloadId")
        got = self.engine.request(lambda api: api.get_payload(payload_id, fork))
        obj = got.get("executionPayload", got)
        self._last_get_payload_response = got
        return payload_from_json(obj, types, fork)

    def produce_payload_and_requests(self, state, types, spec,
                                     suggested_fee_recipient=None):
        """(payload, ExecutionRequests) for electra block production — the
        requests come from engine_getPayloadV4's executionRequests field."""
        from .engine_api import execution_requests_from_json

        payload = self.produce_payload(
            state, types, spec, suggested_fee_recipient=suggested_fee_recipient
        )
        requests = execution_requests_from_json(
            self._last_get_payload_response.get("executionRequests"), types
        )
        return payload, requests

    # ------------------------------------------------------------- status

    def is_online(self) -> bool:
        return self.engine.state == STATE_ONLINE or self.engine.upcheck()


def _payload_fork(payload) -> str:
    if hasattr(payload, "blob_gas_used"):
        return "deneb"
    if hasattr(payload, "withdrawals"):
        return "capella"
    return "bellatrix"
