"""Engine connection state machine: offline → online, with automatic
re-upcheck.

Equivalent of the reference's ``execution_layer/src/engines.rs`` (``Engine``
+ ``State::{Online,Offline,Syncing,AuthFailed}``): every request funnels
through ``request()``, which upchecks an offline engine first and flips the
state on connection errors so callers get fast-fail behavior plus automatic
recovery when the EL comes back.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, TypeVar

from .engine_api import EngineApiClient, EngineApiError, EngineOffline

T = TypeVar("T")

STATE_ONLINE = "online"
STATE_OFFLINE = "offline"
STATE_AUTH_FAILED = "auth_failed"


class Engine:
    def __init__(self, api: EngineApiClient, upcheck_cooldown: float = 1.0):
        self.api = api
        self.state = STATE_OFFLINE
        self.capabilities: List[str] = []
        self._lock = threading.Lock()
        self._last_upcheck = 0.0
        self._cooldown = upcheck_cooldown

    def upcheck(self) -> bool:
        """engine_exchangeCapabilities as the health probe (engines.rs
        ``Engine::upcheck``)."""
        with self._lock:
            now = time.monotonic()
            if self.state == STATE_ONLINE:
                return True
            if now - self._last_upcheck < self._cooldown:
                return False
            self._last_upcheck = now
        try:
            caps = self.api.exchange_capabilities()
        except EngineOffline:
            self.state = STATE_OFFLINE
            return False
        except EngineApiError as e:
            self.state = STATE_AUTH_FAILED if "auth" in str(e).lower() else STATE_OFFLINE
            return False
        self.capabilities = caps or []
        self.state = STATE_ONLINE
        return True

    def request(self, fn: Callable[[EngineApiClient], T]) -> T:
        """Run ``fn`` against the API; offline engines are upchecked first,
        and connection failures flip the state back to offline."""
        if self.state != STATE_ONLINE and not self.upcheck():
            raise EngineOffline(f"engine {self.api.url} is {self.state}")
        from .. import fault_injection

        if fault_injection.ACTIVE:
            try:
                fault_injection.check("engine.request")
            except fault_injection.InjectedFault as e:
                # An injected fault plays a dropped connection: the engine
                # flips offline and recovers through the normal
                # upcheck/cooldown machinery.
                self.state = STATE_OFFLINE
                raise EngineOffline(f"engine {self.api.url}: {e}") from e
        try:
            return fn(self.api)
        except EngineOffline:
            self.state = STATE_OFFLINE
            raise
