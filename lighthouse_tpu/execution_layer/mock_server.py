"""Mock execution engine served over real HTTP JSON-RPC with JWT auth.

Equivalent of the reference's ``execution_layer/src/test_utils/`` MockServer:
the same fake-EL semantics as ``chain/mock_el.py`` but behind an actual
socket speaking the engine API, so the ``ExecutionLayer`` client, JWT auth,
capability exchange, and the offline→online state machine are all exercised
for real (VERDICT r1 item 8: "serve the existing MockExecutionEngine over
real HTTP to test it").
"""

from __future__ import annotations

import json
import threading
from hashlib import sha256
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Set

from . import auth
from .engine_api import SUPPORTED_METHODS


class MockEngineServer:
    def __init__(self, jwt_secret: bytes, host: str = "127.0.0.1", port: int = 0):
        self.jwt_secret = jwt_secret
        self.head_hash = b"\x00" * 32
        self.finalized_hash = b"\x00" * 32
        self.block_number = 0
        self.invalid_hashes: Set[bytes] = set()
        self.syncing_hashes: Set[bytes] = set()
        self.payloads_seen = 0
        self.fcu_seen = 0
        self._payload_id = 0
        self._pending: Dict[str, dict] = {}  # payloadId -> {head, attributes}
        # block_hash -> ExecutionPayloadBodyV1 JSON, for
        # engine_getPayloadBodiesByHash/Range (payload reconstruction).
        self._bodies: Dict[bytes, dict] = {}
        self._lock = threading.Lock()

        server = ThreadingHTTPServer((host, port), _Handler)
        server.mock = self  # type: ignore[attr-defined]
        server.daemon_threads = True
        self._httpd = server
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MockEngineServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mock-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------- methods

    def handle(self, method: str, params: list):
        if method == "engine_exchangeCapabilities":
            return SUPPORTED_METHODS
        if method.startswith("engine_newPayload"):
            payload = params[0]
            self.payloads_seen += 1
            block_hash = bytes.fromhex(payload["blockHash"][2:])
            with self._lock:
                self._bodies[block_hash] = {
                    "blockNumber": payload.get("blockNumber", "0x0"),
                    "transactions": list(payload.get("transactions", [])),
                    "withdrawals": payload.get("withdrawals"),
                }
            if block_hash in self.invalid_hashes:
                return {"status": "INVALID", "latestValidHash": None,
                        "validationError": "marked invalid by test"}
            if block_hash in self.syncing_hashes:
                return {"status": "SYNCING", "latestValidHash": None}
            return {"status": "VALID",
                    "latestValidHash": payload["blockHash"]}
        if method.startswith("engine_forkchoiceUpdated"):
            state, attributes = params[0], params[1] if len(params) > 1 else None
            self.fcu_seen += 1
            with self._lock:
                self.head_hash = bytes.fromhex(state["headBlockHash"][2:])
                self.finalized_hash = bytes.fromhex(state["finalizedBlockHash"][2:])
                result = {
                    "payloadStatus": {"status": "VALID",
                                      "latestValidHash": state["headBlockHash"]},
                    "payloadId": None,
                }
                if attributes:
                    self._payload_id += 1
                    pid = "0x" + self._payload_id.to_bytes(8, "big").hex()
                    self._pending[pid] = {
                        "head": self.head_hash, "attributes": attributes,
                    }
                    result["payloadId"] = pid
            return result
        if method == "engine_getClientVersionV1":
            return [{"code": "MK", "name": "mock-engine",
                     "version": "0.1.0", "commit": "deadbeef"}]
        if method == "engine_getPayloadBodiesByHashV1":
            with self._lock:
                return [
                    self._body_json(self._bodies.get(bytes.fromhex(h[2:])))
                    for h in params[0]
                ]
        if method == "engine_getPayloadBodiesByRangeV1":
            start, count = int(params[0], 16), int(params[1], 16)
            with self._lock:
                by_number = {
                    int(b["blockNumber"], 16): b for b in self._bodies.values()
                }
                return [
                    self._body_json(by_number.get(n))
                    for n in range(start, start + count)
                ]
        if method.startswith("engine_getPayload"):
            pid = params[0]
            with self._lock:
                pending = self._pending.pop(pid, None)
            if pending is None:
                raise _RpcError(-38001, "Unknown payload")
            payload = self._build_payload(pending["head"], pending["attributes"])
            if method.endswith("V1"):
                return payload
            out = {"executionPayload": payload, "blockValue": "0x0"}
            if method.endswith("V3") or method.endswith("V4"):
                out["blobsBundle"] = {"commitments": [], "proofs": [], "blobs": []}
                out["shouldOverrideBuilder"] = False
            if method.endswith("V4"):
                out["executionRequests"] = []
            return out
        raise _RpcError(-32601, f"method not found: {method}")

    @staticmethod
    def _body_json(body: Optional[dict]) -> Optional[dict]:
        if body is None:
            return None
        return {"transactions": body["transactions"],
                "withdrawals": body["withdrawals"]}

    def _build_payload(self, head: bytes, attrs: dict) -> dict:
        with self._lock:
            self.block_number += 1
            number = self.block_number
        timestamp = attrs["timestamp"]
        block_hash = sha256(
            b"mock-engine" + head + bytes.fromhex(timestamp[2:].zfill(16))
            + number.to_bytes(8, "big")
        ).digest()
        out = {
            "parentHash": "0x" + head.hex(),
            "feeRecipient": attrs.get("suggestedFeeRecipient", "0x" + "00" * 20),
            "stateRoot": "0x" + "00" * 32,
            "receiptsRoot": "0x" + "00" * 32,
            "logsBloom": "0x" + "00" * 256,
            "prevRandao": attrs["prevRandao"],
            "blockNumber": hex(number),
            "gasLimit": hex(30_000_000),
            "gasUsed": "0x0",
            "timestamp": timestamp,
            "extraData": "0x",
            "baseFeePerGas": "0x7",
            "blockHash": "0x" + block_hash.hex(),
            "transactions": [],
        }
        if "withdrawals" in attrs:
            out["withdrawals"] = attrs["withdrawals"]
        if "parentBeaconBlockRoot" in attrs:
            out["blobGasUsed"] = "0x0"
            out["excessBlobGas"] = "0x0"
        with self._lock:
            self._bodies[block_hash] = {
                "blockNumber": out["blockNumber"],
                "transactions": list(out["transactions"]),
                "withdrawals": out.get("withdrawals"),
            }
        return out


class _RpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        mock: MockEngineServer = self.server.mock  # type: ignore[attr-defined]
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length)
        # JWT gate (auth.rs semantics): missing/invalid token -> 401.
        header = self.headers.get("Authorization", "")
        token = header[len("Bearer "):] if header.startswith("Bearer ") else ""
        try:
            auth.validate_token(token, mock.jwt_secret)
        except auth.JwtError as e:
            self._respond(401, {"error": f"unauthorized: {e}"})
            return
        try:
            req = json.loads(raw)
            result = mock.handle(req.get("method", ""), req.get("params", []))
            self._respond(200, {"jsonrpc": "2.0", "id": req.get("id"), "result": result})
        except _RpcError as e:
            self._respond(200, {
                "jsonrpc": "2.0", "id": None,
                "error": {"code": e.code, "message": e.message},
            })
        except Exception as e:
            self._respond(200, {
                "jsonrpc": "2.0", "id": None,
                "error": {"code": -32603, "message": f"{type(e).__name__}: {e}"},
            })

    def _respond(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
