"""External block-builder (MEV relay) client + in-process mock relay.

Equivalent of the reference's ``beacon_node/builder_client`` (228 LoC HTTP
client: register_validators / get_header / submit_blinded_block against the
builder-specs API) plus the ``MockBuilder`` test relay the reference keeps in
``execution_layer/test_utils``.

The flow (reference ``http_api/src/produce_block.rs`` + builder bid
validation in ``execution_layer``):

1. VC registers fee recipients (``register_validators``).
2. At proposal time the BN asks ``get_header(slot, parent_hash, pubkey)``;
   the relay answers with a ``SignedBuilderBid`` carrying a payload HEADER
   and a value.
3. The BN builds a BLINDED block around the header; the proposer signs it.
4. ``submit_blinded_block`` reveals the full payload; because
   ``header.hash_tree_root() == payload.hash_tree_root()`` the proposer's
   signature is valid for the unblinded block, which the BN imports and
   publishes.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..consensus import helpers as h
from ..consensus.per_block import execution_payload_to_header
from ..crypto.bls import api as bls
from ..http_api.serde import container_from_json, to_json
from ..types.spec import DOMAIN_APPLICATION_BUILDER


class BuilderError(Exception):
    pass


def builder_signing_root(message_root: bytes, spec) -> bytes:
    """Builder-API objects sign over the APPLICATION_BUILDER domain with the
    genesis fork version and an empty genesis-validators-root (builder-specs;
    reference ``signed_validator_registration`` verification)."""
    domain = h.compute_domain(
        DOMAIN_APPLICATION_BUILDER, spec.genesis_fork_version, None
    )
    return h.compute_signing_root(message_root, domain)


class BuilderHttpClient:
    """The BN-side relay client (reference ``builder_client/src/lib.rs``)."""

    def __init__(self, base_url: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, path: str, body=None):
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            raise BuilderError(f"builder {e.code}: {e.read().decode(errors='replace')}") from None
        except OSError as e:
            raise BuilderError(f"builder unreachable: {e}") from None

    def register_validators(self, signed_registrations) -> None:
        self._request(
            "POST", "/eth/v1/builder/validators",
            [to_json(r) for r in signed_registrations],
        )

    def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes, types):
        resp = self._request(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{bytes(parent_hash).hex()}"
            f"/0x{bytes(pubkey).hex()}",
        )
        if resp is None:
            return None, None
        # Relay output is untrusted: any malformed answer is a BuilderError
        # so callers' local-production fallback engages.
        try:
            fork = resp["version"]
            bid = container_from_json(types.signed_builder_bid[fork], resp["data"])
        except (KeyError, TypeError, ValueError) as e:
            raise BuilderError(f"malformed builder bid: {e}") from e
        return fork, bid

    def submit_blinded_block(self, signed_blinded_block, types):
        fork = type(signed_blinded_block.message).fork_name
        resp = self._request(
            "POST", "/eth/v1/builder/blinded_blocks",
            to_json(signed_blinded_block),
        )
        payload_cls = types.execution_payload[fork]
        try:
            return container_from_json(payload_cls, resp["data"])
        except (KeyError, TypeError, ValueError) as e:
            raise BuilderError(f"malformed revealed payload: {e}") from e


class MockRelay:
    """In-process relay: builds payloads exactly like the mock EL (so bids
    validate against the chain's state), signs bids with its own key, and
    reveals payloads on submission (reference ``MockBuilder``)."""

    def __init__(self, chain, bid_value: int = 1_000_000_000):
        self.chain = chain
        self.bid_value = bid_value
        self.key = bls.SecretKey(0x42424242)
        self.pubkey = self.key.public_key().to_bytes()
        self.registrations: Dict[bytes, object] = {}  # pubkey -> registration
        self._payloads: Dict[bytes, object] = {}  # header root -> payload
        self._server: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------ behavior

    def build_bid(self, slot: int, parent_hash: bytes):
        chain = self.chain
        types, spec = chain.types, chain.spec
        state, _ = chain.state_at_slot(slot)
        if bytes(state.latest_execution_payload_header.block_hash) != bytes(parent_hash):
            raise BuilderError("unknown parent hash")
        fork = type(state).fork_name
        requests = None
        if fork == "electra" and hasattr(
            chain.execution_engine, "produce_payload_and_requests"
        ):
            payload, requests = chain.execution_engine.produce_payload_and_requests(
                state, types, spec
            )
        else:
            payload = chain.execution_engine.produce_payload(state, types, spec)
        header = execution_payload_to_header(payload, types, fork)
        self._payloads[header.hash_tree_root()] = payload
        bid_kwargs = dict(header=header, value=self.bid_value, pubkey=self.pubkey)
        if "blob_kzg_commitments" in types.builder_bid[fork].fields:
            bid_kwargs["blob_kzg_commitments"] = []
        if "execution_requests" in types.builder_bid[fork].fields:
            bid_kwargs["execution_requests"] = (
                requests if requests is not None
                else types.ExecutionRequests(
                    deposits=[], withdrawals=[], consolidations=[])
            )
        bid = types.builder_bid[fork](**bid_kwargs)
        sig = self.key.sign(builder_signing_root(bid.hash_tree_root(), spec))
        return fork, types.signed_builder_bid[fork](
            message=bid, signature=sig.to_bytes()
        )

    def reveal_payload(self, signed_blinded_block):
        header = signed_blinded_block.message.body.execution_payload_header
        payload = self._payloads.get(header.hash_tree_root())
        if payload is None:
            raise BuilderError("no payload for that header (not our bid)")
        return payload

    # -------------------------------------------------------------- server

    def start(self) -> "MockRelay":
        relay = self
        chain = self.chain

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, obj=None):
                body = b"" if obj is None else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                # eth/v1/builder/header/{slot}/{parent_hash}/{pubkey}
                if len(parts) == 7 and parts[:4] == ["eth", "v1", "builder", "header"]:
                    try:
                        fork, bid = relay.build_bid(
                            int(parts[4]), bytes.fromhex(parts[5][2:])
                        )
                    except Exception as e:
                        self._reply(400, {"code": 400, "message": str(e)})
                        return
                    self._reply(200, {"version": fork, "data": to_json(bid)})
                    return
                if parts[-1] == "status":
                    self._reply(200)
                    return
                self._reply(404, {"code": 404, "message": "unknown route"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"null")
                if self.path.endswith("/eth/v1/builder/validators"):
                    for reg in body or []:
                        signed = container_from_json(
                            chain.types.SignedValidatorRegistrationV1, reg
                        )
                        relay.registrations[
                            bytes(signed.message.pubkey)
                        ] = signed
                    self._reply(200)
                    return
                if self.path.endswith("/eth/v1/builder/blinded_blocks"):
                    fork = None
                    # newest fork first: older bodies are field-subsets and
                    # could otherwise swallow a newer block's JSON
                    for f, cls in reversed(list(chain.types.signed_blinded_block.items())):
                        try:
                            signed = container_from_json(cls, body)
                            fork = f
                            break
                        except Exception:
                            continue
                    if fork is None:
                        self._reply(400, {"code": 400, "message": "undecodable block"})
                        return
                    try:
                        payload = relay.reveal_payload(signed)
                    except BuilderError as e:
                        self._reply(400, {"code": 400, "message": str(e)})
                        return
                    self._reply(200, {"version": fork, "data": to_json(payload)})
                    return
                self._reply(404, {"code": 404, "message": "unknown route"})

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
