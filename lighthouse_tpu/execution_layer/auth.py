"""Engine-API JWT (HS256) authentication.

Equivalent of the reference's ``execution_layer/src/engine_api/auth.rs:71-79``
(``Auth::generate_token`` — HS256 over an ``iat`` claim, secret from the
jwt-secret file both sides share).  Pure stdlib: hmac + base64url.
"""

from __future__ import annotations

import base64
import hmac
import json
import time
from hashlib import sha256
from typing import Optional

JWT_SECRET_LENGTH = 32
# Engine API spec: tokens older than this are rejected.
MAX_IAT_DRIFT_SECONDS = 60


class JwtError(Exception):
    pass


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _b64url_decode(data: bytes) -> bytes:
    pad = b"=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def strip_prefix(secret_hex: str) -> bytes:
    s = secret_hex.strip()
    if s.startswith("0x"):
        s = s[2:]
    secret = bytes.fromhex(s)
    if len(secret) != JWT_SECRET_LENGTH:
        raise JwtError(f"jwt secret must be {JWT_SECRET_LENGTH} bytes, got {len(secret)}")
    return secret


def generate_token(secret: bytes, iat: Optional[int] = None) -> str:
    """HS256 JWT with an ``iat`` claim (auth.rs generate_token)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = _b64url(json.dumps({"iat": int(time.time()) if iat is None else iat}).encode())
    signing_input = header + b"." + claims
    sig = hmac.new(secret, signing_input, sha256).digest()
    return (signing_input + b"." + _b64url(sig)).decode()


def validate_token(token: str, secret: bytes, now: Optional[int] = None) -> None:
    """Raise JwtError unless ``token`` is a valid, fresh HS256 JWT."""
    parts = token.encode().split(b".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    signing_input = parts[0] + b"." + parts[1]
    expect = hmac.new(secret, signing_input, sha256).digest()
    try:
        sig = _b64url_decode(parts[2])
    except Exception:
        raise JwtError("bad base64 in signature")
    if not hmac.compare_digest(expect, sig):
        raise JwtError("bad signature")
    try:
        claims = json.loads(_b64url_decode(parts[1]))
        if not isinstance(claims, dict):
            raise JwtError("claims not an object")
        iat = int(claims.get("iat", 0))
    except JwtError:
        raise
    except Exception:
        raise JwtError("bad claims")
    now = int(time.time()) if now is None else now
    if abs(now - iat) > MAX_IAT_DRIFT_SECONDS:
        raise JwtError(f"stale iat {iat} (now {now})")
